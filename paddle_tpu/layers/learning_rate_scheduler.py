"""In-graph learning-rate schedules.

Reference: ``python/paddle/fluid/layers/learning_rate_scheduler.py`` — each
schedule is emitted as ops over a persistable global step counter, so the
LR update runs on-device inside the same jitted block as the optimizer.
"""
from __future__ import annotations

import math

from ..core.program import OP_ROLE_ATTR, OpRole, default_main_program
from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper
from . import tensor

LR_COUNTER = "@LR_DECAY_COUNTER@"


def _step_counter(name, begin=0, step=1.0):
    """Create-or-return a persistable auto-incrementing counter var
    (one increment prepended per run).  Distinct names give independent
    counters — the LR schedulers share LR_COUNTER; the public
    autoincreased_step_counter defaults to its own @STEP_COUNTER@
    (reference layers/nn.py:~autoincreased_step_counter)."""
    helper = LayerHelper("global_step_counter")
    program = default_main_program()
    gb = program.global_block
    if gb.has_var(name):
        return gb.vars[name]
    counter = helper.create_global_variable(
        shape=(), dtype="float32", persistable=True, name=name)
    # the prepended increment runs before any read, so start at
    # begin-step to make the first run observe `begin`
    helper.set_variable_initializer(
        counter, ConstantInitializer(float(begin) - float(step)))
    with program.op_role_guard(OpRole.LRSched):
        gb.prepend_op("increment", {"X": [name]}, {"Out": [name]},
                      {"step": float(step), OP_ROLE_ATTR: OpRole.LRSched})
    return counter


def _decay_step_counter(begin=0):
    return _step_counter(LR_COUNTER, begin=begin, step=1.0)


def _sched_op(helper, type, ins, attrs=None, shape=()):
    out = helper.create_variable_for_type_inference("float32", shape=shape)
    helper.append_op(type, ins, {"Out": [out]}, {
        **(attrs or {}), OP_ROLE_ATTR: OpRole.LRSched})
    return out


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    helper = LayerHelper("exponential_decay")
    step = _decay_step_counter()
    div = _sched_op(helper, "scale", {"X": [step]}, {"scale": 1.0 / decay_steps})
    if staircase:
        div = _sched_op(helper, "floor", {"X": [div]})
    # decay_rate ** div  ==  exp(div * log(decay_rate))
    scaled = _sched_op(helper, "scale", {"X": [div]}, {"scale": math.log(decay_rate)})
    factor = _sched_op(helper, "exp", {"X": [scaled]})
    return _sched_op(helper, "scale", {"X": [factor]}, {"scale": float(learning_rate)})


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    helper = LayerHelper("natural_exp_decay")
    step = _decay_step_counter()
    div = _sched_op(helper, "scale", {"X": [step]}, {"scale": 1.0 / decay_steps})
    if staircase:
        div = _sched_op(helper, "floor", {"X": [div]})
    scaled = _sched_op(helper, "scale", {"X": [div]}, {"scale": -decay_rate})
    factor = _sched_op(helper, "exp", {"X": [scaled]})
    return _sched_op(helper, "scale", {"X": [factor]}, {"scale": float(learning_rate)})


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    helper = LayerHelper("inverse_time_decay")
    step = _decay_step_counter()
    div = _sched_op(helper, "scale", {"X": [step]}, {"scale": 1.0 / decay_steps})
    if staircase:
        div = _sched_op(helper, "floor", {"X": [div]})
    denom = _sched_op(helper, "scale", {"X": [div]},
                      {"scale": decay_rate, "bias": 1.0, "bias_after_scale": True})
    inv = _sched_op(helper, "reciprocal", {"X": [denom]})
    return _sched_op(helper, "scale", {"X": [inv]}, {"scale": float(learning_rate)})


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    helper = LayerHelper("polynomial_decay")
    step = _decay_step_counter()
    capped = _sched_op(helper, "clip", {"X": [step]},
                       {"min": 0.0, "max": float(decay_steps)})
    frac = _sched_op(helper, "scale", {"X": [capped]}, {"scale": 1.0 / decay_steps})
    one_minus = _sched_op(helper, "scale", {"X": [frac]},
                          {"scale": -1.0, "bias": 1.0})
    powed = _sched_op(helper, "pow", {"X": [one_minus]}, {"factor": power})
    return _sched_op(
        helper, "scale", {"X": [powed]},
        {"scale": float(learning_rate - end_learning_rate),
         "bias": float(end_learning_rate)})


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (transformer schedule; reference learning_rate_scheduler.py:44)."""
    helper = LayerHelper("noam_decay")
    step = _decay_step_counter(begin=1)
    a = _sched_op(helper, "pow", {"X": [step]}, {"factor": -0.5})
    b = _sched_op(helper, "scale", {"X": [step]},
                  {"scale": warmup_steps ** -1.5})
    m = _sched_op(helper, "elementwise_min", {"X": [a], "Y": [b]})
    return _sched_op(helper, "scale", {"X": [m]}, {"scale": d_model ** -0.5})


def piecewise_decay(boundaries, values):
    """Step-function LR via nested where ops."""
    assert len(values) == len(boundaries) + 1
    helper = LayerHelper("piecewise_decay")
    step = _decay_step_counter()
    lr = tensor.fill_constant((), "float32", values[-1])
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        boundary = tensor.fill_constant((), "float32", float(b))
        cond = _sched_op(helper, "less_than", {"X": [step], "Y": [boundary]})
        val = tensor.fill_constant((), "float32", float(v))
        lr = _sched_op(helper, "where", {"Condition": [cond], "X": [val], "Y": [lr]})
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    helper = LayerHelper("cosine_decay")
    step = _decay_step_counter()
    epoch = _sched_op(helper, "scale", {"X": [step]}, {"scale": 1.0 / step_each_epoch})
    epoch = _sched_op(helper, "floor", {"X": [epoch]})
    inner = _sched_op(helper, "scale", {"X": [epoch]}, {"scale": math.pi / epochs})
    cosv = _sched_op(helper, "cos", {"X": [inner]})
    return _sched_op(
        helper, "scale", {"X": [cosv]},
        {"scale": learning_rate * 0.5, "bias": learning_rate * 0.5})
