"""Layer DSL: functions that append ops to the current program block.

Reference: ``python/paddle/fluid/layers/nn.py`` (~140 layer functions, each
creating vars via LayerHelper and appending OpDescs).  Signatures follow the
reference so user programs port over; the ops they emit lower to XLA.

Sequence convention (the LoDTensor redesign, SURVEY.md §5): a variable-length
sequence batch is a *padded* dense tensor ``[B, T, ...]`` plus an ``int32``
length vector ``[B]`` held in a companion var named ``<name>@LEN`` (created
by ``layers.data(..., lod_level=1)``).  Sequence ops take the lengths as an
explicit ``SeqLen`` input and mask internally — static shapes for XLA, same
semantics as the reference's nested-LoD offsets for level-1 sequences.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.program import Variable
from ..initializer import ConstantInitializer, NormalInitializer
from ..layer_helper import LayerHelper


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def seq_len_var(x: Variable) -> Optional[Variable]:
    """Companion length var of a padded sequence batch, if declared."""
    b = x.block
    while b is not None:
        if x.name in b.seq_len_map:
            return b.var_or_none(b.seq_len_map[x.name])
        b = b.parent_block
    return x.block.var_or_none(x.name + "@LEN")


def seq_len2_var(x: Variable) -> Optional[Variable]:
    """Inner (level-2) [B, S] lengths companion of a padded-nested batch."""
    b = x.block
    while b is not None:
        if x.name in getattr(b, "seq_len2_map", {}):
            return b.var_or_none(b.seq_len2_map[x.name])
        b = b.parent_block
    return x.block.var_or_none(x.name + "@LEN2")


# ---------------------------------------------------------------------------
# core layers
# ---------------------------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected (reference nn.py fc): mul + (sum) + bias + act."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        in_features = int(np.prod(inp.shape[num_flatten_dims:]))
        w = helper.create_parameter(pattr, [in_features, size], dtype)
        out_shape = tuple(inp.shape[:num_flatten_dims]) + (size,)
        tmp = helper.create_variable_for_type_inference(dtype, shape=out_shape)
        helper.append_op(
            "mul", {"X": [inp], "Y": [w]}, {"Out": [tmp]},
            {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype, shape=mul_results[0].shape)
        helper.append_op("sum", {"X": mul_results}, {"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    out = helper.append_activation(pre_act)
    first = inputs[0]
    if num_flatten_dims >= 2:
        _propagate_lod(out, first)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    """Embedding lookup (reference nn.py:272).  ``is_sparse`` makes the
    gradient a SelectedRows row-slice pair (no dense [V, D] grad is ever
    materialised); ``is_distributed`` marks the table for the pserver
    transpiler's sharded-table path."""
    if is_distributed and not is_sparse:
        raise ValueError(
            "embedding(is_distributed=True) requires is_sparse=True: the "
            "sharded-table gradient travels as a SelectedRows row slice "
            "(reference nn.py:272 remote-prefetch path)")
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(param_attr, size, dtype)
    out_shape = tuple(input.shape[:-1] if input.shape[-1] == 1 else input.shape) + (size[1],)
    out = helper.create_variable_for_type_inference(dtype, shape=out_shape)
    # negative padding_idx counts from the end (reference nn.py:292:
    # kNoPadding if None else idx if idx >= 0 else size[0] + idx)
    if padding_idx is None:
        padding_idx = -1  # kNoPadding sentinel
    elif padding_idx < 0:
        padding_idx = size[0] + padding_idx
    helper.append_op(
        "lookup_table", {"W": [w], "Ids": [input]}, {"Out": [out]},
        {"is_sparse": is_sparse, "is_distributed": is_distributed,
         "padding_idx": padding_idx},
    )
    _propagate_lod(out, input)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_layout="NCHW"):
    helper = LayerHelper("conv2d", bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    groups = groups or 1
    fs, st, pd, dl = _pair(filter_size), _pair(stride), _pair(padding), _pair(dilation)
    nhwc = data_layout == "NHWC"
    C = input.shape[-1] if nhwc else input.shape[1]
    # Filter params stay OIHW regardless of activation layout (checkpoint
    # compatibility); the lowering retargets the conv spec.
    w_shape = [num_filters, C // groups, fs[0], fs[1]]
    std = (2.0 / (fs[0] * fs[1] * C)) ** 0.5
    w = helper.create_parameter(
        param_attr, w_shape, dtype,
        default_initializer=NormalInitializer(0.0, std),
    )
    hin, win = (input.shape[1:3] if nhwc else input.shape[2:4])
    H = (hin + 2 * pd[0] - (dl[0] * (fs[0] - 1) + 1)) // st[0] + 1
    W = (win + 2 * pd[1] - (dl[1] * (fs[1] - 1) + 1)) // st[1] + 1
    out_shape = ((input.shape[0], H, W, num_filters) if nhwc
                 else (input.shape[0], num_filters, H, W))
    pre_bias = helper.create_variable_for_type_inference(dtype, shape=out_shape)
    helper.append_op(
        "conv2d", {"Input": [input], "Filter": [w]}, {"Output": [pre_bias]},
        {"strides": st, "paddings": pd, "dilations": dl, "groups": groups,
         "data_layout": data_layout},
    )
    if nhwc:
        pre_act = helper.append_bias_op(pre_bias, dim_start=3, dim_end=4)
    else:
        pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, param_attr=None, bias_attr=None, act=None,
                     name=None):
    helper = LayerHelper("conv2d_transpose", bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    fs, st, pd, dl = _pair(filter_size), _pair(stride), _pair(padding), _pair(dilation)
    C = input.shape[1]
    w = helper.create_parameter(param_attr, [C, num_filters, fs[0], fs[1]], dtype)
    H = (input.shape[2] - 1) * st[0] - 2 * pd[0] + dl[0] * (fs[0] - 1) + 1
    W = (input.shape[3] - 1) * st[1] - 2 * pd[1] + dl[1] * (fs[1] - 1) + 1
    out_shape = (input.shape[0], num_filters, H, W)
    pre_bias = helper.create_variable_for_type_inference(dtype, shape=out_shape)
    helper.append_op(
        "conv2d_transpose", {"Input": [input], "Filter": [w]},
        {"Output": [pre_bias]},
        {"strides": st, "paddings": pd, "dilations": dl},
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False,
           exclusive=True, name=None, data_layout="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    ks, st, pd = _pair(pool_size), _pair(pool_stride), _pair(pool_padding)
    nhwc = data_layout == "NHWC"
    hin, win = (input.shape[1:3] if nhwc else input.shape[2:4])
    if global_pooling:
        H = W = 1
    else:
        H = (hin + 2 * pd[0] - ks[0]) // st[0] + 1
        W = (win + 2 * pd[1] - ks[1]) // st[1] + 1
    ch = input.shape[-1] if nhwc else input.shape[1]
    shape = (input.shape[0], H, W, ch) if nhwc else (input.shape[0], ch, H, W)
    out = helper.create_variable_for_type_inference(input.dtype, shape=shape)
    helper.append_op(
        "pool2d", {"X": [input]}, {"Out": [out]},
        {"pooling_type": pool_type, "ksize": ks, "strides": st,
         "paddings": pd, "global_pooling": global_pooling,
         "exclusive": exclusive, "data_layout": data_layout},
    )
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               in_place=False):
    helper = LayerHelper("batch_norm", act=act, name=name)
    dtype = input.dtype
    C = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        param_attr, [C], "float32",
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, [C], "float32", is_bias=True)
    mean = helper.create_or_get_global_variable(
        [C], "float32", moving_mean_name or helper.name + ".mean",
        persistable=True)
    variance = helper.create_or_get_global_variable(
        [C], "float32", moving_variance_name or helper.name + ".variance",
        persistable=True)
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))
    saved_mean = helper.create_variable_for_type_inference("float32", shape=(C,))
    saved_var = helper.create_variable_for_type_inference("float32", shape=(C,))
    out = helper.create_variable_for_type_inference(dtype, shape=input.shape)
    helper.append_op(
        "batch_norm",
        {"X": [input], "Scale": [scale], "Bias": [bias],
         "Mean": [mean], "Variance": [variance]},
        {"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
         "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout},
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", act=act, name=name)
    dtype = input.dtype
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            param_attr, norm_shape, "float32",
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, norm_shape, "float32", is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype, shape=input.shape)
    mean = helper.create_variable_for_type_inference(
        "float32", shape=input.shape[:begin_norm_axis])
    var = helper.create_variable_for_type_inference(
        "float32", shape=input.shape[:begin_norm_axis])
    helper.append_op(
        "layer_norm", inputs, {"Y": [out], "Mean": [mean], "Variance": [var]},
        {"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    mask = helper.create_variable_for_type_inference(
        x.dtype, shape=x.shape, stop_gradient=True)
    helper.append_op(
        "dropout", {"X": [x]}, {"Out": [out], "Mask": [mask]},
        {"dropout_prob": dropout_prob, "is_test": is_test,
         "seed": seed or 0, "dropout_implementation": dropout_implementation},
    )
    return out


# ---------------------------------------------------------------------------
# losses / classification
# ---------------------------------------------------------------------------

def softmax(input, axis=-1, use_cudnn=False, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, shape=input.shape)
    helper.append_op("softmax", {"X": [input]}, {"Out": [out]}, {"axis": axis})
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out_shape = tuple(input.shape[:-1]) + (1,)
    out = helper.create_variable_for_type_inference(input.dtype, shape=out_shape)
    helper.append_op(
        "cross_entropy", {"X": [input], "Label": [label]}, {"Y": [out]},
        {"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    sm = helper.create_variable_for_type_inference(logits.dtype, shape=logits.shape)
    loss_shape = tuple(logits.shape[:-1]) + (1,)
    loss = helper.create_variable_for_type_inference(logits.dtype, shape=loss_shape)
    helper.append_op(
        "softmax_with_cross_entropy",
        {"Logits": [logits], "Label": [label]},
        {"Softmax": [sm], "Loss": [loss]},
        {"soft_label": soft_label, "ignore_index": ignore_index},
    )
    if return_softmax:
        return loss, sm
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype, shape=input.shape)
    helper.append_op("square_error_cost", {"X": [input], "Y": [label]}, {"Out": [out]})
    return out


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        {"X": [x], "Label": [label]}, {"Out": [out]})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference layers/metric_op.py accuracy)."""
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(
        input.dtype, shape=tuple(input.shape[:-1]) + (k,))
    topk_idx = helper.create_variable_for_type_inference(
        "int64", shape=tuple(input.shape[:-1]) + (k,), stop_gradient=True)
    helper.append_op("top_k", {"X": [input]},
                     {"Out": [topk_out], "Indices": [topk_idx]}, {"k": k})
    acc = helper.create_variable_for_type_inference("float32", shape=(), stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference(
        "int32", shape=(), stop_gradient=True)
    total = total or helper.create_variable_for_type_inference(
        "int32", shape=(), stop_gradient=True)
    helper.append_op(
        "accuracy",
        {"Out": [topk_out], "Indices": [topk_idx], "Label": [label]},
        {"Accuracy": [acc], "Correct": [correct], "Total": [total]},
    )
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=0):
    """Streaming in-graph ROC-AUC (reference layers/metric_op.py auc /
    auc_op.cc).  Threshold-bucket histograms live as persistable state
    vars updated every step; returns (auc_value, [stat_pos, stat_neg])."""
    from ..initializer import ConstantInitializer

    if curve != "ROC":
        raise NotImplementedError(f"auc curve={curve!r}: only ROC is "
                                  f"implemented (PR-AUC is not)")
    if topk != 1 or slide_steps not in (0, 1):
        raise NotImplementedError(
            "auc topk>1 / sliding-window accumulation are not implemented; "
            "use the default all-time accumulation")
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        shape=(num_thresholds + 1,), dtype="int64", persistable=True,
        name=helper.name + ".stat_pos")
    stat_neg = helper.create_global_variable(
        shape=(num_thresholds + 1,), dtype="int64", persistable=True,
        name=helper.name + ".stat_neg")
    helper.set_variable_initializer(stat_pos, ConstantInitializer(0.0))
    helper.set_variable_initializer(stat_neg, ConstantInitializer(0.0))
    auc_out = helper.create_variable_for_type_inference(
        "float32", shape=(), stop_gradient=True)
    helper.append_op(
        "auc",
        {"Predict": [input], "Label": [label],
         "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        {"AUC": [auc_out], "StatPosOut": [stat_pos],
         "StatNegOut": [stat_neg]},
        {"curve": curve, "num_thresholds": num_thresholds})
    return auc_out, [stat_pos, stat_neg]


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    """Batched Levenshtein distance over padded id sequences (reference
    nn.py edit_distance / edit_distance_op.cc); returns (dist [B,1],
    seq_num)."""
    helper = LayerHelper("edit_distance", name=name)
    dist = helper.create_variable_for_type_inference(
        "float32", shape=(input.shape[0], 1), stop_gradient=True)
    seq_num = helper.create_variable_for_type_inference(
        "int64", shape=(), stop_gradient=True)
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLen"] = [input_length]
    if label_length is not None:
        ins["RefsLen"] = [label_length]
    helper.append_op("edit_distance", ins,
                     {"Out": [dist], "SequenceNum": [seq_num]},
                     {"normalized": normalized})
    return dist, seq_num


def precision_recall(max_probs, indices, labels, class_number, name=None):
    """Multi-class precision/recall with running per-class stats
    (precision_recall_op.cc); returns (batch_metrics[6], accum_metrics[6])
    = macro/micro precision, recall, F1."""
    from ..initializer import ConstantInitializer

    helper = LayerHelper("precision_recall", name=name)
    states = helper.create_global_variable(
        shape=(class_number, 4), dtype="float32", persistable=True,
        name=helper.name + ".states")
    helper.set_variable_initializer(states, ConstantInitializer(0.0))
    batch_m = helper.create_variable_for_type_inference(
        "float32", shape=(6,), stop_gradient=True)
    accum_m = helper.create_variable_for_type_inference(
        "float32", shape=(6,), stop_gradient=True)
    helper.append_op(
        "precision_recall",
        {"MaxProbs": [max_probs], "Indices": [indices], "Labels": [labels],
         "StatesInfo": [states]},
        {"BatchMetrics": [batch_m], "AccumMetrics": [accum_m],
         "AccumStatesInfo": [states]},
        {"class_number": class_number})
    return batch_m, accum_m


# ---------------------------------------------------------------------------
# tensor manipulation
# ---------------------------------------------------------------------------

def _unary(op_type, x, helper_name=None, attrs=None, out_shape=None, out_dtype=None):
    helper = LayerHelper(helper_name or op_type)
    out = helper.create_variable_for_type_inference(
        out_dtype or x.dtype, shape=out_shape if out_shape is not None else x.shape)
    helper.append_op(op_type, {"X": [x]}, {"Out": [out]}, attrs or {})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    new_shape = list(shape)
    known = [s for s in new_shape if s > 0]
    resolved = []
    for i, s in enumerate(new_shape):
        resolved.append(x.shape[i] if s == 0 else s)
    if -1 in resolved:
        total = int(np.prod([s for s in x.shape if s != -1]))
        # keep -1 symbolic when the input batch is symbolic
        pass
    helper = LayerHelper("reshape", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=tuple(resolved))
    helper.append_op("reshape", {"X": [x]}, {"Out": [out]}, {"shape": list(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    out_shape = tuple(x.shape[p] for p in perm)
    return _unary("transpose", x, attrs={"axis": list(perm)}, out_shape=out_shape)


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    shp = list(input[0].shape)
    shp[axis] = sum(int(v.shape[axis]) for v in input)
    out = helper.create_variable_for_type_inference(input[0].dtype, shape=tuple(shp))
    helper.append_op("concat", {"X": input}, {"Out": [out]}, {"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else len(input.shape) + dim
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
        sizes = [input.shape[dim] // num] * num
    else:
        sections = list(num_or_sections)
        num = len(sections)
        sizes = sections
    outs = []
    for s in sizes:
        shp = list(input.shape)
        shp[dim] = s
        outs.append(helper.create_variable_for_type_inference(input.dtype, shape=tuple(shp)))
    helper.append_op(
        "split", {"X": [input]}, {"Out": outs},
        {"axis": dim, "sections": sections, "num": 0 if sections else num},
    )
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    xs = x if isinstance(x, (list, tuple)) else [x]
    shp = list(xs[0].shape)
    shp.insert(axis if axis >= 0 else len(shp) + axis + 1, len(xs))
    out = helper.create_variable_for_type_inference(xs[0].dtype, shape=tuple(shp))
    helper.append_op("stack", {"X": xs}, {"Y": [out]}, {"axis": axis})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    shp = list(input.shape)
    for ax, st, en in zip(axes, starts, ends):
        dim = shp[ax]
        if dim == -1:
            continue
        st2 = max(st + dim, 0) if st < 0 else min(st, dim)
        en2 = max(en + dim, 0) if en < 0 else min(en, dim)
        shp[ax] = max(en2 - st2, 0)
    out = helper.create_variable_for_type_inference(input.dtype, shape=tuple(shp))
    helper.append_op(
        "slice", {"Input": [input]}, {"Out": [out]},
        {"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def squeeze(input, axes, name=None):
    shp = [s for i, s in enumerate(input.shape) if i not in axes]
    return _unary("squeeze", input, attrs={"axes": list(axes)}, out_shape=tuple(shp))


def unsqueeze(input, axes, name=None):
    shp = list(input.shape)
    for ax in sorted(axes):
        shp.insert(ax, 1)
    return _unary("unsqueeze", input, attrs={"axes": list(axes)}, out_shape=tuple(shp))


def expand(x, expand_times, name=None):
    shp = tuple(s * t if s != -1 else -1 for s, t in zip(x.shape, expand_times))
    return _unary("expand", x, attrs={"expand_times": list(expand_times)}, out_shape=shp)


def gather(input, index):
    helper = LayerHelper("gather")
    shp = tuple(index.shape) + tuple(input.shape[1:])
    out = helper.create_variable_for_type_inference(input.dtype, shape=shp)
    helper.append_op("gather", {"X": [input], "Index": [index]}, {"Out": [out]})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    xs = list(x.shape)
    ys = list(y.shape)
    if transpose_x and len(xs) > 1:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if transpose_y and len(ys) > 1:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    batch = xs[:-2] if len(xs) > 2 else (ys[:-2] if len(ys) > 2 else [])
    out_shape = tuple(batch) + (xs[-2] if len(xs) > 1 else 1, ys[-1])
    if len(xs) == 1:
        out_shape = (ys[-1],)
    out = helper.create_variable_for_type_inference(x.dtype, shape=out_shape)
    helper.append_op(
        "matmul", {"X": [x], "Y": [y]}, {"Out": [out]},
        {"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": alpha},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out_shape = tuple(x.shape[:x_num_col_dims]) + tuple(y.shape[y_num_col_dims:])
    out = helper.create_variable_for_type_inference(x.dtype, shape=out_shape)
    helper.append_op(
        "mul", {"X": [x], "Y": [y]}, {"Out": [out]},
        {"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def _tile_rows(x, times):
    """[B, ...] -> [B*times, ...] repeating each row (beam fan-out;
    shared by models/machine_translation.py and contrib/decoder.py)."""
    expanded = expand(unsqueeze(x, [1]),
                      [1, times] + [1] * (len(x.shape) - 1))
    return reshape(expanded, [-1] + list(x.shape[1:]))


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    shp = tuple(input.shape[:-1]) + (k,)
    vals = helper.create_variable_for_type_inference(input.dtype, shape=shp)
    idx = helper.create_variable_for_type_inference("int64", shape=shp, stop_gradient=True)
    helper.append_op("top_k", {"X": [input]}, {"Out": [vals], "Indices": [idx]}, {"k": k})
    return vals, idx


def argmax(x, axis=-1):
    shp = tuple(s for i, s in enumerate(x.shape) if i != (axis % len(x.shape)))
    return _unary("arg_max", x, attrs={"axis": axis}, out_shape=shp, out_dtype="int64")


def cast(x, dtype):
    return _unary("cast", x, attrs={"out_dtype": dtype}, out_dtype=dtype)


def one_hot(input, depth):
    shp = tuple(input.shape[:-1] if input.shape[-1] == 1 else input.shape) + (depth,)
    return _unary("one_hot", input, attrs={"depth": depth}, out_shape=shp,
                  out_dtype="float32")


# ---------------------------------------------------------------------------
# elementwise / reductions / misc math
# ---------------------------------------------------------------------------

def _binary(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, act=act, name=name)
    shp = x.shape if len(x.shape) >= len(y.shape) else y.shape
    out = helper.create_variable_for_type_inference(x.dtype, shape=shp)
    helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [out]}, {"axis": axis})
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_pow", x, y, axis, act, name)


def _reduce_shape(x, dim, keep_dim):
    if dim is None:
        return () if not keep_dim else tuple(1 for _ in x.shape)
    dims = [d % len(x.shape) for d in (dim if isinstance(dim, (list, tuple)) else [dim])]
    if keep_dim:
        return tuple(1 if i in dims else s for i, s in enumerate(x.shape))
    return tuple(s for i, s in enumerate(x.shape) if i not in dims)


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=_reduce_shape(input, dim, keep_dim))
    attrs = {"keep_dim": keep_dim, "reduce_all": dim is None}
    if dim is not None:
        attrs["dim"] = list(dim) if isinstance(dim, (list, tuple)) else [dim]
    helper.append_op(op_type, {"X": [input]}, {"Out": [out]}, attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def mean(x, name=None):
    return _unary("mean", x, out_shape=())


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(
        "scale", {"X": [x]}, {"Out": [out]},
        {"scale": scale, "bias": bias, "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def clip(x, min, max, name=None):
    return _unary("clip", x, attrs={"min": min, "max": max})


def clip_by_norm(x, max_norm, name=None):
    return _unary("clip_by_norm", x, attrs={"max_norm": max_norm})


def _cmp_layer(op_type, x, y, cond=None, name=None):
    """Shared comparison/logical wrapper (less_than + the r5 equal/
    logical family)."""
    helper = LayerHelper(op_type, name=name)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool",
                                                         shape=x.shape)
    helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [cond]})
    return cond


def sums(input, out=None):
    helper = LayerHelper("sum")
    out = out or helper.create_variable_for_type_inference(
        input[0].dtype, shape=input[0].shape)
    helper.append_op("sum", {"X": input}, {"Out": [out]})
    if seq_len_var(input[0]) is not None:
        _alias_len(out, seq_len_var(input[0]))
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    norm = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(
        "norm", {"X": [x]}, {"Out": [out], "Norm": [norm]},
        {"axis": axis, "epsilon": epsilon},
    )
    return out


# ---------------------------------------------------------------------------
# recurrent layers (padded-sequence contract)
# ---------------------------------------------------------------------------

def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 seq_len=None):
    """LSTM over a padded sequence batch (reference nn.py dynamic_lstm).

    ``input``: [B, T, 4H] pre-projected gates (x·Wx + b, make with
    fc(num_flatten_dims=2)); ``size`` = 4H.  Returns (hidden [B,T,H],
    cell [B,T,H]).  Lengths come from ``seq_len`` or the companion
    ``<name>@LEN`` var of ``input``.
    """
    helper = LayerHelper("lstm", name=name)
    H = size // 4
    w = helper.create_parameter(param_attr, [H, 4 * H], dtype)
    b = helper.create_parameter(bias_attr, [4 * H], dtype, is_bias=True)
    biased = elementwise_add(input, b, axis=2)
    B, T = input.shape[0], input.shape[1]
    hidden = helper.create_variable_for_type_inference(dtype, shape=(B, T, H))
    cell = helper.create_variable_for_type_inference(dtype, shape=(B, T, H))
    last_h = helper.create_variable_for_type_inference(dtype, shape=(B, H))
    last_c = helper.create_variable_for_type_inference(dtype, shape=(B, H))
    ins = {"Input": [biased], "Weight": [w]}
    sl = seq_len or seq_len_var(input)
    if sl is not None:
        ins["SeqLen"] = [sl]
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    helper.append_op(
        "lstm", ins,
        {"Hidden": [hidden], "Cell": [cell], "LastH": [last_h], "LastC": [last_c]},
        {"is_reverse": is_reverse},
    )
    if sl is not None:
        _alias_len(hidden, sl)
    return hidden, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, h_0=None, name=None, seq_len=None,
                dtype="float32"):
    """GRU over a padded batch; ``input``: [B,T,3H], ``size`` = H."""
    helper = LayerHelper("gru", name=name)
    H = size
    w = helper.create_parameter(param_attr, [H, 3 * H], dtype)
    b = helper.create_parameter(bias_attr, [3 * H], dtype, is_bias=True)
    biased = elementwise_add(input, b, axis=2)
    B, T = input.shape[0], input.shape[1]
    hidden = helper.create_variable_for_type_inference(dtype, shape=(B, T, H))
    last_h = helper.create_variable_for_type_inference(dtype, shape=(B, H))
    ins = {"Input": [biased], "Weight": [w]}
    sl = seq_len or seq_len_var(input)
    if sl is not None:
        ins["SeqLen"] = [sl]
    if h_0 is not None:
        ins["H0"] = [h_0]
    helper.append_op(
        "gru", ins, {"Hidden": [hidden], "LastH": [last_h]},
        {"is_reverse": is_reverse},
    )
    if sl is not None:
        _alias_len(hidden, sl)
    return hidden


def _alias_len(var, seq_len):
    """Register seq_len as var's companion length var."""
    var.block.seq_len_map[var.name] = seq_len.name


def _propagate_lod(out, x):
    """Carry BOTH length companions and the lod_level through a
    shape-preserving layer (embedding/fc/elementwise...): without this,
    a nested ids -> embedding -> sequence_pool pipeline would silently
    fall back to the level-1 path with outer lengths applied to the
    sentence axis."""
    sl = seq_len_var(x)
    if sl is not None:
        _alias_len(out, sl)
    if getattr(x, "lod_level", 0) == 2:
        sl2 = seq_len2_var(x)
        if sl2 is not None:
            out.block.seq_len2_map[out.name] = sl2.name
            out.lod_level = 2


# ---------------------------------------------------------------------------
# sequence layers (padded contract; reference sequence_* op family)
# ---------------------------------------------------------------------------

def _seq_op(op_type, input, attrs=None, out_shape=None, pool=False, name=None):
    """Sequence-op layer shim.  Nested (lod_level 2) inputs route their
    inner [B, S] lengths through the op's "SeqLen2" slot (the op flattens
    to [B*S, W, ...] internally — ops/sequence_ops.py _nestable); pooling
    then REMOVES the inner level, so the result is a level-1 sequence
    whose companion is the OUTER lengths."""
    helper = LayerHelper(op_type, name=name)
    sl = seq_len_var(input)
    sl2 = seq_len2_var(input)
    nested = getattr(input, "lod_level", 0) == 2 and sl2 is not None
    if nested and pool and out_shape is None:
        out_shape = tuple(input.shape[:2]) + tuple(input.shape[3:])
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=out_shape if out_shape is not None else input.shape)
    ins = {"X": [input]}
    if sl is not None and not nested:
        ins["SeqLen"] = [sl]
    if sl2 is not None:
        ins["SeqLen2"] = [sl2]
    helper.append_op(op_type, ins, {"Out": [out]}, attrs or {})
    if nested:
        out.lod_level = 1 if pool else 2
        if sl is not None:
            _alias_len(out, sl)       # outer lengths survive either way
        if not pool and sl2 is not None:
            out.block.seq_len2_map[out.name] = sl2.name
    elif not pool and sl is not None:
        _alias_len(out, sl)
    return out


def sequence_pool(input, pool_type, name=None):
    if getattr(input, "lod_level", 0) == 2:
        return _seq_op("sequence_pool", input,
                       {"pooltype": pool_type.upper()}, pool=True, name=name)
    out_shape = (input.shape[0],) + tuple(input.shape[2:])
    return _seq_op("sequence_pool", input, {"pooltype": pool_type.upper()},
                   out_shape=out_shape, pool=True, name=name)


def sequence_softmax(input, name=None):
    return _seq_op("sequence_softmax", input, name=name)


def sequence_reverse(x, name=None):
    return _seq_op("sequence_reverse", x, name=name)


def sequence_first_step(input):
    out_shape = (None if getattr(input, "lod_level", 0) == 2
                 else (input.shape[0],) + tuple(input.shape[2:]))
    return _seq_op("sequence_first_step", input, out_shape=out_shape, pool=True)


def sequence_last_step(input):
    out_shape = (None if getattr(input, "lod_level", 0) == 2
                 else (input.shape[0],) + tuple(input.shape[2:]))
    return _seq_op("sequence_last_step", input, out_shape=out_shape, pool=True)


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out_shape = (x.shape[0], y.shape[1]) + tuple(x.shape[1:])
    out = helper.create_variable_for_type_inference(x.dtype, shape=out_shape)
    helper.append_op("sequence_expand", {"X": [x], "Y": [y]}, {"Out": [out]})
    sl = seq_len_var(y)
    if sl is not None:
        _alias_len(out, sl)
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    T = sum(v.shape[1] for v in input)
    out_shape = (input[0].shape[0], T) + tuple(input[0].shape[2:])
    out = helper.create_variable_for_type_inference(input[0].dtype, shape=out_shape)
    helper.append_op("sequence_concat", {"X": input}, {"Out": [out]})
    return out


def _seq_op_with_len(op_type, input, ins_extra, attrs, out_shape, out_dtype,
                     len_slot="OutLen", name=None):
    """Sequence op emitting (Out, new length vector); the out var gets the
    new lengths aliased as its @LEN companion."""
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(
        out_dtype or input.dtype, shape=out_shape or input.shape)
    new_len = helper.create_variable_for_type_inference(
        "int64", shape=(input.shape[0],), stop_gradient=True)
    ins = {"X": [input], **ins_extra}
    sl = seq_len_var(input)
    if sl is not None:
        ins.setdefault("SeqLen", [sl])
    helper.append_op(op_type, ins, {"Out": [out], len_slot: [new_len]},
                     attrs or {})
    _alias_len(out, new_len)
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, param_attr=None, bias_attr=None, act=None,
                  name=None):
    """Context-window convolution (reference nn.py sequence_conv)."""
    helper = LayerHelper("sequence_conv", bias_attr=bias_attr, act=act,
                         name=name)
    D = int(input.shape[-1])
    w = helper.create_parameter(param_attr, [filter_size * D, num_filters],
                                input.dtype)
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=(input.shape[0], input.shape[1], num_filters))
    ins = {"X": [input], "Filter": [w]}
    sl = seq_len_var(input)
    if sl is not None:
        ins["SeqLen"] = [sl]
    helper.append_op("sequence_conv", ins, {"Out": [out]},
                     {"contextLength": filter_size,
                      "contextStart": -(filter_size // 2),
                      "contextStride": filter_stride})
    pre_act = helper.append_bias_op(out, dim_start=2)
    final = helper.append_activation(pre_act)
    if sl is not None:
        _alias_len(final, sl)  # the RETURNED var carries the lengths
    return final


def sequence_slice(input, offset, length, name=None):
    return _seq_op_with_len("sequence_slice", input,
                            {"Offset": [offset], "Length": [length]}, {},
                            input.shape, input.dtype, name=name)


def sequence_erase(input, tokens, name=None):
    return _seq_op_with_len("sequence_erase", input, {},
                            {"tokens": list(tokens)}, input.shape,
                            input.dtype, name=name)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    lead = (input.shape[0], input.shape[1])
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=lead + (win_size,))
    ins = {"X": [input]}
    sl = seq_len_var(input)
    if sl is not None:
        ins["SeqLen"] = [sl]
    helper.append_op("sequence_enumerate", ins, {"Out": [out]},
                     {"win_size": win_size, "pad_value": pad_value})
    if sl is not None:
        _alias_len(out, sl)
    return out


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(
        x.dtype, shape=(x.shape[0], y.shape[1]) + tuple(x.shape[1:]))
    ins = {"X": [x], "Y": [y]}
    sl = seq_len_var(y)
    if sl is not None:
        ins["SeqLen"] = [sl]
    helper.append_op("sequence_expand_as", ins, {"Out": [out]})
    if sl is not None:
        _alias_len(out, sl)
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Returns (padded, lengths) like the reference (nn.py sequence_pad)."""
    helper = LayerHelper("sequence_pad", name=name)
    T = maxlen or x.shape[1]
    out = helper.create_variable_for_type_inference(
        x.dtype, shape=(x.shape[0], T) + tuple(x.shape[2:]))
    lens = helper.create_variable_for_type_inference(
        "int64", shape=(x.shape[0],), stop_gradient=True)
    ins = {"X": [x], "PadValue": [pad_value]}
    sl = seq_len_var(x)
    if sl is not None:
        ins["SeqLen"] = [sl]
    helper.append_op("sequence_pad", ins, {"Out": [out], "Length": [lens]},
                     {"padded_length": maxlen or -1})
    return out, lens


def sequence_unpad(x, length, name=None):
    return _seq_op_with_len("sequence_unpad", x, {"Length": [length]}, {},
                            x.shape, x.dtype, name=name)


def sequence_reshape(input, new_dim, name=None):
    D = int(input.shape[-1])
    T = int(input.shape[1]) * D // new_dim
    return _seq_op_with_len("sequence_reshape", input, {},
                            {"new_dim": new_dim},
                            (input.shape[0], T, new_dim), input.dtype,
                            name=name)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Lookahead convolution (reference nn.py row_conv)."""
    helper = LayerHelper("row_conv", name=name)
    D = int(input.shape[-1])
    w = helper.create_parameter(param_attr, [future_context_size, D],
                                input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=input.shape)
    ins = {"X": [input], "Filter": [w]}
    sl = seq_len_var(input)
    if sl is not None:
        ins["SeqLen"] = [sl]
    helper.append_op("row_conv", ins, {"Out": [out]}, {})
    if sl is not None:
        _alias_len(out, sl)
    return out


# ---------------------------------------------------------------------------
# structured losses: CTC + linear-chain CRF (ops/ctc_crf_ops.py)
# ---------------------------------------------------------------------------

def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None, name=None):
    """CTC loss (reference nn.py warpctc); returns [B,1] losses."""
    helper = LayerHelper("warpctc", name=name)
    loss = helper.create_variable_for_type_inference(
        "float32", shape=(input.shape[0], 1))
    ins = {"Logits": [input], "Label": [label]}
    il = input_length if input_length is not None else seq_len_var(input)
    ll = label_length if label_length is not None else seq_len_var(label)
    if il is not None:
        ins["LogitsLength"] = [il]
    if ll is not None:
        ins["LabelLength"] = [ll]
    helper.append_op("warpctc", ins, {"Loss": [loss]},
                     {"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """Greedy CTC decode: per-step argmax then ctc_align cleanup; returns
    (decoded ids [B,T], lengths [B])."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    am = helper.create_variable_for_type_inference(
        "int64", shape=tuple(input.shape[:2]), stop_gradient=True)
    helper.append_op("arg_max", {"X": [input]}, {"Out": [am]}, {"axis": -1})
    out = helper.create_variable_for_type_inference(
        "int64", shape=tuple(input.shape[:2]), stop_gradient=True)
    out_len = helper.create_variable_for_type_inference(
        "int64", shape=(input.shape[0],), stop_gradient=True)
    ins = {"Input": [am]}
    il = input_length if input_length is not None else seq_len_var(input)
    if il is not None:
        ins["InputLength"] = [il]
    helper.append_op("ctc_align", ins,
                     {"Output": [out], "OutputLength": [out_len]},
                     {"blank": blank, "merge_repeated": True})
    _alias_len(out, out_len)
    return out, out_len


def linear_chain_crf(input, label, param_attr=None, length=None, name=None):
    """Linear-chain CRF log-likelihood (reference nn.py linear_chain_crf);
    creates the [C+2, C] transition parameter."""
    helper = LayerHelper("linear_chain_crf", name=name)
    C = int(input.shape[-1])
    trans = helper.create_parameter(param_attr, [C + 2, C], "float32")
    ll = helper.create_variable_for_type_inference(
        "float32", shape=(input.shape[0], 1))
    ins = {"Emission": [input], "Transition": [trans], "Label": [label]}
    ln = length if length is not None else seq_len_var(input)
    if ln is not None:
        ins["Length"] = [ln]
    helper.append_op("linear_chain_crf", ins, {"LogLikelihood": [ll]}, {})
    return ll


def crf_decoding(input, param_attr, length=None, name=None):
    """Viterbi decode sharing the CRF transition parameter by name."""
    helper = LayerHelper("crf_decoding", name=name)
    trans_name = param_attr.name if hasattr(param_attr, "name") else str(param_attr)
    trans = input.block.program.global_block.var(trans_name)
    path = helper.create_variable_for_type_inference(
        "int64", shape=tuple(input.shape[:2]), stop_gradient=True)
    ins = {"Emission": [input], "Transition": [trans]}
    ln = length if length is not None else seq_len_var(input)
    if ln is not None:
        ins["Length"] = [ln]
    helper.append_op("crf_decoding", ins, {"ViterbiPath": [path]}, {})
    return path
