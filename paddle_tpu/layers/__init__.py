from . import control_flow, detection, io, learning_rate_scheduler, nn, ops, tensor  # noqa: F401
from .control_flow import (  # noqa: F401
    ConditionalBlock,
    DynamicRNN,
    StaticRNN,
    Switch,
    While,
    array_length,
    array_read,
    array_write,
    beam_search,
    beam_search_decode,
    create_array,
    less_than,
)
from .io import data  # noqa: F401
from .nn import *  # noqa: F401,F403
from . import nn_extras  # noqa: F401
from .nn_extras import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    assign,
    create_global_var,
    create_parameter,
    create_tensor,
    fill_constant,
    fill_constant_batch_size_like,
    increment,
    ones,
    zeros,
    zeros_like,
)
