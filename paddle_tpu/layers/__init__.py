from . import control_flow, detection, io, learning_rate_scheduler, nn, ops, tensor  # noqa: F401
from .control_flow import (  # noqa: F401
    ConditionalBlock,
    DynamicRNN,
    IfElse,
    RankTable,
    StaticRNN,
    Switch,
    While,
    array_length,
    array_read,
    array_to_lod_tensor,
    array_write,
    beam_search,
    beam_search_decode,
    create_array,
    less_than,
    lod_rank_table,
    lod_tensor_to_array,
    max_sequence_len,
    merge_lod_tensor,
    reorder_lod_tensor_by_rank,
    shrink_memory,
    split_lod_tensor,
)
from .io import (  # noqa: F401
    data,
    double_buffer,
    get_places,
    py_reader,
    read_file,
)
from .detection import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from . import nn_extras  # noqa: F401
from .nn_extras import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    argmin,
    assign,
    create_global_var,
    create_parameter,
    create_tensor,
    fill_constant,
    fill_constant_batch_size_like,
    increment,
    ones,
    zeros,
    zeros_like,
)
