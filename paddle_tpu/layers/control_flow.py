"""Control-flow layers: While, StaticRNN, Switch, ConditionalBlock.

Reference: ``python/paddle/fluid/layers/control_flow.py`` (StaticRNN:429,
While:654, ConditionalBlock:1203, Switch:1285).  Same user API; the emitted
ops carry explicit ``carry_vars``/``memories`` attrs so the lowering can
build ``lax.while_loop``/``scan``/``cond`` (see ops/control_flow_ops.py).
"""
from __future__ import annotations

import collections
from typing import List, Optional

from ..core.program import Variable, default_main_program
from ..layer_helper import LayerHelper
from .nn import _unary  # reuse helper


def _written_names(block) -> List[str]:
    out = []
    for op in block.ops:
        for n in op.output_arg_names():
            if n and n not in out:
                out.append(n)
    return out


def _unstop_float_carries(parent, carries) -> None:
    """Loop/branch carries are mutable state, not constants: a float TEMP
    var written inside the block becomes differentiable even if its initial
    value came from a stop-gradient source (fill_constant init — the
    decoder-state pattern, machine_translation.py:104).  Persistable vars
    keep their flag: an explicit user freeze (target nets, running stats)
    must not be overridden."""
    from ..core.types import is_float

    for n in carries:
        v = parent.var_or_none(n)
        if v is not None and not v.persistable \
                and (v.dtype is None or is_float(v.dtype)):
            v.stop_gradient = False


def _copy_carry_inits(parent, sub_idx, names) -> List[str]:
    """Snapshot pre-block carry values into explicit ``@INIT`` vars (assign
    ops before the control-flow op).  The grad lowering reads these — they
    survive host-op segmentation, unlike a trace-local stash (the
    step-scope capture of while_op.cc:56 as program state)."""
    out = []
    for n in names:
        v = parent.var(n)
        init = parent.create_var(name=f"{n}@INIT@{sub_idx}", shape=v.shape,
                                 dtype=v.dtype, stop_gradient=True)
        parent.append_op("assign", {"X": [n]}, {"Out": [init.name]})
        out.append(init.name)
    return out


def _captured_names(block, exclude) -> List[str]:
    defined = set(exclude)
    captured = []
    for op in block.ops:
        for n in op.input_arg_names():
            if n and n not in defined and n not in captured \
                    and not block.has_var(n):
                captured.append(n)
        defined |= {n for n in op.output_arg_names() if n}
    return captured


class BlockGuard:
    def __init__(self, program):
        self.program = program

    def __enter__(self):
        self.block = self.program._create_block()
        return self.block

    def __exit__(self, exc_type, *a):
        self.program._rollback()
        return False


class While:
    """while loop (control_flow.py:654).  The sub-block must reassign the
    condition var; vars assigned in the block that exist outside become the
    loop carry.  ``max_iters`` (a static trip-count bound) makes the loop
    differentiable: the backward pass replays it as a masked scan."""

    def __init__(self, cond: Variable, name: Optional[str] = None,
                 max_iters: Optional[int] = None):
        self.helper = LayerHelper("while", name=name)
        assert cond.dtype == "bool", "While condition must be bool"
        self.cond_var = cond
        self.max_iters = max_iters

    def block(self):
        return _WhileGuard(self)


class _WhileGuard(BlockGuard):
    def __init__(self, while_op: While):
        super().__init__(default_main_program())
        self.while_op = while_op

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            self.program._rollback()
            return False
        sub = self.block
        self.program._rollback()
        parent = self.program.current_block()
        cond_name = self.while_op.cond_var.name
        carries = [n for n in _written_names(sub)
                   if parent.var_or_none(n) is not None and n != cond_name]
        captured = [n for n in _captured_names(sub, [cond_name] + carries)
                    if parent.var_or_none(n) is not None]
        _unstop_float_carries(parent, carries)
        init_names = _copy_carry_inits(parent, sub.idx, [cond_name] + carries)
        parent.append_op(
            "while",
            {"Condition": [cond_name], "X": carries, "Captured": captured,
             "Init": init_names},
            {"Out": carries},
            {"sub_block": sub.idx, "carry_vars": [cond_name] + carries,
             "captured_vars": captured,
             "max_iters": self.while_op.max_iters or 0},
        )
        return False


class StaticRNN:
    """Fixed-length RNN over [B, T, ...] step inputs (control_flow.py:429;
    lowers to lax.scan → trains via reverse-scan vjp)."""

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = self.BEFORE_RNN_BLOCK
        self._step_inputs = []      # (outer_name, inner_var)
        self._memories = []         # [inner_mem_name, init_name, updated_name]
        self._outputs = []          # (inner_name, outer_var)
        self._sub_block = None
        self.seq_len = None

    def step(self):
        return _RnnGuard(self)

    def _assert_in_rnn_block(self):
        assert self.status == self.IN_RNN_BLOCK, "must be called in rnn.step() block"

    def step_input(self, x: Variable) -> Variable:
        self._assert_in_rnn_block()
        if self.seq_len is None:
            self.seq_len = x.shape[1]
        inner = self._sub_block.create_var(
            name=x.name + "@STEP", dtype=x.dtype,
            shape=(x.shape[0],) + tuple(x.shape[2:]))
        self._step_inputs.append((x.name, inner))
        return inner

    def memory(self, init: Optional[Variable] = None, shape=None,
               batch_ref: Optional[Variable] = None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1) -> Variable:
        self._assert_in_rnn_block()
        if init is None:
            assert shape is not None and batch_ref is not None, \
                "memory needs init or (shape, batch_ref)"
            parent = self._sub_block.parent_block
            # batch_ref may be an inner step var — the init op lives in the
            # parent block, so reference the outer sequence input instead
            ref_name = batch_ref.name
            for outer, inner in self._step_inputs:
                if inner.name == ref_name:
                    ref_name = outer
                    break
            init = parent.create_var(
                name=self.helper.name + f".mem_init_{len(self._memories)}",
                dtype=batch_ref.dtype,
                shape=(batch_ref.shape[0],) + tuple(shape))
            # materialize init before the rnn op (in the parent block)
            parent.append_op(
                "fill_constant_batch_size_like",
                {"Input": [ref_name]}, {"Out": [init.name]},
                {"shape": [-1] + list(shape), "dtype": init.dtype,
                 "value": init_value, "input_dim_idx": 0, "output_dim_idx": 0})
        mem = self._sub_block.create_var(
            name=self.helper.name + f".mem_{len(self._memories)}",
            dtype=init.dtype, shape=init.shape)
        self._memories.append([mem.name, init.name, None])
        return mem

    def update_memory(self, mem: Variable, var: Variable) -> None:
        self._assert_in_rnn_block()
        for rec in self._memories:
            if rec[0] == mem.name:
                rec[2] = var.name
                return
        raise ValueError(f"{mem.name} is not a memory of this RNN")

    def step_output(self, o: Variable) -> None:
        self._assert_in_rnn_block()
        outer = self._sub_block.parent_block.create_var(
            name=o.name + "@SEQ", dtype=o.dtype,
            shape=(o.shape[0], self.seq_len) + tuple(o.shape[1:]))
        self._outputs.append((o.name, outer))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        assert self.status == self.AFTER_RNN_BLOCK, "call rnn() after the step block"
        outs = [outer for _, outer in self._outputs]
        return outs[0] if len(outs) == 1 else outs

    def _complete(self):
        sub = self._sub_block
        parent = sub.parent_block
        assert all(rec[2] is not None for rec in self._memories), \
            "every memory needs update_memory"
        inner_defined = [inner.name for _, inner in self._step_inputs] + \
            [rec[0] for rec in self._memories]
        captured = _captured_names(sub, inner_defined)
        parent.append_op(
            "static_rnn",
            {"X": [outer for outer, _ in self._step_inputs],
             "Init": [rec[1] for rec in self._memories],
             "Captured": captured},
            {"Out": [outer.name for _, outer in self._outputs]},
            {"sub_block": sub.idx,
             "step_inputs": [outer for outer, _ in self._step_inputs],
             "step_input_vars": [inner.name for _, inner in self._step_inputs],
             "memories": self._memories,
             "step_outputs": [[inner, outer.name] for inner, outer in self._outputs]},
        )


class _RnnGuard(BlockGuard):
    def __init__(self, rnn: StaticRNN):
        super().__init__(default_main_program())
        self.rnn = rnn

    def __enter__(self):
        self.block = self.program._create_block()
        self.rnn._sub_block = self.block
        self.rnn.status = StaticRNN.IN_RNN_BLOCK
        return self.block

    def __exit__(self, exc_type, *a):
        self.program._rollback()
        if exc_type is None:
            self.rnn.status = StaticRNN.AFTER_RNN_BLOCK
            self.rnn._complete()
        return False


class ConditionalBlock:
    """Run a block iff condition (control_flow.py:1203)."""

    def __init__(self, inputs, is_scalar_condition=True, name=None):
        self.helper = LayerHelper("conditional_block", name=name)
        self.cond = inputs[0] if isinstance(inputs, (list, tuple)) else inputs

    def block(self):
        return _CondGuard(self)


class _CondGuard(BlockGuard):
    def __init__(self, cb: ConditionalBlock):
        super().__init__(default_main_program())
        self.cb = cb

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            self.program._rollback()
            return False
        sub = self.block
        self.program._rollback()
        parent = self.program.current_block()
        carries = [n for n in _written_names(sub)
                   if parent.var_or_none(n) is not None]
        cond_name = self.cb.cond.name
        captured = [n for n in _captured_names(sub, [cond_name] + carries)
                    if parent.var_or_none(n) is not None]
        _unstop_float_carries(parent, carries)
        init_names = _copy_carry_inits(parent, sub.idx, carries)
        parent.append_op(
            "conditional_block",
            {"Condition": [cond_name], "X": carries, "Captured": captured,
             "Init": init_names},
            {"Out": carries},
            {"sub_block": sub.idx, "carry_vars": carries,
             "captured_vars": captured},
        )
        return False


class Switch:
    """case/default sugar over ConditionalBlock (control_flow.py:1285)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conditions: List[Variable] = []

    def case(self, condition):
        from . import nn
        if self.pre_not_conditions:
            not_prev = _unary("logical_not", self.pre_not_conditions[-1],
                              out_dtype="bool")
            cond = self._and(not_prev, condition)
        else:
            cond = condition
        self.pre_not_conditions.append(
            self._or(self.pre_not_conditions[-1], condition)
            if self.pre_not_conditions else condition)
        return ConditionalBlock([cond]).block()

    def default(self):
        assert self.pre_not_conditions, "default needs a prior case"
        not_all = _unary("logical_not", self.pre_not_conditions[-1],
                         out_dtype="bool")
        return ConditionalBlock([not_all]).block()

    def _and(self, a, b):
        helper = LayerHelper("logical_and")
        out = helper.create_variable_for_type_inference("bool", shape=a.shape)
        helper.append_op("logical_and", {"X": [a], "Y": [b]}, {"Out": [out]})
        return out

    def _or(self, a, b):
        helper = LayerHelper("logical_or")
        out = helper.create_variable_for_type_inference("bool", shape=a.shape)
        helper.append_op("logical_or", {"X": [a], "Y": [b]}, {"Out": [out]})
        return out

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class DynamicRNN(StaticRNN):
    """Variable-length RNN over padded sequences (control_flow.py:1541).

    The reference sorts sequences with a LoDRankTable and shrinks the
    batch per step; the TPU redesign scans the padded [B, T, ...] layout
    and masks memory updates + outputs by each row's sequence length (the
    ``@LEN`` companion of the lod_level>=1 input) — rows past their length
    keep their last state and emit zeros.  Same lax.scan reverse-mode
    gradient as StaticRNN.
    """

    def __init__(self, name: Optional[str] = None):
        super().__init__(name=name)
        self._seq_len_name = None

    def block(self):  # reference API name
        return self.step()

    def step_input(self, x: Variable) -> Variable:
        from .nn import seq_len_var

        lv = seq_len_var(x)
        if lv is None:
            raise ValueError(
                f"DynamicRNN.step_input needs a sequence input with a "
                f"length companion (data(lod_level=1)); {x.name!r} has "
                f"none — use StaticRNN for fixed-length input")
        if self._seq_len_name is None:
            self._seq_len_name = lv.name
        return super().step_input(x)

    def _complete(self):
        sub = self._sub_block
        parent = sub.parent_block
        assert all(rec[2] is not None for rec in self._memories), \
            "every memory needs update_memory"
        inner_defined = [inner.name for _, inner in self._step_inputs] + \
            [rec[0] for rec in self._memories]
        captured = _captured_names(sub, inner_defined)
        parent.append_op(
            "dynamic_rnn",
            {"X": [outer for outer, _ in self._step_inputs],
             "Init": [rec[1] for rec in self._memories],
             "Captured": captured,
             "SeqLen": [self._seq_len_name]},
            {"Out": [outer.name for _, outer in self._outputs]},
            {"sub_block": sub.idx,
             "step_inputs": [outer for outer, _ in self._step_inputs],
             "step_input_vars": [inner.name for _, inner in self._step_inputs],
             "memories": self._memories,
             "step_outputs": [[inner, outer.name]
                              for inner, outer in self._outputs]},
        )
        # outputs are padded sequences with the same lengths as the input
        from .nn import _alias_len

        seq_len = parent.var(self._seq_len_name)
        for _, outer in self._outputs:
            _alias_len(outer, seq_len)


def less_than(x, y, cond=None):
    from .nn import _cmp_layer

    return _cmp_layer("less_than", x, y, cond)


# ---------------------------------------------------------------------------
# TensorArray (preallocated [max_len, ...] + int64 length; ops/array_ops.py)
# ---------------------------------------------------------------------------

def _array_len_var(array: Variable) -> Variable:
    return array.block.var(array.name + "@ALEN")


def create_array(dtype, element_shape, max_len, name=None) -> Variable:
    """TensorArray of capacity ``max_len`` (LoDTensorArray analogue;
    the reference grows on write — XLA needs the bound up front)."""
    helper = LayerHelper("array", name=name)
    arr = helper.create_variable_for_type_inference(
        dtype, shape=(max_len,) + tuple(element_shape))
    ln = arr.block.create_var(name=arr.name + "@ALEN", dtype="int64",
                              shape=(1,))
    helper.append_op("fill_constant", {}, {"Out": [arr]},
                     {"shape": [max_len] + list(element_shape),
                      "dtype": arr.dtype, "value": 0.0})
    helper.append_op("fill_constant", {}, {"Out": [ln]},
                     {"shape": [1], "dtype": "int64", "value": 0})
    return arr


def array_write(x: Variable, i: Variable, array: Variable) -> Variable:
    """array[i] = x (tensor_array_read_write_op.cc WriteToArray)."""
    ln = _array_len_var(array)
    array.block.program.current_block().append_op(
        "array_write",
        {"X": [x.name], "I": [i.name], "Array": [array.name],
         "ArrayLen": [ln.name]},
        {"Out": [array.name], "LenOut": [ln.name]})
    return array


def array_read(array: Variable, i: Variable) -> Variable:
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(
        array.dtype, shape=tuple(array.shape[1:]))
    helper.append_op("array_read", {"Array": [array], "I": [i]},
                     {"Out": [out]})
    return out


def array_length(array: Variable) -> Variable:
    """Number of written slots (reference array_length op)."""
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64", shape=(1,))
    helper.append_op("assign", {"X": [_array_len_var(array)]},
                     {"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# beam search (ops/array_ops.py; reference beam_search_op.cc)
# ---------------------------------------------------------------------------

def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                name=None):
    """One step of beam search over [batch*beam, K] candidates; returns
    (selected_ids [BW,1], selected_scores [BW,1], parent_idx [BW]).
    Seed pre_scores with 0 for beam 0 and -inf for the others of each
    group at step 0 (see ops/array_ops.py beam_search docstring)."""
    helper = LayerHelper("beam_search", name=name)
    bw = ids.shape[0]
    sel_ids = helper.create_variable_for_type_inference("int64", shape=(bw, 1))
    sel_scores = helper.create_variable_for_type_inference(
        scores.dtype, shape=(bw, 1))
    parent = helper.create_variable_for_type_inference("int64", shape=(bw,))
    helper.append_op(
        "beam_search",
        {"PreIds": [pre_ids], "PreScores": [pre_scores], "Ids": [ids],
         "Scores": [scores]},
        {"SelectedIds": [sel_ids], "SelectedScores": [sel_scores],
         "ParentIdx": [parent]},
        {"beam_size": beam_size, "end_id": end_id})
    return sel_ids, sel_scores, parent


BeamDecodeResult = collections.namedtuple(
    "BeamDecodeResult", ["ids", "scores", "cand_len", "src_len"])


def beam_search_decode(ids_array, parents_array, beam_size, end_id,
                       scores_array=None, name=None):
    """Backtrack TensorArrays of per-step selections into the reference's
    level-2 nested result (beam_search_decode_op.cc: source -> candidate
    -> token LoD, framework/lod_tensor.h:58), padded encoding:

    - ``ids``      [batch*beam, max_len] flat token values, with its
      ``@LEN`` companion aliased to ``cand_len`` so sequence ops mask
      each candidate at its real token length
    - ``scores``   [batch*beam, max_len] per-token scores along the same
      backtrack (None unless ``scores_array`` is given)
    - ``cand_len`` [batch*beam] tokens per candidate (incl. the end_id)
    - ``src_len``  [batch] candidates per source sentence
    """
    from .nn import _alias_len

    helper = LayerHelper("beam_search_decode", name=name)
    t_max, bw = ids_array.shape[0], ids_array.shape[1]
    sents = helper.create_variable_for_type_inference(
        "int64", shape=(bw, t_max))
    cand_len = helper.create_variable_for_type_inference(
        "int64", shape=(bw,), stop_gradient=True)
    src_len = helper.create_variable_for_type_inference(
        "int64", shape=(bw // beam_size,), stop_gradient=True)
    ins = {"Ids": [ids_array], "Parents": [parents_array],
           "ArrayLen": [_array_len_var(ids_array)]}
    outs = {"SentenceIds": [sents], "SentenceLen": [cand_len],
            "SourceLen": [src_len]}
    scores = None
    if scores_array is not None:
        ins["Scores"] = [scores_array]
        scores = helper.create_variable_for_type_inference(
            scores_array.dtype, shape=(bw, t_max))
        outs["SentenceScores"] = [scores]
    helper.append_op("beam_search_decode", ins, outs,
                     {"end_id": end_id, "beam_size": beam_size})
    _alias_len(sents, cand_len)
    return BeamDecodeResult(sents, scores, cand_len, src_len)


# ---------------------------------------------------------------------------
# LoD rank-table machinery (reference layers/control_flow.py lod_rank_table
# family + IfElse).  A RankTable is a pair of [B] vars: sequence indices in
# descending-length order and the lengths in that order.
# ---------------------------------------------------------------------------

class RankTable:
    """LoDRankTable analogue (framework/lod_rank_table.h) on the padded
    contract."""

    def __init__(self, rank_idx: Variable, rank_len: Variable):
        self.rank_idx = rank_idx
        self.rank_len = rank_len


def lod_rank_table(x, level=0):
    """Build a rank table from x's @LEN companion (level-1 sequences;
    reference lod_rank_table_op.cc)."""
    from .nn import seq_len_var

    if level != 0:
        raise ValueError(
            "lod_rank_table: only level-0 of the level-1 padded contract "
            "exists on TPU (nested LoD is intentionally unported)")
    sl = seq_len_var(x)
    if sl is None:
        raise ValueError(f"lod_rank_table: {x.name!r} has no @LEN companion")
    helper = LayerHelper("lod_rank_table")
    idx = helper.create_variable_for_type_inference(
        "int64", shape=(x.shape[0],), stop_gradient=True)
    lens = helper.create_variable_for_type_inference(
        "int64", shape=(x.shape[0],), stop_gradient=True)
    helper.append_op("lod_rank_table", {"SeqLen": [sl]},
                     {"RankIdx": [idx], "RankLen": [lens]}, {})
    return RankTable(idx, lens)


def max_sequence_len(rank_table):
    """Longest sequence length in the table (max_sequence_len_op.cc)."""
    helper = LayerHelper("max_sequence_len")
    out = helper.create_variable_for_type_inference(
        "int64", shape=(1,), stop_gradient=True)
    helper.append_op("max_sequence_len",
                     {"RankLen": [rank_table.rank_len]}, {"Out": [out]}, {})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """Gather rows into rank order (reorder_lod_tensor_by_rank_op.cc)."""
    from .nn import seq_len_var, _alias_len

    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    ins = {"X": [x], "RankIdx": [rank_table.rank_idx]}
    outs = {"Out": [out]}
    sl = seq_len_var(x)
    if sl is not None:
        new_len = helper.create_variable_for_type_inference(
            "int64", shape=(x.shape[0],), stop_gradient=True)
        ins["SeqLen"] = [sl]
        outs["OutLen"] = [new_len]
    helper.append_op("reorder_lod_tensor_by_rank", ins, outs, {})
    if sl is not None:
        _alias_len(out, new_len)
    return out


def lod_tensor_to_array(x, table):
    """[B,T,...] -> TensorArray [T,B,...] in rank order
    (lod_tensor_to_array_op.cc; the array is full-batch per step — see
    ops/array_ops.py for the static-shape rationale)."""
    helper = LayerHelper("lod_tensor_to_array")
    T = x.shape[1]
    arr = helper.create_variable_for_type_inference(
        x.dtype, shape=(T, x.shape[0]) + tuple(x.shape[2:]))
    ln = arr.block.create_var(name=arr.name + "@ALEN", dtype="int64",
                              shape=(1,))
    helper.append_op("lod_tensor_to_array",
                     {"X": [x], "RankIdx": [table.rank_idx]},
                     {"Out": [arr], "LenOut": [ln]}, {})
    return arr


def array_to_lod_tensor(x, table):
    """Inverse of lod_tensor_to_array (array_to_lod_tensor_op.cc)."""
    from .nn import _alias_len

    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_variable_for_type_inference(
        x.dtype, shape=(x.shape[1], x.shape[0]) + tuple(x.shape[2:]))
    out_len = helper.create_variable_for_type_inference(
        "int64", shape=(x.shape[1],), stop_gradient=True)
    helper.append_op("array_to_lod_tensor",
                     {"X": [x], "RankIdx": [table.rank_idx],
                      "RankLen": [table.rank_len]},
                     {"Out": [out], "OutLen": [out_len]}, {})
    _alias_len(out, out_len)  # lengths restored to original row order
    return out


def shrink_memory(x, i, table):
    """Zero memory rows of finished sequences at step i
    (shrink_rnn_memory_op.cc; masked instead of sliced — static shapes)."""
    helper = LayerHelper("shrink_rnn_memory")
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op("shrink_rnn_memory",
                     {"X": [x], "I": [i], "RankLen": [table.rank_len]},
                     {"Out": [out]}, {})
    return out


def split_lod_tensor(input, mask, level=0):
    """Route rows by boolean mask into (true, false) full-batch tensors
    with unselected rows zeroed (split_lod_tensor_op.cc redesign)."""
    helper = LayerHelper("split_lod_tensor")
    out_true = helper.create_variable_for_type_inference(
        input.dtype, shape=input.shape)
    out_false = helper.create_variable_for_type_inference(
        input.dtype, shape=input.shape)
    helper.append_op("split_lod_tensor",
                     {"X": [input], "Mask": [mask]},
                     {"OutTrue": [out_true], "OutFalse": [out_false]}, {})
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0):
    """Row-wise select (merge_lod_tensor_op.cc)."""
    helper = LayerHelper("merge_lod_tensor")
    out = helper.create_variable_for_type_inference(
        in_true.dtype, shape=in_true.shape)
    helper.append_op("merge_lod_tensor",
                     {"InTrue": [in_true], "InFalse": [in_false],
                      "Mask": [mask], "X": [x]},
                     {"Out": [out]}, {})
    return out


class IfElse:
    """Row-wise if-else (reference layers/control_flow.py IfElse).

    TPU redesign: the reference splits the batch by ``cond`` and runs each
    block on its subset; here both blocks run on the full batch (unselected
    rows zeroed by split_lod_tensor) and outputs merge row-wise — the
    compute-both-and-select pattern XLA wants.  Contract unchanged for
    row-wise blocks (each output row depends only on its input row).

    Usage matches the reference::

        ie = fluid.layers.IfElse(cond)      # cond: [B, 1] bool
        with ie.true_block():
            d = ie.input(x)
            ie.output(some_layers(d))
        with ie.false_block():
            d = ie.input(x)
            ie.output(other_layers(d))
        out, = ie()
    """

    OUT, IN_TRUE, IN_FALSE = 0, 1, 2

    def __init__(self, cond, name=None):
        self.cond = cond
        self.status = self.OUT
        self._splits = {}     # input name -> (true_masked, false_masked)
        self._outputs = {self.IN_TRUE: [], self.IN_FALSE: []}

    def _guard(self, status):
        import contextlib

        @contextlib.contextmanager
        def guard():
            if self.status != self.OUT:
                raise ValueError("cannot nest IfElse blocks")
            self.status = status
            try:
                yield
            finally:
                self.status = self.OUT
        return guard()

    def true_block(self):
        return self._guard(self.IN_TRUE)

    def false_block(self):
        return self._guard(self.IN_FALSE)

    def input(self, x):
        if self.status == self.OUT:
            raise ValueError("IfElse.input() must be called inside a block")
        if x.name not in self._splits:
            self._splits[x.name] = split_lod_tensor(x, self.cond)
        t, f = self._splits[x.name]
        return t if self.status == self.IN_TRUE else f

    def output(self, *outs):
        if self.status == self.OUT:
            raise ValueError("IfElse.output() must be called inside a block")
        self._outputs[self.status].extend(outs)

    def __call__(self):
        t_outs = self._outputs[self.IN_TRUE]
        f_outs = self._outputs[self.IN_FALSE]
        if len(t_outs) != len(f_outs):
            raise ValueError(
                f"IfElse blocks produced {len(t_outs)} vs {len(f_outs)} "
                "outputs; both blocks must ie.output() the same arity")
        return [merge_lod_tensor(t, f, t, self.cond)
                for t, f in zip(t_outs, f_outs)]
