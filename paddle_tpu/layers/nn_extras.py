"""Layer DSL tail: the remaining reference ``layers/nn.py`` ``__all__``
surface (reference python/paddle/fluid/layers/nn.py — losses, image ops,
RNN unit cells, candidate-sampling classifiers, random layers).

Split from ``nn.py`` only for file size; ``layers/__init__`` re-exports
both, so ``fluid.layers.<fn>`` matches the reference API.
"""
from __future__ import annotations

import numpy as np

from ..initializer import ConstantInitializer, NormalInitializer
from ..layer_helper import LayerHelper
from .nn import (_pair, seq_len_var, _alias_len, _seq_op_with_len,
                 _cmp_layer)

__all__ = [
    "equal", "not_equal", "less_equal", "greater_than",
    "logical_and", "logical_or", "logical_xor", "logical_not",
    "is_empty", "isfinite", "has_inf", "has_nan", "sum", "Print",
    "autoincreased_step_counter", "append_LARS", "cumsum",
    "cos_sim", "hinge_loss", "log_loss", "rank_loss", "margin_rank_loss",
    "modified_huber_loss", "squared_l2_distance", "squared_l2_norm",
    "l1_norm", "bilinear_tensor_product", "minus", "label_smooth",
    "smooth_l1", "dice_loss", "flatten", "reverse", "unstack", "crop",
    "pad", "pad2d", "pad_constant_like", "multiplex", "argsort", "shape",
    "scatter", "sequence_scatter", "sequence_mask", "lod_reset",
    "im2sequence", "prelu", "affine_channel", "lrn", "maxout",
    "bilinear_interp", "image_resize", "image_resize_short",
    "resize_bilinear", "roi_pool", "random_crop", "mean_iou", "chunk_eval",
    "gru_unit", "lstm_unit", "dynamic_lstmp", "conv3d", "pool3d",
    "conv3d_transpose", "nce", "hsigmoid", "sampling_id", "gaussian_random",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
]


def _simple(op_type, ins, attrs=None, out_shape=None, out_dtype=None,
            out_slot="Out", extra_outs=(), name=None, ref=None):
    """Append one op whose main output mirrors the first input."""
    helper = LayerHelper(op_type, name=name)
    ref = ref if ref is not None else next(iter(ins.values()))[0]
    out = helper.create_variable_for_type_inference(
        out_dtype or ref.dtype, shape=out_shape or ref.shape)
    outs = {out_slot: [out]}
    for slot, shape, dtype in extra_outs:
        outs[slot] = [helper.create_variable_for_type_inference(
            dtype or ref.dtype, shape=shape or ref.shape)]
    helper.append_op(op_type, ins, outs, attrs or {})
    return out


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def cos_sim(X, Y, name=None):
    return _simple("cos_sim", {"X": [X], "Y": [Y]},
                   out_shape=(X.shape[0], 1),
                   extra_outs=[("XNorm", (X.shape[0], 1), None),
                               ("YNorm", (Y.shape[0], 1), None)], name=name)


def hinge_loss(input, label, name=None):
    return _simple("hinge_loss", {"Logits": [input], "Labels": [label]},
                   out_slot="Loss", name=name)


def log_loss(input, label, epsilon=1e-4, name=None):
    return _simple("log_loss", {"Predicted": [input], "Labels": [label]},
                   {"epsilon": epsilon}, out_slot="Loss", name=name)


def rank_loss(label, left, right, name=None):
    return _simple("rank_loss",
                   {"Label": [label], "Left": [left], "Right": [right]},
                   ref=left, name=name)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return _simple("margin_rank_loss",
                   {"Label": [label], "X1": [left], "X2": [right]},
                   {"margin": margin}, ref=left,
                   extra_outs=[("Activated", left.shape, None)], name=name)


def modified_huber_loss(input, label, name=None):
    return _simple("modified_huber_loss", {"X": [input], "Y": [label]},
                   extra_outs=[("IntermediateVal", input.shape, None)],
                   name=name)


def squared_l2_distance(x, y, name=None):
    return _simple("squared_l2_distance", {"X": [x], "Y": [y]},
                   out_shape=(x.shape[0], 1),
                   extra_outs=[("sub_result", x.shape, None)], name=name)


def squared_l2_norm(x, name=None):
    return _simple("squared_l2_norm", {"X": [x]}, out_shape=(1,), name=name)


def l1_norm(x, name=None):
    return _simple("l1_norm", {"X": [x]}, out_shape=(1,), name=name)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", bias_attr=bias_attr,
                         act=act, name=name)
    w = helper.create_parameter(
        param_attr, [size, int(x.shape[1]), int(y.shape[1])], x.dtype)
    ins = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [1, size], x.dtype,
                                    is_bias=True)
        ins["Bias"] = [b]
    out = helper.create_variable_for_type_inference(
        x.dtype, shape=(x.shape[0], size))
    helper.append_op("bilinear_tensor_product", ins, {"Out": [out]}, {})
    return helper.append_activation(out)


def minus(x, y, name=None):
    return _simple("minus", {"X": [x], "Y": [y]}, name=name)


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    ins = {"X": [label]}
    if prior_dist is not None:
        ins["PriorDist"] = [prior_dist]
    return _simple("label_smooth", ins, {"epsilon": float(epsilon)},
                   name=name)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None,
              name=None):
    ins = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        ins["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        ins["OutsideWeight"] = [outside_weight]
    return _simple("smooth_l1_loss", ins,
                   {"sigma": sigma if sigma is not None else 1.0},
                   out_shape=(x.shape[0], 1),
                   extra_outs=[("Diff", x.shape, None)], name=name)


def dice_loss(input, label, epsilon=1e-5):
    """Reference nn.py dice_loss: pure composition over existing layers."""
    from . import nn as _nn
    from .ops import square  # generated activation wrappers

    label = _nn.one_hot(label, depth=input.shape[-1]) \
        if label.dtype != input.dtype and int(label.shape[-1]) == 1 \
        else label
    reduce_dims = list(range(1, len(input.shape)))
    inse = _nn.reduce_sum(_nn.elementwise_mul(input, label), dim=reduce_dims)
    dice_denominator = _nn.elementwise_add(
        _nn.reduce_sum(input, dim=reduce_dims),
        _nn.reduce_sum(label, dim=reduce_dims))
    dice_score = _nn.scale(
        _nn.elementwise_div(
            _nn.scale(inse, scale=2.0),
            _nn.scale(dice_denominator, scale=1.0, bias=epsilon)),
        scale=-1.0, bias=1.0)
    return _nn.reduce_mean(dice_score)


# ---------------------------------------------------------------------------
# shape / indexing
# ---------------------------------------------------------------------------

def flatten(x, axis=1, name=None):
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    tail = int(np.prod(x.shape[axis:]))
    return _simple("flatten", {"X": [x]}, {"axis": axis},
                   out_shape=(lead, tail), name=name)


def reverse(x, axis, name=None):
    axis = [axis] if isinstance(axis, int) else list(axis)
    return _simple("reverse", {"X": [x]}, {"axis": axis}, name=name)


def unstack(x, axis=0, num=None, name=None):
    helper = LayerHelper("unstack", name=name)
    num = num if num is not None else x.shape[axis]
    shape = tuple(s for i, s in enumerate(x.shape) if i != axis % len(x.shape))
    outs = [helper.create_variable_for_type_inference(x.dtype, shape=shape)
            for _ in range(num)]
    helper.append_op("unstack", {"X": [x]}, {"Y": outs}, {"axis": axis,
                                                          "num": num})
    return outs


def crop(x, shape=None, offsets=None, name=None):
    if shape is None:
        raise ValueError("crop requires shape (a list/tuple or a Variable "
                         "whose shape is the crop target)")
    ins = {"X": [x]}
    attrs = {}
    if shape is not None and not isinstance(shape, (list, tuple)):
        ins["Y"] = [shape]
        out_shape = shape.shape
    else:
        attrs["shape"] = list(shape)
        out_shape = tuple(shape)
    attrs["offsets"] = list(offsets) if offsets is not None \
        else [0] * len(x.shape)
    return _simple("crop", ins, attrs, out_shape=out_shape, name=name)


def pad(x, paddings, pad_value=0.0, name=None):
    shape = tuple(s + paddings[2 * i] + paddings[2 * i + 1]
                  for i, s in enumerate(x.shape))
    return _simple("pad", {"X": [x]},
                   {"paddings": list(paddings), "pad_value": pad_value},
                   out_shape=shape, name=name)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    p = list(paddings)
    if data_format == "NCHW":
        shape = (input.shape[0], input.shape[1],
                 input.shape[2] + p[0] + p[1], input.shape[3] + p[2] + p[3])
    else:
        shape = (input.shape[0], input.shape[1] + p[0] + p[1],
                 input.shape[2] + p[2] + p[3], input.shape[3])
    return _simple("pad2d", {"X": [input]},
                   {"paddings": p, "mode": mode, "pad_value": pad_value,
                    "data_format": data_format}, out_shape=shape, name=name)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _simple("pad_constant_like", {"X": [x], "Y": [y]},
                   {"pad_value": pad_value}, out_shape=x.shape, ref=y,
                   name=name)


def multiplex(inputs, index, name=None):
    return _simple("multiplex", {"X": list(inputs), "Ids": [index]},
                   ref=inputs[0], name=name)


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=input.shape)
    ids = helper.create_variable_for_type_inference("int64",
                                                    shape=input.shape,
                                                    stop_gradient=True)
    helper.append_op("argsort", {"X": [input]},
                     {"Out": [out], "Indices": [ids]}, {"axis": axis})
    return out, ids


def shape(input, name=None):
    return _simple("shape", {"Input": [input]},
                   out_shape=(len(input.shape),), out_dtype="int64",
                   name=name)


def scatter(input, index, updates, name=None, overwrite=True):
    return _simple("scatter",
                   {"X": [input], "Ids": [index], "Updates": [updates]},
                   {"overwrite": overwrite}, name=name)


def sequence_scatter(input, index, updates, name=None):
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    sl = seq_len_var(index)
    if sl is not None:
        ins["SeqLen"] = [sl]
    return _simple("sequence_scatter", ins, name=name)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    if maxlen is None:
        raise ValueError(
            "sequence_mask on TPU needs a static maxlen (a dynamic "
            "max-length would make the output shape data-dependent)")
    return _simple("sequence_mask", {"X": [x]},
                   {"maxlen": int(maxlen), "out_dtype": dtype},
                   out_shape=tuple(x.shape) + (int(maxlen),),
                   out_dtype=dtype, out_slot="Y", name=name)


def lod_reset(x, y=None, target_lod=None):
    """Reference nn.py lod_reset on the padded contract: data unchanged,
    the @LEN companion becomes y's lengths / the target lengths."""
    helper = LayerHelper("lod_reset")
    ins = {"X": [x]}
    attrs = {}
    if y is not None:
        sl = seq_len_var(y)
        if sl is not None:
            ins["TargetLenTensor"] = [sl]
        else:
            ins["Y"] = [y]
    elif target_lod is not None:
        attrs["target_lod"] = list(target_lod)
    else:
        raise ValueError("lod_reset needs y or target_lod")
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    new_len = helper.create_variable_for_type_inference(
        "int64", shape=(x.shape[0],), stop_gradient=True)
    helper.append_op("lod_reset", ins, {"Out": [out], "OutLen": [new_len]},
                     attrs)
    _alias_len(out, new_len)
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    fs, st = _pair(filter_size), _pair(stride)
    pd = list(padding) if isinstance(padding, (list, tuple)) \
        else [padding] * 4
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    n, c, h, w = input.shape
    oh = (h + pd[0] + pd[2] - fs[0]) // st[0] + 1
    ow = (w + pd[1] + pd[3] - fs[1]) // st[1] + 1
    return _seq_op_with_len(
        "im2sequence", input, {}, {"kernels": list(fs), "strides": list(st),
                                   "paddings": pd},
        (n, oh * ow, c * fs[0] * fs[1]), input.dtype)


# ---------------------------------------------------------------------------
# image ops
# ---------------------------------------------------------------------------

def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [int(x.shape[1])]
    else:
        alpha_shape = [int(np.prod(x.shape[1:]))]
    alpha = helper.create_parameter(
        param_attr, alpha_shape, x.dtype,
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op("prelu", {"X": [x], "Alpha": [alpha]}, {"Out": [out]},
                     {"mode": mode})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    return _simple("affine_channel",
                   {"X": [x], "Scale": [scale], "Bias": [bias]},
                   {"data_layout": data_layout}, name=name)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    return _simple("lrn", {"X": [input]},
                   {"n": n, "k": k, "alpha": alpha, "beta": beta},
                   extra_outs=[("MidOut", input.shape, None)], name=name)


def maxout(x, groups, name=None):
    n, c, h, w = x.shape
    return _simple("maxout", {"X": [x]}, {"groups": groups},
                   out_shape=(n, c // groups, h, w), name=name)


def bilinear_interp(input, out_h, out_w, name=None):
    n, c = input.shape[0], input.shape[1]
    return _simple("bilinear_interp", {"X": [input]},
                   {"out_h": int(out_h), "out_w": int(out_w)},
                   out_shape=(n, c, int(out_h), int(out_w)), name=name)


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR"):
    if resample != "BILINEAR":
        raise ValueError("image_resize supports BILINEAR (reference parity)")
    if out_shape is not None:
        oh, ow = int(out_shape[0]), int(out_shape[1])
    else:
        oh = int(input.shape[2] * scale)
        ow = int(input.shape[3] * scale)
    return bilinear_interp(input, oh, ow, name=name)


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, "BILINEAR")


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    oh = int(round(h * out_short_len / short))
    ow = int(round(w * out_short_len / short))
    return image_resize(input, [oh, ow], resample=resample)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    n_rois = rois.shape[0]
    c = input.shape[1]
    return _simple("roi_pool", {"X": [input], "ROIs": [rois]},
                   {"pooled_height": pooled_height,
                    "pooled_width": pooled_width,
                    "spatial_scale": spatial_scale},
                   out_shape=(n_rois, c, pooled_height, pooled_width))


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    lead = len(x.shape) - len(shape)
    out_shape = tuple(x.shape[:lead]) + tuple(shape)
    out = helper.create_variable_for_type_inference(x.dtype, shape=out_shape)
    seed_out = helper.create_variable_for_type_inference(
        "int64", shape=(1,), stop_gradient=True)
    helper.append_op("random_crop", {"X": [x]},
                     {"Out": [out], "SeedOut": [seed_out]},
                     {"shape": list(shape), "seed": seed or 0})
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32", shape=())
    wrong = helper.create_variable_for_type_inference(
        "int32", shape=(num_classes,))
    correct = helper.create_variable_for_type_inference(
        "int32", shape=(num_classes,))
    helper.append_op("mean_iou",
                     {"Predictions": [input], "Labels": [label]},
                     {"OutMeanIou": [miou], "OutWrong": [wrong],
                      "OutCorrect": [correct]},
                     {"num_classes": num_classes})
    return miou, wrong, correct


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    helper = LayerHelper("chunk_eval")
    mk = lambda dt, sh: helper.create_variable_for_type_inference(
        dt, shape=sh, stop_gradient=True)
    precision, recall, f1 = mk("float32", (1,)), mk("float32", (1,)), \
        mk("float32", (1,))
    n_inf, n_lab, n_cor = mk("int64", (1,)), mk("int64", (1,)), \
        mk("int64", (1,))
    ins = {"Inference": [input], "Label": [label]}
    sl = seq_len_var(input) or seq_len_var(label)
    if sl is not None:
        ins["SeqLen"] = [sl]
    helper.append_op(
        "chunk_eval", ins,
        {"Precision": [precision], "Recall": [recall], "F1-Score": [f1],
         "NumInferChunks": [n_inf], "NumLabelChunks": [n_lab],
         "NumCorrectChunks": [n_cor]},
        {"chunk_scheme": chunk_scheme, "num_chunk_types": num_chunk_types,
         "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1, n_inf, n_lab, n_cor


# ---------------------------------------------------------------------------
# RNN unit cells
# ---------------------------------------------------------------------------

def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """Reference nn.py gru_unit: size = 3*hidden_dim; returns
    (hidden, reset_hidden_prev, gate)."""
    helper = LayerHelper("gru_unit")
    act_ids = {"identity": 0, "sigmoid": 1, "tanh": 2, "relu": 3}
    d = size // 3
    w = helper.create_parameter(param_attr, [d, 3 * d], input.dtype)
    ins = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [1, 3 * d], input.dtype,
                                    is_bias=True)
        ins["Bias"] = [b]
    B = input.shape[0]
    gate = helper.create_variable_for_type_inference(input.dtype,
                                                     shape=(B, 3 * d))
    rhp = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=(B, d))
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=(B, d))
    helper.append_op(
        "gru_unit", ins,
        {"Gate": [gate], "ResetHiddenPrev": [rhp], "Hidden": [out]},
        {"activation": act_ids[activation],
         "gate_activation": act_ids[gate_activation]})
    return out, rhp, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Reference nn.py lstm_unit: fc([x_t, h_prev]) -> 4H gates -> cell
    step (composition + the lstm_unit op)."""
    from . import nn as _nn

    helper = LayerHelper("lstm_unit", name=name)
    size = int(cell_t_prev.shape[1])
    concat = _nn.concat([x_t, hidden_t_prev], axis=1)
    fc_out = _nn.fc(concat, 4 * size, param_attr=param_attr,
                    bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype,
                                                  shape=cell_t_prev.shape)
    h = helper.create_variable_for_type_inference(x_t.dtype,
                                                  shape=cell_t_prev.shape)
    helper.append_op("lstm_unit",
                     {"X": [fc_out], "C_prev": [cell_t_prev]},
                     {"C": [c], "H": [h]}, {"forget_bias": forget_bias})
    return h, c


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="identity",
                  dtype="float32", name=None):
    """Reference nn.py dynamic_lstmp (lstmp_op): LSTM with recurrent
    projection.  ``input`` is the [B,T,4H] x-projection (as with
    dynamic_lstm); returns (projection [B,T,P], cell [B,T,H])."""
    if use_peepholes:
        raise ValueError(
            "dynamic_lstmp: peephole connections are not ported (the "
            "reference book configs use use_peepholes=False)")
    helper = LayerHelper("lstmp", name=name)
    H = size // 4
    w = helper.create_parameter(param_attr, [proj_size, 4 * H], dtype)
    wproj = helper.create_parameter(param_attr, [H, proj_size], dtype)
    bias = helper.create_parameter(bias_attr, [1, 4 * H], dtype,
                                   is_bias=True)
    from . import nn as _nn
    gates = _nn.elementwise_add(input, bias)
    B, T = input.shape[0], input.shape[1]
    proj = helper.create_variable_for_type_inference(dtype,
                                                     shape=(B, T, proj_size))
    cell = helper.create_variable_for_type_inference(dtype, shape=(B, T, H))
    last_h = helper.create_variable_for_type_inference(dtype,
                                                       shape=(B, proj_size))
    last_c = helper.create_variable_for_type_inference(dtype, shape=(B, H))
    ins = {"Input": [gates], "Weight": [w], "ProjWeight": [wproj]}
    sl = seq_len_var(input)
    if sl is not None:
        ins["SeqLen"] = [sl]
    helper.append_op(
        "lstmp", ins,
        {"Projection": [proj], "Cell": [cell], "LastH": [last_h],
         "LastC": [last_c]},
        {"is_reverse": is_reverse, "proj_activation": proj_activation})
    if sl is not None:
        _alias_len(proj, sl)
        _alias_len(cell, sl)
    return proj, cell


# ---------------------------------------------------------------------------
# 3-D conv family
# ---------------------------------------------------------------------------

def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper("conv3d", bias_attr=bias_attr, act=act, name=name)
    groups = groups or 1
    fs, st = _triple(filter_size), _triple(stride)
    pd, dl = _triple(padding), _triple(dilation)
    c = input.shape[1]
    std = (2.0 / (np.prod(fs) * c)) ** 0.5
    w = helper.create_parameter(
        param_attr, [num_filters, c // groups] + list(fs), input.dtype,
        default_initializer=NormalInitializer(0.0, std))
    dims = [(input.shape[2 + i] + 2 * pd[i] - (dl[i] * (fs[i] - 1) + 1))
            // st[i] + 1 for i in range(3)]
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=(input.shape[0], num_filters) + tuple(dims))
    helper.append_op("conv3d", {"Input": [input], "Filter": [w]},
                     {"Output": [out]},
                     {"strides": list(st), "paddings": list(pd),
                      "dilations": list(dl), "groups": groups})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool3d", name=name)
    ks, st = _triple(pool_size), _triple(pool_stride)
    pd = _triple(pool_padding)
    if global_pooling:
        dims = (1, 1, 1)
    else:
        dims = tuple((input.shape[2 + i] + 2 * pd[i] - ks[i]) // st[i] + 1
                     for i in range(3))
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=(input.shape[0], input.shape[1]) + dims)
    helper.append_op("pool3d", {"X": [input]}, {"Out": [out]},
                     {"pooling_type": pool_type, "ksize": list(ks),
                      "strides": list(st), "paddings": list(pd),
                      "global_pooling": global_pooling,
                      "exclusive": exclusive})
    return out


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=None, param_attr=None,
                     bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d_transpose", bias_attr=bias_attr, act=act,
                         name=name)
    groups = groups or 1
    fs, st = _triple(filter_size), _triple(stride)
    pd, dl = _triple(padding), _triple(dilation)
    c = input.shape[1]
    w = helper.create_parameter(
        param_attr, [c, num_filters // groups] + list(fs), input.dtype)
    dims = [(input.shape[2 + i] - 1) * st[i] - 2 * pd[i]
            + dl[i] * (fs[i] - 1) + 1 for i in range(3)]
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=(input.shape[0], num_filters) + tuple(dims))
    helper.append_op("conv3d_transpose",
                     {"Input": [input], "Filter": [w]}, {"Output": [out]},
                     {"strides": list(st), "paddings": list(pd),
                      "dilations": list(dl), "groups": groups})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


# ---------------------------------------------------------------------------
# candidate sampling / random
# ---------------------------------------------------------------------------

def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None):
    helper = LayerHelper("nce", name=name)
    dim = int(input.shape[1])
    num_neg = num_neg_samples if num_neg_samples is not None else 10
    w = helper.create_parameter(param_attr, [num_total_classes, dim],
                                input.dtype)
    ins = {"Input": [input], "Label": [label], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_total_classes, 1],
                                    input.dtype, is_bias=True)
        ins["Bias"] = [b]
    if sample_weight is not None:
        ins["SampleWeight"] = [sample_weight]
    B = input.shape[0]
    num_true = int(label.shape[1]) if len(label.shape) > 1 else 1
    cost = helper.create_variable_for_type_inference(input.dtype,
                                                     shape=(B, 1))
    logits = helper.create_variable_for_type_inference(
        input.dtype, shape=(B, num_true + num_neg))
    labels = helper.create_variable_for_type_inference(
        "int64", shape=(B, num_true + num_neg), stop_gradient=True)
    helper.append_op("nce", ins,
                     {"Cost": [cost], "SampleLogits": [logits],
                      "SampleLabels": [labels]},
                     {"num_total_classes": num_total_classes,
                      "num_neg_samples": num_neg})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    helper = LayerHelper("hierarchical_sigmoid", name=name)
    dim = int(input.shape[1])
    w = helper.create_parameter(param_attr, [num_classes - 1, dim],
                                input.dtype)
    ins = {"X": [input], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [1, num_classes - 1],
                                    input.dtype, is_bias=True)
        ins["Bias"] = [b]
    B = input.shape[0]
    L = max(int(np.ceil(np.log2(num_classes))) + 1, 1)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=(B, 1))
    pre = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=(B, L))
    helper.append_op("hierarchical_sigmoid", ins,
                     {"Out": [out], "PreOut": [pre]},
                     {"num_classes": num_classes})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(
        "int64", shape=(x.shape[0],), stop_gradient=True)
    helper.append_op("sampling_id", {"X": [x]}, {"Out": [out]},
                     {"seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype,
                                                    shape=tuple(shape))
    helper.append_op("gaussian_random", {}, {"Out": [out]},
                     {"shape": list(shape), "mean": mean, "std": std,
                      "seed": seed, "dtype": dtype})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out_shape = list(shape)
    out_shape[output_dim_idx] = input.shape[input_dim_idx]
    out = helper.create_variable_for_type_inference(dtype,
                                                    shape=tuple(out_shape))
    helper.append_op("uniform_random_batch_size_like", {"Input": [input]},
                     {"Out": [out]},
                     {"shape": list(shape), "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx, "min": min,
                      "max": max, "seed": seed, "dtype": dtype})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out_shape = list(shape)
    out_shape[output_dim_idx] = input.shape[input_dim_idx]
    out = helper.create_variable_for_type_inference(dtype,
                                                    shape=tuple(out_shape))
    helper.append_op("gaussian_random_batch_size_like", {"Input": [input]},
                     {"Out": [out]},
                     {"shape": list(shape), "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx, "mean": mean,
                      "std": std, "seed": seed, "dtype": dtype})
    return out


# -- comparison / logical / guard surface (reference layers/control_flow.py
# equal + layers/ops auto-gen logical family + isfinite_op family) --------

def equal(x, y, cond=None, name=None):
    """Elementwise x == y (reference control_flow.py equal)."""
    return _cmp_layer("equal", x, y, cond, name)


def not_equal(x, y, cond=None, name=None):
    return _cmp_layer("not_equal", x, y, cond, name)


def less_equal(x, y, cond=None, name=None):
    return _cmp_layer("less_equal", x, y, cond, name)


def greater_than(x, y, cond=None, name=None):
    return _cmp_layer("greater_than", x, y, cond, name)


def logical_and(x, y, out=None, name=None):
    return _cmp_layer("logical_and", x, y, out, name)


def logical_or(x, y, out=None, name=None):
    return _cmp_layer("logical_or", x, y, out, name)


def logical_xor(x, y, out=None, name=None):
    return _cmp_layer("logical_xor", x, y, out, name)


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference("bool",
                                                        shape=x.shape)
    helper.append_op("logical_not", {"X": [x]}, {"Out": [out]})
    return out


def is_empty(x, cond=None, name=None):
    """[1]-shaped bool: does x have zero elements (is_empty_op.cc —
    the op emits a 1-element array, matching the reference's [1]
    output)."""
    helper = LayerHelper("is_empty", name=name)
    if cond is None:
        cond = helper.create_variable_for_type_inference(
            "bool", shape=(1,), stop_gradient=True)
    helper.append_op("is_empty", {"X": [x]}, {"Out": [cond]})
    return cond


def isfinite(x, name=None):
    """Scalar bool: every element finite (isfinite_op.cc)."""
    helper = LayerHelper("isfinite", name=name)
    out = helper.create_variable_for_type_inference(
        "bool", shape=(), stop_gradient=True)
    helper.append_op("isfinite", {"X": [x]}, {"Out": [out]})
    return out


def has_inf(x, name=None):
    """Scalar bool: any element infinite (overflow-guard family)."""
    helper = LayerHelper("has_inf", name=name)
    out = helper.create_variable_for_type_inference(
        "bool", shape=(), stop_gradient=True)
    helper.append_op("has_inf", {"X": [x]}, {"Out": [out]})
    return out


def has_nan(x, name=None):
    helper = LayerHelper("has_nan", name=name)
    out = helper.create_variable_for_type_inference(
        "bool", shape=(), stop_gradient=True)
    helper.append_op("has_nan", {"X": [x]}, {"Out": [out]})
    return out


def sum(x, name=None):  # noqa: A001 — reference layer name
    """Sum a LIST of same-shaped tensors (sum_op.cc; the reference
    fluid.layers.sum — delegates to sums(), which also propagates the
    sequence-length alias).  For one tensor's reduction use
    ``reduce_sum``."""
    from .nn import sums

    return sums(list(x) if isinstance(x, (list, tuple)) else [x])


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug-print a tensor each execution and pass it through
    (reference control_flow.py Print / print_op.cc; lowers to
    jax.debug.print — the formatting knobs are accepted for API parity,
    the printed payload is the runtime array)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=input.shape)
    helper.append_op("print", {"In": [input]}, {"Out": [out]},
                     {"message": message or "",
                      "first_n": first_n, "summarize": summarize,
                      "print_phase": print_phase})
    if seq_len_var(input) is not None:  # identity op: keep the length
        _alias_len(out, seq_len_var(input))
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int64 step counter incremented once per run
    (reference layers/nn.py autoincreased_step_counter — the global-step
    the LR schedulers consume)."""
    from .learning_rate_scheduler import _step_counter

    return _step_counter(counter_name or "@STEP_COUNTER@",
                         begin=begin, step=step)


def append_LARS(params_grads, learning_rate, weight_decay):
    """Layer-wise Adaptive Rate Scaling: per-param LR =
    lr * ||param|| / (||grad|| + weight_decay * ||param||)
    (reference layers/nn.py append_LARS)."""
    from .nn import elementwise_add, elementwise_div, elementwise_mul
    from .nn import scale as _scale

    def _norm(v):
        helper = LayerHelper("l2_norm")
        out = helper.create_variable_for_type_inference(v.dtype, shape=())
        helper.append_op("squared_l2_norm", {"X": [v]}, {"Out": [out]})
        return sqrt_layer(out)

    def sqrt_layer(v):
        helper = LayerHelper("sqrt")
        out = helper.create_variable_for_type_inference(v.dtype,
                                                        shape=v.shape)
        helper.append_op("sqrt", {"X": [v]}, {"Out": [out]})
        return out

    decayed = []
    for param, grad in params_grads:
        p_norm = _norm(param)
        g_norm = _norm(grad)
        denom = elementwise_add(g_norm,
                                _scale(p_norm, scale=float(weight_decay)))
        ratio = elementwise_div(p_norm, denom)
        decayed.append(elementwise_mul(ratio, learning_rate, axis=0))
    return decayed


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    """Cumulative sum along ``axis`` (cum_op.cc)."""
    helper = LayerHelper("cumsum", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op("cumsum", {"X": [x]}, {"Out": [out]},
                     {"axis": axis, "exclusive": exclusive,
                      "reverse": reverse})
    return out
