"""Detection layers over the detection op subset (reference
python/paddle/fluid/layers/detection.py)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "multiclass_nms",
           "bipartite_match"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    H, W = input.shape[2], input.shape[3]
    ars = list(aspect_ratios)
    n_ar = 1 + sum(2 if flip and abs(a - 1.0) > 1e-6 else
                   (0 if abs(a - 1.0) < 1e-6 else 1) for a in ars)
    P = len(min_sizes) * n_ar + len(max_sizes or [])
    boxes = helper.create_variable_for_type_inference(
        "float32", shape=(H, W, P, 4), stop_gradient=True)
    var = helper.create_variable_for_type_inference(
        "float32", shape=(H, W, P, 4), stop_gradient=True)
    helper.append_op(
        "prior_box", {"Input": [input], "Image": [image]},
        {"Boxes": [boxes], "Variances": [var]},
        {"min_sizes": list(min_sizes), "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios), "variances": list(variance),
         "flip": flip, "clip": clip, "step_w": steps[0], "step_h": steps[1],
         "offset": offset})
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    if "encode" in code_type:
        shape = (target_box.shape[0], prior_box.shape[0], 4)
    else:
        shape = tuple(target_box.shape)
    out = helper.create_variable_for_type_inference("float32", shape=shape)
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = [prior_box_var]
    helper.append_op("box_coder", ins, {"OutputBox": [out]},
                     {"code_type": code_type,
                      "box_normalized": box_normalized})
    return out


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", shape=(x.shape[0], y.shape[0]))
    helper.append_op("iou_similarity", {"X": [x], "Y": [y]}, {"Out": [out]})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", name=name)
    m = dist_matrix.shape[1]
    idx = helper.create_variable_for_type_inference(
        "int32", shape=(1, m), stop_gradient=True)
    dist = helper.create_variable_for_type_inference(
        "float32", shape=(1, m), stop_gradient=True)
    helper.append_op(
        "bipartite_match", {"DistMat": [dist_matrix]},
        {"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [dist]},
        {"match_type": match_type or "bipartite",
         "dist_threshold": dist_threshold or 0.5})
    return idx, dist


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, background_label=0,
                   name=None):
    """Returns (Out [B, keep_top_k, 6], valid counts [B])."""
    helper = LayerHelper("multiclass_nms", name=name)
    B = bboxes.shape[0]
    out = helper.create_variable_for_type_inference(
        "float32", shape=(B, keep_top_k, 6), stop_gradient=True)
    num = helper.create_variable_for_type_inference(
        "int64", shape=(B,), stop_gradient=True)
    helper.append_op(
        "multiclass_nms", {"BBoxes": [bboxes], "Scores": [scores]},
        {"Out": [out], "NmsRoisNum": [num]},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "background_label": background_label})
    return out, num
