"""Detection layers over the detection op subset (reference
python/paddle/fluid/layers/detection.py)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "multiclass_nms",
           "bipartite_match"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    H, W = input.shape[2], input.shape[3]
    ars = list(aspect_ratios)
    n_ar = 1 + sum(2 if flip and abs(a - 1.0) > 1e-6 else
                   (0 if abs(a - 1.0) < 1e-6 else 1) for a in ars)
    P = len(min_sizes) * n_ar + len(max_sizes or [])
    boxes = helper.create_variable_for_type_inference(
        "float32", shape=(H, W, P, 4), stop_gradient=True)
    var = helper.create_variable_for_type_inference(
        "float32", shape=(H, W, P, 4), stop_gradient=True)
    helper.append_op(
        "prior_box", {"Input": [input], "Image": [image]},
        {"Boxes": [boxes], "Variances": [var]},
        {"min_sizes": list(min_sizes), "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios), "variances": list(variance),
         "flip": flip, "clip": clip, "step_w": steps[0], "step_h": steps[1],
         "offset": offset})
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    if "encode" in code_type:
        shape = (target_box.shape[0], prior_box.shape[0], 4)
    else:
        shape = tuple(target_box.shape)
    out = helper.create_variable_for_type_inference("float32", shape=shape)
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = [prior_box_var]
    helper.append_op("box_coder", ins, {"OutputBox": [out]},
                     {"code_type": code_type,
                      "box_normalized": box_normalized})
    return out


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", shape=(x.shape[0], y.shape[0]))
    helper.append_op("iou_similarity", {"X": [x], "Y": [y]}, {"Out": [out]})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", name=name)
    m = dist_matrix.shape[1]
    idx = helper.create_variable_for_type_inference(
        "int32", shape=(1, m), stop_gradient=True)
    dist = helper.create_variable_for_type_inference(
        "float32", shape=(1, m), stop_gradient=True)
    helper.append_op(
        "bipartite_match", {"DistMat": [dist_matrix]},
        {"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [dist]},
        {"match_type": match_type or "bipartite",
         "dist_threshold": dist_threshold or 0.5})
    return idx, dist


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, background_label=0,
                   name=None):
    """Returns (Out [B, keep_top_k, 6], valid counts [B])."""
    helper = LayerHelper("multiclass_nms", name=name)
    B = bboxes.shape[0]
    out = helper.create_variable_for_type_inference(
        "float32", shape=(B, keep_top_k, 6), stop_gradient=True)
    num = helper.create_variable_for_type_inference(
        "int64", shape=(B,), stop_gradient=True)
    helper.append_op(
        "multiclass_nms", {"BBoxes": [bboxes], "Scores": [scores]},
        {"Out": [out], "NmsRoisNum": [num]},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "background_label": background_label})
    return out, num


__all__ += ["anchor_generator", "polygon_box_transform", "target_assign",
            "mine_hard_examples", "rpn_target_assign", "ssd_loss",
            "detection_output", "multi_box_head", "detection_map"]


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    """Faster-RCNN anchors (reference detection.py anchor_generator)."""
    helper = LayerHelper("anchor_generator", name=name)
    H, W = input.shape[2], input.shape[3]
    A = len(anchor_sizes) * len(aspect_ratios)
    anchors = helper.create_variable_for_type_inference(
        "float32", shape=(H, W, A, 4), stop_gradient=True)
    variances = helper.create_variable_for_type_inference(
        "float32", shape=(H, W, A, 4), stop_gradient=True)
    helper.append_op(
        "anchor_generator", {"Input": [input]},
        {"Anchors": [anchors], "Variances": [variances]},
        {"anchor_sizes": list(anchor_sizes),
         "aspect_ratios": list(aspect_ratios),
         "variances": list(variance), "stride": list(stride),
         "offset": offset})
    return anchors, variances


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=input.shape)
    helper.append_op("polygon_box_transform", {"Input": [input]},
                     {"Output": [out]}, {})
    return out


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """Assign per-prediction targets from matched entity rows
    (reference detection.py target_assign; padded [B, M, K] input)."""
    helper = LayerHelper("target_assign", name=name)
    B, P = matched_indices.shape[0], matched_indices.shape[1]
    K = input.shape[-1]
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=(B, P, K))
    out_weight = helper.create_variable_for_type_inference(
        "float32", shape=(B, P, 1))
    ins = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        ins["NegIndices"] = [negative_indices]
    helper.append_op("target_assign", ins,
                     {"Out": [out], "OutWeight": [out_weight]},
                     {"mismatch_value": mismatch_value or 0})
    return out, out_weight


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=1.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=None,
                       name=None):
    helper = LayerHelper("mine_hard_examples", name=name)
    B, P = match_indices.shape[0], match_indices.shape[1]
    neg = helper.create_variable_for_type_inference(
        "int64", shape=(B, P), stop_gradient=True)
    upd = helper.create_variable_for_type_inference(
        "int32", shape=(B, P), stop_gradient=True)
    ins = {"ClsLoss": [cls_loss], "MatchIndices": [match_indices],
           "MatchDist": [match_dist]}
    if loc_loss is not None:
        ins["LocLoss"] = [loc_loss]
    helper.append_op("mine_hard_examples", ins,
                     {"NegIndices": [neg], "UpdatedMatchIndices": [upd]},
                     {"neg_pos_ratio": neg_pos_ratio,
                      "neg_dist_threshold": neg_dist_threshold,
                      "mining_type": mining_type})
    return neg, upd


def rpn_target_assign(loc, scores, anchor_box, gt_box,
                      rpn_batch_size_per_im=256, fg_fraction=0.25,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      name=None):
    """RPN anchor labeling (reference detection.py rpn_target_assign;
    deterministic cap instead of random subsampling — see the op)."""
    from . import nn as _layers
    from .detection import iou_similarity as _iou

    helper = LayerHelper("rpn_target_assign", name=name)
    iou = _iou(anchor_box, gt_box)
    fg = int(rpn_batch_size_per_im * fg_fraction)
    loc_idx = helper.create_variable_for_type_inference(
        "int64", shape=(fg,), stop_gradient=True)
    score_idx = helper.create_variable_for_type_inference(
        "int64", shape=(rpn_batch_size_per_im,), stop_gradient=True)
    tgt_lbl = helper.create_variable_for_type_inference(
        "int64", shape=(rpn_batch_size_per_im,), stop_gradient=True)
    anchor_gt = helper.create_variable_for_type_inference(
        "int64", shape=(anchor_box.shape[0],), stop_gradient=True)
    helper.append_op(
        "rpn_target_assign", {"DistMat": [iou]},
        {"LocationIndex": [loc_idx], "ScoreIndex": [score_idx],
         "TargetLabel": [tgt_lbl], "TargetAnchorGt": [anchor_gt]},
        {"rpn_batch_size_per_im": rpn_batch_size_per_im,
         "rpn_fg_fraction": fg_fraction,
         "rpn_positive_overlap": rpn_positive_overlap,
         "rpn_negative_overlap": rpn_negative_overlap})
    return loc_idx, score_idx, tgt_lbl, anchor_gt


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             name=None):
    """SSD multibox loss (reference detection.py ssd_loss) as one fused
    op; gt_box/gt_label are padded [B, Mg, ...] with @LEN lengths."""
    from .nn import seq_len_var

    if mining_type != "max_negative":
        raise ValueError("Only mining_type == 'max_negative' is supported "
                         "(reference parity)")
    helper = LayerHelper("ssd_loss", name=name)
    B, P = location.shape[0], location.shape[1]
    loss = helper.create_variable_for_type_inference(
        "float32", shape=(B, P))
    ins = {"Loc": [location], "Conf": [confidence], "GtBox": [gt_box],
           "GtLabel": [gt_label], "PriorBox": [prior_box]}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = [prior_box_var]
    sl = seq_len_var(gt_box) or seq_len_var(gt_label)
    if sl is not None:
        ins["GtLen"] = [sl]
    helper.append_op(
        "ssd_loss", ins, {"Loss": [loss]},
        {"background_label": background_label,
         "overlap_threshold": overlap_threshold,
         "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
         "loc_loss_weight": loc_loss_weight,
         "conf_loss_weight": conf_loss_weight, "normalize": normalize})
    return loss


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode predictions + multiclass NMS (reference detection.py
    detection_output = box_coder + transpose + multiclass_nms)."""
    from . import nn as _nn
    from .detection import box_coder as _box_coder
    from .detection import multiclass_nms as _nms

    decoded = _box_coder(prior_box, prior_box_var, loc,
                         code_type="decode_center_size")
    scores_t = _nn.transpose(scores, perm=[0, 2, 1])  # [B, C, P]
    return _nms(decoded, scores_t, score_threshold=score_threshold,
                nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                nms_threshold=nms_threshold,
                background_label=background_label)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """SSD prediction head (reference detection.py multi_box_head): per
    feature map, conv loc/conf predictions + prior boxes, concatenated."""
    from . import nn as _nn
    from .detection import prior_box as _prior_box

    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio schedule: evenly spaced between min/max ratio
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_layer - 2))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes, variances = [], [], [], []
    for i, x in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        step_sz = ([steps[i], steps[i]] if steps
                   else [step_w[i] if step_w else 0.0,
                         step_h[i] if step_h else 0.0])
        box, var = _prior_box(
            x, image, [mins] if not isinstance(mins, list) else mins,
            [maxs] if maxs and not isinstance(maxs, list) else maxs,
            list(ar) if isinstance(ar, (list, tuple)) else [ar],
            variance=list(variance), flip=flip, clip=clip,
            steps=step_sz, offset=offset)
        box = _nn.reshape(box, [-1, 4])
        var = _nn.reshape(var, [-1, 4])
        num_boxes = box.shape[0]
        loc = _nn.conv2d(x, num_boxes // (x.shape[2] * x.shape[3]) * 4,
                         kernel_size, stride, pad)
        loc = _nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = _nn.reshape(loc, [loc.shape[0], -1, 4])
        conf = _nn.conv2d(
            x, num_boxes // (x.shape[2] * x.shape[3]) * num_classes,
            kernel_size, stride, pad)
        conf = _nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = _nn.reshape(conf, [conf.shape[0], -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes.append(box)
        variances.append(var)

    mbox_locs = _nn.concat(locs, axis=1)
    mbox_confs = _nn.concat(confs, axis=1)
    boxes_cat = _nn.concat(boxes, axis=0)
    vars_cat = _nn.concat(variances, axis=0)
    return mbox_locs, mbox_confs, boxes_cat, vars_cat


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral", name=None):
    """Mean average precision metric (detection_map_op.cc) — IN-GRAPH
    device op: per-class AP over NMS outputs [B, K, 6] vs padded gt
    [B, Mg, 6] = (label, x1, y1, x2, y2, difficult).  For accumulative
    mAP across batches, append the op directly with PosCount/TruePos/
    FalsePos state slots (ops/detection_ops.py docstring)."""
    from .nn import seq_len_var

    helper = LayerHelper("detection_map", name=name)
    m = helper.create_variable_for_type_inference(
        "float32", shape=(1,), stop_gradient=True)
    ins = {"DetectRes": [detect_res], "Label": [label]}
    sl = seq_len_var(label)
    if sl is not None:
        ins["GtLen"] = [sl]
    helper.append_op("detection_map", ins, {"MAP": [m]},
                     {"class_num": class_num,
                      "background_label": background_label,
                      "overlap_threshold": overlap_threshold,
                      "evaluate_difficult": evaluate_difficult,
                      "ap_version": ap_version})
    return m
