"""DataFeeder: python minibatches → feed dict of dense arrays.

Reference: ``python/paddle/fluid/data_feeder.py:83`` — converts lists of
per-example tuples into per-place LoDTensor batches.  Here a variable-length
(``lod_level=1``) feed becomes a padded ``[B, T, ...]`` array plus the
``<name>@LEN`` int32 lengths vector (the padded-sequence contract; see
layers/nn.py).  Padding T to a bucket boundary keeps XLA recompiles bounded.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .core.program import Variable, default_main_program
from .core.types import np_dtype

# pad sequence length up to the next multiple (recompile-bucketing policy)
SEQ_LEN_BUCKET = 16


def _bucket(n: int, bucket: int = SEQ_LEN_BUCKET) -> int:
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        self.program = program or default_main_program()
        self.feed_vars: List[Variable] = [
            v if isinstance(v, Variable) else self.program.global_block.var(v)
            for v in feed_list
        ]
        self.place = place

    def feed(self, iterable) -> dict:
        """iterable: list of per-example tuples aligned with feed_list."""
        rows = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_vars):
            col = [row[i] for row in rows]
            if var.lod_level >= 2:
                data, outer, inner = self._pad_nested(col, var)
                out[var.name] = data
                out[var.name + "@LEN"] = outer
                out[var.name + "@LEN2"] = inner
            elif var.lod_level == 1:
                data, lens = self._pad(col, var)
                out[var.name] = data
                out[var.name + "@LEN"] = lens
            else:
                arr = np.asarray(col)
                arr = arr.astype(np_dtype(var.dtype), copy=False)
                want = var.shape
                if want is not None and len(want) == arr.ndim + 1 and want[-1] == 1:
                    arr = arr[..., None]  # reference-style trailing label dim
                out[var.name] = arr
        return out

    def _pad_nested(self, col, var: Variable):
        """Level-2 feed: each example is a list of sentences, each
        sentence a list/array of word rows.  Produces the nested padded
        contract ([B,S,W,...] + @LEN outer [B] + @LEN2 inner [B,S]) via
        the same builder create_lod_tensor uses, so DataFeeder and the
        LoDTensor feed path stay bit-identical."""
        from .lod_tensor import _create_nested

        if var.lod_level > 2:
            raise NotImplementedError(
                "DataFeeder supports lod_level <= 2 (the nested padded "
                "contract; see lod_tensor.py)")
        outer = [len(ex) for ex in col]
        flat = [np.asarray(s) for ex in col for s in ex]
        inner = [len(s) for s in flat]
        # zero-word sentences are legal (they pool to 0 downstream); give
        # them the word-row feature shape so concatenation lines up.
        # When EVERY sentence in the batch is empty, derive the feature
        # shape from the declared var shape ([B, S, W, ...feat]) instead
        # of degrading to (0,)-shaped features (ADVICE r5)
        feat = next((s.shape[1:] for s in flat if len(s)), None)
        if feat is None:
            shp = var.shape
            feat = (tuple(int(d) for d in shp[3:])
                    if shp is not None and len(shp) > 3 else ())
        flat = [s if len(s) else np.zeros((0,) + feat) for s in flat]
        lt = _create_nested(flat, [outer, inner])
        data = lt.data.astype(np_dtype(var.dtype), copy=False)
        want = var.shape
        if want is not None and len(want) == data.ndim + 1 and want[-1] == 1:
            data = data[..., None]  # reference-style trailing word dim
        return (data, lt.seq_lens.astype(np.int64),
                lt.inner_lens.astype(np.int64))

    def _pad(self, col, var: Variable):
        seqs = [np.asarray(s) for s in col]
        lens = np.asarray([len(s) for s in seqs], dtype=np.int32)
        T = _bucket(int(lens.max()) if len(lens) else 1)
        feat = seqs[0].shape[1:] if seqs[0].ndim > 1 else ()
        want_feat = tuple(var.shape[2:]) if var.shape is not None else feat
        if not feat and want_feat == (1,):
            feat = (1,)
            seqs = [s[:, None] for s in seqs]
        data = np.zeros((len(seqs), T) + feat, dtype=np_dtype(var.dtype))
        for j, s in enumerate(seqs):
            data[j, : len(s)] = s
        return data, lens
