"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue/ByNorm/ByGlobalNorm + op-injection pass)."""
from __future__ import annotations

from typing import List, Optional

from .core.program import OP_ROLE_ATTR, OpRole


class BaseGradientClipAttr:
    def _create_operators(self, param, grad):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(name=grad.name + "@CLIP", shape=grad.shape,
                               dtype=grad.dtype, type=grad.type)
        block.append_op("clip", {"X": [grad.name]}, {"Out": [out.name]},
                        {"min": self.min, "max": self.max,
                         OP_ROLE_ATTR: OpRole.Backward})
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(name=grad.name + "@CLIP", shape=grad.shape,
                               dtype=grad.dtype, type=grad.type)
        block.append_op("clip_by_norm", {"X": [grad.name]}, {"Out": [out.name]},
                        {"max_norm": self.clip_norm,
                         OP_ROLE_ATTR: OpRole.Backward})
        return param, out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Global-norm clipping: grad_i *= clip_norm / max(global_norm, clip_norm).

    Emitted as graph ops over all grads at once (reference clip.py:228);
    under data-parallel lowering the global norm is computed after the grad
    psum, matching the reference's post-allreduce clip placement.
    """

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def process(self, params_grads):
        if not params_grads:
            return params_grads
        block = params_grads[0][1].block
        sq_names: List[str] = []
        for p, g in params_grads:
            sq = block.create_var(name=g.name + "@SQSUM", shape=(), dtype="float32")
            block.append_op("__global_norm_sq__", {"X": [g.name]},
                            {"Out": [sq.name]}, {OP_ROLE_ATTR: OpRole.Backward})
            sq_names.append(sq.name)
        total = block.create_var(name="@GLOBAL_NORM_SQ@" + params_grads[0][1].name,
                                 shape=(), dtype="float32")
        block.append_op("sum", {"X": sq_names}, {"Out": [total.name]},
                        {OP_ROLE_ATTR: OpRole.Backward})
        factor = block.create_var(name=total.name + "@FACTOR", shape=(),
                                  dtype="float32")
        block.append_op("__global_norm_factor__", {"X": [total.name]},
                        {"Out": [factor.name]},
                        {"clip_norm": self.clip_norm, OP_ROLE_ATTR: OpRole.Backward})
        out = []
        for p, g in params_grads:
            ng = block.create_var(name=g.name + "@CLIP", shape=g.shape,
                                  dtype=g.dtype, type=g.type)
            block.append_op("elementwise_mul", {"X": [g.name], "Y": [factor.name]},
                            {"Out": [ng.name]}, {OP_ROLE_ATTR: OpRole.Backward})
            out.append((p, ng))
        return out


_global_clip: Optional[BaseGradientClipAttr] = None


def set_gradient_clip(clip, param_list=None, program=None):
    global _global_clip
    _global_clip = clip
    if param_list:
        for p in param_list:
            p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads):
    clips = [(p, g, getattr(p, "gradient_clip_attr", None) or _global_clip)
             for p, g in params_grads]
    if any(isinstance(c, GradientClipByGlobalNorm) for _, _, c in clips):
        gclip = next(c for _, _, c in clips if isinstance(c, GradientClipByGlobalNorm))
        return gclip.process(params_grads)
    out = []
    for p, g, c in clips:
        if c is None or g is None:
            out.append((p, g))
        else:
            out.append(c._create_operators(p, g))
    return out


def error_clip_callback(block, context):  # parity stub
    pass


ErrorClipByValue = GradientClipByValue  # simplified parity alias
