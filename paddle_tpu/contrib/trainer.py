"""Event-driven Trainer (reference contrib/trainer.py:169,379).

The contract: the user supplies ``train_func`` returning (loss, metrics…)
and ``optimizer_func`` returning an Optimizer; the Trainer owns the
programs/scope, drives epochs over a reader, emits Begin/End events, and
checkpoints per epoch when configured.  Single-process (optionally
ParallelExecutor over the local mesh); for distributed runs drive
DistributeTranspiler / parallel.init_from_env directly.
"""
from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from .. import io as _io
from .. import optimizer as _optimizer  # noqa: F401 (re-export surface)
from ..core import unique_name
from ..core.executor import Executor, Scope, scope_guard
from ..core.program import Program, program_guard
from ..data_feeder import DataFeeder


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """Per-epoch checkpointing (reference contrib/trainer.py:100)."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1):
        self.checkpoint_dir = checkpoint_dir or "checkpoints"
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = epoch_interval


class Trainer:
    """reference contrib/trainer.py:229.

    ``train_func`` builds the model in the Trainer's programs and returns
    the loss var (optionally [loss, metric, ...]); ``optimizer_func``
    returns the Optimizer to minimize it.
    """

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 place=None, parallel: bool = False,
                 checkpoint_config: Optional[CheckpointConfig] = None):
        self.place = place
        self.parallel = parallel
        self.checkpoint_cfg = checkpoint_config
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        self.__stop = False

        with program_guard(self.train_program, self.startup_program), \
                unique_name.guard():
            out = train_func()
            if isinstance(out, (list, tuple)):
                self.loss = out[0]
                self.metrics = list(out)
            else:
                self.loss = out
                self.metrics = [out]
            optimizer_func().minimize(self.loss)

        self.exe = Executor(place)
        self.exe.run(self.startup_program, scope=self.scope)
        self._epoch_offset = 0
        self._maybe_load_checkpoint()
        self._pe = None

    def _maybe_load_checkpoint(self):
        cfg = self.checkpoint_cfg
        if cfg and os.path.isdir(cfg.checkpoint_dir):
            latest = self._latest_checkpoint()
            if latest is not None:
                with scope_guard(self.scope):
                    _io.load_persistables(self.exe, latest,
                                          main_program=self.train_program)
                # resume numbering after the loaded epoch, so retention
                # never deletes the freshest checkpoint
                self._epoch_offset = int(
                    os.path.basename(latest).split("_")[1]) + 1

    def _checkpoints(self) -> List[str]:
        cfg = self.checkpoint_cfg
        if not cfg or not os.path.isdir(cfg.checkpoint_dir):
            return []
        subs = [d for d in os.listdir(cfg.checkpoint_dir)
                if d.startswith("epoch_")]
        return [os.path.join(cfg.checkpoint_dir, d)
                for d in sorted(subs, key=lambda d: int(d.split("_")[1]))]

    def _latest_checkpoint(self) -> Optional[str]:
        cps = self._checkpoints()
        return cps[-1] if cps else None

    def _save_checkpoint(self, epoch_id: int) -> None:
        cfg = self.checkpoint_cfg
        path = os.path.join(cfg.checkpoint_dir, f"epoch_{epoch_id}")
        with scope_guard(self.scope):
            _io.save_persistables(self.exe, path,
                                  main_program=self.train_program)
        extra = self._checkpoints()[:-cfg.max_num_checkpoints]
        import shutil
        for old in extra:
            shutil.rmtree(old, ignore_errors=True)

    # -- public API --------------------------------------------------------
    def stop(self):
        self.__stop = True

    def train(self, num_epochs: int, event_handler: Callable,
              reader: Callable = None,
              feed_order: Optional[Sequence[str]] = None):
        if reader is None or feed_order is None:
            raise ValueError(
                "Trainer.train requires reader and feed_order (feed-order "
                "inference from the program is not implemented)")
        feeder = DataFeeder(list(feed_order), program=self.train_program)
        runner = self._runner()
        for epoch_id in range(num_epochs):
            event_handler(BeginEpochEvent(epoch_id))
            for step_id, data in enumerate(reader()):
                if self.__stop:
                    return
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                fetch = self.metrics if begin.fetch_metrics else []
                metrics = runner(feeder.feed(data), fetch)
                event_handler(EndStepEvent(epoch_id, step_id, metrics))
            event_handler(EndEpochEvent(epoch_id))
            cfg = self.checkpoint_cfg
            if cfg and (epoch_id + 1) % cfg.epoch_interval == 0:
                self._save_checkpoint(epoch_id + self._epoch_offset)

    def _runner(self):
        if self.parallel:
            if self._pe is None:
                from ..parallel import ParallelExecutor
                self._pe = ParallelExecutor(
                    loss_name=self.loss.name,
                    main_program=self.train_program, scope=self.scope)

            def run_pe(feed, fetch):
                return self._pe.run(feed=feed, fetch_list=fetch)
            return run_pe

        def run_exe(feed, fetch):
            return self.exe.run(self.train_program, feed=feed,
                                fetch_list=fetch, scope=self.scope)
        return run_exe

    def save_params(self, param_path: str) -> None:
        with scope_guard(self.scope):
            _io.save_params(self.exe, param_path,
                            main_program=self.train_program)

    def save_inference_model(self, param_path: str,
                             feeded_var_names: Sequence[str],
                             target_var_indexes: Sequence[int]) -> None:
        targets = [self.metrics[i] for i in target_var_indexes]
        with scope_guard(self.scope):
            _io.save_inference_model(param_path, list(feeded_var_names),
                                     targets, self.exe,
                                     main_program=self.train_program)

    def save_train_model(self, dirname: str,
                         feeded_var_names: Sequence[str]) -> None:
        """Export the TRAINABLE model (full programs + optimizer state)
        in the fluid.io.save_train_model layout, so training can be
        continued by the native C trainer (pt_trainer_*) or another
        Python process — the deployment handoff the reference's
        fluid/train demo consumes."""
        with scope_guard(self.scope):
            _io.save_train_model(dirname, list(feeded_var_names),
                                 self.loss, self.exe,
                                 main_program=self.train_program,
                                 startup_program=self.startup_program)
