"""Program introspection utilities.

Reference: ``python/paddle/fluid/contrib/memory_usage_calc.py:46``
(memory_usage) and ``contrib/op_frequence.py:23`` (op_freq_statistic).
TPU note: actual device memory is owned by XLA buffer assignment, so
``memory_usage`` is the same static var-shape estimate the reference
gives — a sizing heuristic, not an allocator report.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..core.program import Program
from ..core.types import np_dtype

_UNITS = ["B", "KB", "MB", "GB", "TB"]


def memory_usage(program: Program, batch_size: int):
    """Estimate (lower, upper, unit) memory usage of one replica of
    ``program`` at ``batch_size`` (reference memory_usage_calc.py:46:
    sums var sizes with -1 leading dims taken as the batch; the bounds
    bracket XLA's buffer reuse between 70% and 150% of the var total,
    the same fudge band the reference applies)."""
    if not isinstance(program, Program):
        raise ValueError("memory_usage expects a Program")
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    total = 0.0
    for block in program.blocks:
        for var in block.vars.values():
            shape = [batch_size if d == -1 else d for d in (var.shape or ())]
            total += float(np.prod(shape)) * np.dtype(
                np_dtype(var.dtype)).itemsize if shape else 0.0
    lo, hi = total * 0.7, total * 1.5
    unit = 0
    while hi >= 1024.0 and unit < len(_UNITS) - 1:
        lo /= 1024.0
        hi /= 1024.0
        unit += 1
    return lo, hi, _UNITS[unit]


def op_freq_statistic(program: Program):
    """(single-op freq, adjacent-op-pair freq) ordered by count desc
    (reference op_frequence.py:23)."""
    if not isinstance(program, Program):
        raise ValueError("op_freq_statistic expects a Program")
    uni, adj = {}, {}
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] = uni.get(op.type, 0) + 1
            if prev is not None:
                key = f"{prev}->{op.type}"
                adj[key] = adj.get(key, 0) + 1
            prev = op.type
    order = lambda d: OrderedDict(sorted(d.items(), key=lambda kv: -kv[1]))
    return order(uni), order(adj)
