"""High-level decoder API: InitState / StateCell / TrainingDecoder /
BeamSearchDecoder (reference contrib/decoder/beam_search_decoder.py:75 —
clean-room reimplementation of the API contract).

The reference builds these over LoD machinery: training decode through
``DynamicRNN`` and beam decode through a raw While loop whose beams GROW
as nested LoD levels, with ``sequence_expand`` fanning states out per
live candidate.  The TPU redesign keeps the same user-facing API but
maps it onto this framework's fixed-shape sequence contract:

- ``TrainingDecoder`` drives our masked-scan ``DynamicRNN`` (state
  memories become ``rnn.memory``/``update_memory`` pairs — the
  ``_MemoryState`` role);
- ``BeamSearchDecoder`` keeps a FIXED ``[beam]`` width: states are
  loop-carried ``[beam, ...]`` variables, and after each
  ``layers.beam_search`` step the decoder gathers them by the returned
  parent pointers (the fixed-width analogue of the reference's
  ``sequence_expand`` LoD fan-out — dynamic beam shapes cannot compile
  under XLA).  Early stop folds into ``beam_search_decode``'s end_id
  truncation instead of a mid-loop break.
"""
from __future__ import annotations

import contextlib

from .. import layers as L
from ..core.program import Variable
from ..layer_helper import LayerHelper
from ..layers.nn import _tile_rows

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder", "IncrementalBeamDecoder"]


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState:
    """Initial hidden state: either an explicit variable or a constant
    tensor shaped like ``init_boot`` (reference
    beam_search_decoder.py:43).  ``need_reorder`` is accepted for API
    parity and ignored: the padded-sequence DynamicRNN never reorders
    rows, so states stay batch-aligned by construction."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the shape of "
                "InitState.")
        else:
            self._init = L.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState:
    """Training-mode state: a DynamicRNN memory (reference
    beam_search_decoder.py:99)."""

    def __init__(self, rnn, init_state):
        self._rnn = rnn
        self._mem = rnn.memory(init=init_state.value)

    def get_state(self):
        return self._mem

    def update_state(self, state):
        self._rnn.update_memory(self._mem, state)


class _BeamState:
    """Beam-mode state: a loop-carried [beam, ...] variable.  The
    decoder gathers it by parent pointers after each beam step (the
    fixed-width role of the reference's _ArrayState + sequence_expand)."""

    def __init__(self, carried):
        self.carried = carried
        self.pending = None

    def get_state(self):
        return self.carried

    def update_state(self, state):
        self.pending = state  # finalized by the decoder's parent-gather


class StateCell:
    """Named step-inputs + named hidden states + a user ``state_updater``
    that computes the new states each step (reference
    beam_search_decoder.py:157 — same contract)."""

    def __init__(self, inputs, states, out_state, name=None):
        self._helper = LayerHelper("state_cell", name=name)
        self._cur_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError("state must be an InitState object.")
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = dict(inputs)
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._states_holder = {}
        self._switched_decoder = False
        self._state_updater = None
        self._out_state = out_state
        if self._out_state not in self._cur_states:
            raise ValueError("out_state must be one state in states")

    # -- decoder attachment ------------------------------------------------
    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError("StateCell has already entered a decoder.")
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder:
            raise ValueError("StateCell not in decoder, invalid leave.")
        if self._cur_decoder_obj is not decoder_obj:
            raise ValueError("Inconsistent decoder object in StateCell.")
        self._in_decoder = False
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        if not self._in_decoder:
            raise ValueError("StateCell must enter a decoder first.")
        if self._switched_decoder:
            raise ValueError("StateCell already switched decoder.")
        dec = self._cur_decoder_obj
        for state_name in self._state_names:
            holder = self._states_holder.setdefault(state_name, {})
            if id(dec) not in holder:
                state = self._cur_states[state_name]
                if not isinstance(state, InitState):
                    raise ValueError(
                        f"state {state_name} is {type(state)}, expected "
                        "InitState")
                if dec.type == _DecoderType.TRAINING:
                    holder[id(dec)] = _MemoryState(dec.dynamic_rnn, state)
                elif dec.type == _DecoderType.BEAM_SEARCH:
                    holder[id(dec)] = _BeamState(
                        dec._carried_state(state_name, state))
                else:
                    raise ValueError("Unknown decoder type.")
            self._cur_states[state_name] = holder[id(dec)].get_state()
        self._switched_decoder = True

    # -- user API ----------------------------------------------------------
    def get_state(self, state_name):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError(f"Unknown state {state_name}.")
        return self._cur_states[state_name]

    def get_input(self, input_name):
        if input_name not in self._inputs or \
                self._inputs[input_name] is None:
            raise ValueError(f"Invalid input {input_name}.")
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        self._state_updater = updater

        def _decorator(state_cell):
            if state_cell is not self:
                raise TypeError(
                    "Updater should only accept this StateCell object.")
            updater(state_cell)

        return _decorator

    def compute_state(self, inputs):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError(
                    f"Unknown input {input_name}. Please make sure "
                    f"{input_name} is a declared input placeholder.")
            self._inputs[input_name] = input_value
        self._state_updater(self)

    def update_states(self):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        dec_id = id(self._cur_decoder_obj)
        for state_name, holder in self._states_holder.items():
            if dec_id not in holder:
                raise ValueError(
                    "Unknown decoder object; switch_decoder not invoked.")
            holder[dec_id].update_state(self._cur_states[state_name])

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder:
    """Teacher-forced decoder over the masked-scan DynamicRNN (reference
    beam_search_decoder.py:380 — same block/step_input/static_input/
    output surface)."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper("training_decoder", name=name)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._dynamic_rnn = L.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError("decoder.block() can only be invoked once")
        self._status = TrainingDecoder.IN_DECODER
        with self._dynamic_rnn.block():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    @property
    def state_cell(self):
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._dynamic_rnn

    @property
    def type(self):
        return self._type

    def step_input(self, x):
        self._assert_in_decoder_block("step_input")
        return self._dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block("static_input")
        return self._dynamic_rnn.static_input(x)

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self._dynamic_rnn.output(*outputs)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError("Output of training decoder can only be "
                             "visited outside the block.")
        return self._dynamic_rnn(*args, **kwargs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError(
                f"{method} should be invoked inside block of "
                "TrainingDecoder object.")


class BeamSearchDecoder:
    """Beam-search inference decoder (reference
    beam_search_decoder.py:523 — same constructor and
    ``decode()`` / ``__call__`` contract).

    Fixed-width TPU semantics: ``init_ids``/``init_scores`` are
    ``[beam_size, 1]`` (seed scores 0 for beam 0, -inf for the rest);
    states and ``input_var_dict`` entries whose leading dim is the
    batch (1) are tiled to the beam width.  ``__call__`` returns
    ``(ids, scores)`` as ``[beam, max_len]`` padded sequences whose
    ``@LEN`` companions carry each candidate's true token length
    (``end_id`` truncation — the role of the reference's early_stop)."""

    BEFORE = 0
    IN = 1
    AFTER = 2

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None, emb_param_attr=None, score_param_attr=None,
                 score_bias_attr=None):
        self._helper = LayerHelper("beam_search_decoder", name=name)
        self._type = _DecoderType.BEAM_SEARCH
        self._status = BeamSearchDecoder.BEFORE
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = min(topk_size, target_dict_dim)
        self._sparse_emb = sparse_emb
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        # the reference's decode() creates its embedding/projection with
        # auto-generated names and relies on unique_name counters lining
        # up with the training program; these additive kwargs make the
        # weight sharing explicit instead
        self._emb_param_attr = emb_param_attr
        self._score_param_attr = score_param_attr
        self._score_bias_attr = score_bias_attr
        self._carried = {}
        self._decode_result = None
        self._state_cell._enter_decoder(self)

    @property
    def type(self):
        return self._type

    @property
    def state_cell(self):
        return self._state_cell

    def _carried_state(self, state_name, init_state):
        """Materialize one loop-carried [beam, ...] state variable from
        its InitState (called by StateCell._switch_decoder)."""
        # states arrive at BATCH width (batch 1 for the decode loop)
        # and ALWAYS fan out to the beam width — the reference's
        # sequence_expand role.  Never pre-tile the inputs: an
        # unconditional tile is the only unambiguous rule under dynamic
        # (-1) batch dims.
        init = _tile_rows(init_state.value, self._beam_size)
        carried = L.assign(init)  # private copy the loop mutates
        self._carried[state_name] = carried
        return carried

    def decode(self):
        """Build the fixed-width beam loop: embed previous ids, run the
        state updater, score with a softmax projection, take
        ``topk_size`` candidates, advance one ``beam_search`` step, and
        gather every carried state by the returned parent pointers."""
        if self._status != BeamSearchDecoder.BEFORE:
            raise ValueError("decode() can only be invoked once")
        self._status = BeamSearchDecoder.IN
        cell = self._state_cell
        bw = self._beam_size

        # materialize the loop-carried [beam, ...] states in the PARENT
        # block BEFORE entering the While: StateCell switches lazily on
        # the first get_state(), which used to happen inside the loop
        # body — so the carried vars (and their assign-from-init) were
        # created in the SUB-block, never qualified as loop carries,
        # and re-initialized every iteration: beam states silently
        # froze at their init values (decode degenerated to
        # conditioning on the last token only).  Pinned by the
        # incremental-vs-whole-sequence exactness test in
        # tests/test_contrib_decoder.py.
        if not cell._switched_decoder:
            cell._switch_decoder()

        pre_ids = L.assign(self._init_ids)
        pre_scores = L.assign(self._init_scores)
        ids_arr = L.create_array("int64", [bw], max_len=self._max_len)
        par_arr = L.create_array("int64", [bw], max_len=self._max_len)
        score_arr = L.create_array("float32", [bw], max_len=self._max_len)

        # beam-tiled statics for the cell's non-word inputs
        feed_static = {}
        for name, var in self._input_var_dict.items():
            if name not in cell._inputs:
                raise ValueError(f"Variable {name} not found in "
                                 "StateCell!")
            feed_static[name] = _tile_rows(var, bw)

        i = L.fill_constant([1], "int64", 0)
        n = L.fill_constant([1], "int64", self._max_len)
        cond = L.less_than(i, n)
        with L.While(cond).block():
            prev_emb = L.embedding(
                pre_ids, [self._target_dict_dim, self._word_dim],
                is_sparse=self._sparse_emb,
                param_attr=self._emb_param_attr)    # [bw, word_dim]
            feed = dict(feed_static)
            for input_name in cell._inputs:
                if input_name not in feed:
                    feed[input_name] = prev_emb
            cell.compute_state(inputs=feed)
            cell.update_states()                    # stash pending states
            current = cell.out_state()
            probs = L.fc(current, self._target_dict_dim, act="softmax",
                         param_attr=self._score_param_attr,
                         bias_attr=self._score_bias_attr)
            topk_scores, topk_ids = L.topk(probs, k=self._topk_size)
            acc = L.elementwise_add(L.log(topk_scores), pre_scores)
            sel_ids, sel_scores, parent = L.beam_search(
                pre_ids, pre_scores, topk_ids, acc,
                beam_size=bw, end_id=self._end_id)
            # beams reordered: every carried state follows its parent
            for state_name, carried in self._carried.items():
                holder = cell._states_holder[state_name][id(self)]
                pending = holder.pending
                if pending is None:  # state never updated this step
                    pending = carried
                L.assign(L.gather(pending, parent), carried)
                holder.pending = None
                cell.set_state(state_name, carried)
            L.array_write(L.squeeze(sel_ids, [1]), i, ids_arr)
            L.array_write(parent, i, par_arr)
            L.array_write(L.squeeze(sel_scores, [1]), i, score_arr)
            L.assign(sel_ids, pre_ids)
            L.assign(sel_scores, pre_scores)
            L.increment(i, 1)
            L.less_than(i, n, cond=cond)

        self._decode_result = L.beam_search_decode(
            ids_arr, par_arr, beam_size=bw, end_id=self._end_id,
            scores_array=score_arr)
        self._status = BeamSearchDecoder.AFTER
        self._state_cell._leave_decoder(self)

    def __call__(self):
        if self._status != BeamSearchDecoder.AFTER:
            raise ValueError("Output of BeamSearchDecoder can only be "
                             "visited after decode().")
        return self._decode_result.ids, self._decode_result.scores

    @property
    def result(self):
        """The full BeamDecodeResult (ids/scores/cand_len/src_len)."""
        return self._decode_result


class IncrementalBeamDecoder:
    """Beam search one decode step at a time — the decode plane's
    incremental twin of :class:`BeamSearchDecoder`.

    Where ``BeamSearchDecoder.decode()`` compiles the whole beam loop
    into ONE While program, this class carries the beam state
    (``pre_ids`` / ``pre_scores`` / the per-step selections) ACROSS
    executor dispatches, so a serving loop can interleave beam steps of
    many requests (token-level continuous batching) and stream partial
    hypotheses.  Exactness contract: each :meth:`step` runs the same op
    chain the While body compiles (``log`` → ``elementwise_add`` →
    ``beam_search``) as a one-step program, and :meth:`finalize` runs
    the same ``beam_search_decode`` backtrack op over the stacked
    per-step selections — so after ``max_len`` steps the result is
    bit-identical to the whole-sequence decoder's (pinned by
    tests/test_contrib_decoder.py on the machine-translation model).

    The caller owns the model half of each step (embed the previous
    ids, run the cell, score, top-k — exactly what it would put inside
    ``decoder.block()``) and must gather its carried states by the
    returned ``parent`` pointers, the role the whole-sequence decoder's
    in-loop ``L.gather`` plays.
    """

    def __init__(self, beam_size: int, end_id: int, topk_size: int,
                 executor=None):
        from ..core.executor import Executor, Scope
        from ..core.program import Program, program_guard
        from ..core import unique_name

        self.beam_size = int(beam_size)
        self.end_id = int(end_id)
        self.topk_size = int(topk_size)
        self._exe = executor if executor is not None \
            else Executor(training=False)
        self._scope = Scope()
        self._ids = []       # per-step selected ids     [bw]
        self._parents = []   # per-step parent pointers  [bw]
        self._scores = []    # per-step selected scores  [bw]
        self.pre_ids = None      # [bw, 1] int64
        self.pre_scores = None   # [bw, 1] float32
        # the one-step program: the While body's scoring-to-selection
        # tail (log + add + beam_search), compiled once, hit thereafter
        self._step_prog = Program()
        with program_guard(self._step_prog, Program()), \
                unique_name.guard():
            from .. import layers as L
            pre_ids = L.data("ibd_pre_ids", [1], dtype="int64")
            pre_scores = L.data("ibd_pre_scores", [1])
            cand_ids = L.data("ibd_cand_ids", [self.topk_size],
                              dtype="int64")
            cand_probs = L.data("ibd_cand_probs", [self.topk_size])
            acc = L.elementwise_add(L.log(cand_probs), pre_scores)
            sel_ids, sel_scores, parent = L.beam_search(
                pre_ids, pre_scores, cand_ids, acc,
                beam_size=self.beam_size, end_id=self.end_id)
            self._step_fetches = [sel_ids.name, sel_scores.name,
                                  parent.name]

    def start(self, init_ids=None, init_scores=None) -> None:
        """Seed the beam (the ``init_ids``/``init_scores`` contract of
        BeamSearchDecoder: zeros, and 0 / -1e9 scores so identical
        initial beams don't multiply)."""
        import numpy as np
        bw = self.beam_size
        self.pre_ids = (np.zeros((bw, 1), "int64") if init_ids is None
                        else np.asarray(init_ids, "int64").reshape(bw, 1))
        if init_scores is None:
            init_scores = [[0.0]] + [[-1e9]] * (bw - 1)
        self.pre_scores = np.asarray(init_scores,
                                     "float32").reshape(bw, 1)
        self._ids, self._parents, self._scores = [], [], []

    def step(self, cand_ids, cand_probs):
        """Advance one beam step.  ``cand_ids``/``cand_probs``:
        [beam, topk_size] top-k tokens and their (softmax) probabilities
        from the caller's cell+scoring dispatch.  Returns ``(sel_ids
        [bw, 1], parent [bw])`` — gather every carried model state by
        ``parent`` before computing the next step's candidates."""
        import numpy as np
        if self.pre_ids is None:
            self.start()
        bw = self.beam_size
        feed = {"ibd_pre_ids": self.pre_ids,
                "ibd_pre_scores": self.pre_scores,
                "ibd_cand_ids": np.asarray(cand_ids,
                                           "int64").reshape(bw, -1),
                "ibd_cand_probs": np.asarray(cand_probs,
                                             "float32").reshape(bw, -1)}
        sel_ids, sel_scores, parent = self._exe.run(
            self._step_prog, feed=feed, fetch_list=self._step_fetches,
            scope=self._scope, sync=True)
        sel_ids = np.asarray(sel_ids).reshape(bw, 1)
        sel_scores = np.asarray(sel_scores).reshape(bw, 1)
        parent = np.asarray(parent).reshape(bw)
        self._ids.append(sel_ids[:, 0].copy())
        self._parents.append(parent.copy())
        self._scores.append(sel_scores[:, 0].copy())
        self.pre_ids, self.pre_scores = sel_ids, sel_scores
        return sel_ids, parent

    @property
    def steps(self) -> int:
        return len(self._ids)

    def finalize(self):
        """Backtrack the accumulated selections through the SAME
        ``beam_search_decode`` op the whole-sequence decoder ends with;
        returns a numpy ``BeamDecodeResult`` (ids [bw, T], scores,
        cand_len [bw], src_len [1])."""
        import numpy as np
        from ..core.program import Program, program_guard
        from ..core import unique_name
        from ..layer_helper import LayerHelper
        from ..layers.control_flow import BeamDecodeResult
        from .. import layers as L

        if not self._ids:
            raise ValueError("finalize() before any step()")
        bw, t = self.beam_size, len(self._ids)
        prog = Program()
        with program_guard(prog, Program()), unique_name.guard():
            ids_v = L.data("ibd_arr_ids", [bw], dtype="int64")
            par_v = L.data("ibd_arr_parents", [bw], dtype="int64")
            sc_v = L.data("ibd_arr_scores", [bw])
            len_v = L.data("ibd_arr_len", [1], dtype="int64",
                           append_batch_size=False)
            helper = LayerHelper("beam_search_decode")
            sents = helper.create_variable_for_type_inference(
                "int64", shape=(bw, t))
            cand_len = helper.create_variable_for_type_inference(
                "int64", shape=(bw,), stop_gradient=True)
            src_len = helper.create_variable_for_type_inference(
                "int64", shape=(1,), stop_gradient=True)
            scores = helper.create_variable_for_type_inference(
                "float32", shape=(bw, t))
            helper.append_op(
                "beam_search_decode",
                {"Ids": [ids_v], "Parents": [par_v], "Scores": [sc_v],
                 "ArrayLen": [len_v]},
                {"SentenceIds": [sents], "SentenceLen": [cand_len],
                 "SourceLen": [src_len], "SentenceScores": [scores]},
                {"end_id": self.end_id, "beam_size": self.beam_size})
            fetches = [sents.name, scores.name, cand_len.name,
                       src_len.name]
        feed = {"ibd_arr_ids": np.stack(self._ids),
                "ibd_arr_parents": np.stack(self._parents),
                "ibd_arr_scores": np.stack(self._scores),
                "ibd_arr_len": np.asarray([t], "int64")}
        out = self._exe.run(prog, feed=feed, fetch_list=fetches,
                            scope=self._scope, sync=True)
        ids, scores, cand_len, src_len = (np.asarray(v) for v in out)
        return BeamDecodeResult(ids, scores, cand_len, src_len)
