"""Quantization-aware training transpiler.

Reference: ``python/paddle/fluid/contrib/quantize/quantize_transpiler.py:1``
(QuantizeTranspiler): rewrite a program so every quantizable op (conv2d,
depthwise_conv2d, mul) consumes fake-quantized versions of its inputs —
simulating int8 error during training; gradients pass straight through
(ops/quant_ops.py registers STE grads).

TPU redesign notes:
- The reference inserts a fake_quantize op producing an int-domain tensor
  followed by a fake_dequantize back to float.  This repo's fake_quantize
  lowerings (ops/quant_ops.py) emit the quantize→dequantize COMPOSITION
  directly (one op, float in/float out) — same math, one HLO fusion, and
  the int tensor never materializes in HBM.  The ``.quantized.dequantized``
  var naming of the reference is kept so freeze tooling can recognize it.
- ``range_abs_max`` maps to the moving-average scale op (the reference's
  window-based range tracker serves the same purpose: a running estimate
  of the activation range that inference can reuse); its scale/accum/state
  ride persistable vars initialized by the startup program.
- Transpile may run before OR after backward ops exist, like the
  reference: forward ops are rewired to the quantized inputs, and any
  existing grad ops get their forward-input references renamed
  (straight-through at the same points).
"""
from __future__ import annotations

from typing import Optional

from ..core.program import (Program, default_main_program,
                            default_startup_program)
from ..core.registry import GRAD_OP_SUFFIX

_QUANTIZABLE_OP_TYPES = ("conv2d", "depthwise_conv2d", "mul")
_QUANT_TYPES = ("abs_max", "range_abs_max", "moving_average_abs_max")


def _quantized_var_name(name):
    return f"{name}.quantized.dequantized"


def _scale_name(name):
    return f"{name}.scale"


class QuantizeTranspiler:
    """Program rewrite for simulated-quantization training (reference
    quantize_transpiler.py:80 API)."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "abs_max",
                 weight_quantize_type: str = "abs_max",
                 window_size: Optional[int] = None,
                 moving_rate: float = 0.9):
        if activation_quantize_type not in _QUANT_TYPES:
            raise ValueError(
                f"Unknown activation_quantize_type {activation_quantize_type!r};"
                f" one of {_QUANT_TYPES}")
        if weight_quantize_type not in ("abs_max",):
            raise ValueError(
                f"Unknown weight_quantize_type {weight_quantize_type!r}; "
                "weights are fixed per step, 'abs_max' is the supported mode")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        # the reference's range tracker averages over a window_size-step
        # window; the moving-average scale op approximates it with an EMA
        # of the same effective horizon (rate = 1 - 1/window)
        self.moving_rate = (moving_rate if window_size is None
                            else max(moving_rate, 1.0 - 1.0 / window_size))

    # -- public API --------------------------------------------------------
    def training_transpile(self, program: Optional[Program] = None,
                           startup_program: Optional[Program] = None):
        """In-place rewrite: insert fake-quant ops ahead of every
        quantizable op and rewire op (and existing grad-op) inputs."""
        program = program or default_main_program()
        startup = startup_program or default_startup_program()
        block = program.global_block
        params = {name for name, v in block.vars.items()
                  if getattr(v, "persistable", False)}
        grad_types = {t + GRAD_OP_SUFFIX for t in _QUANTIZABLE_OP_TYPES}

        qdq_of = {}           # original name -> qdq name
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type in _QUANTIZABLE_OP_TYPES and not op.attrs.get(
                    "__quantized__", False):
                for slot, names in list(op.inputs.items()):
                    for j, name in enumerate(names):
                        if not name or name.endswith(".quantized.dequantized"):
                            continue
                        if name not in qdq_of:
                            n_new = self._insert_qdq(
                                block, startup, i, name, name in params)
                            qdq_of[name] = n_new
                            i += 1              # one op inserted before
                        op.inputs[slot][j] = qdq_of[name]
                op.attrs["__quantized__"] = True
                program._version += 1
            elif op.type in grad_types:
                # straight-through: grad ops read the same qdq'ed values
                # the forward consumed (reference _transpile_backward)
                for slot, names in op.inputs.items():
                    if slot.endswith("@GRAD"):
                        continue
                    for j, name in enumerate(names):
                        if name in qdq_of:
                            op.inputs[slot][j] = qdq_of[name]
                program._version += 1
            i += 1
        return program

    def freeze_program(self, program: Optional[Program] = None):
        """Stamp the rewritten program for inference (is_test):
        moving-average/range activation quantizers switch to their stored
        running scales; plain abs_max quantizers stay dynamic BY DESIGN —
        the reference documents abs_max as "calculated dynamically each
        step in both training and testing period"
        (quantize_transpiler.py:96).  The save/load_inference_model path
        keeps the ops in-graph."""
        program = program or default_main_program()
        for op in program.global_block.ops:
            if op.type.startswith("fake_") and "quantize" in op.type:
                op.attrs["is_test"] = True
        program._version += 1
        return program

    # -- internals ---------------------------------------------------------
    def _insert_qdq(self, block, startup, idx, name, is_param):
        var = block.var(name)
        qdq = block.create_var(name=_quantized_var_name(name),
                               shape=var.shape, dtype=var.dtype)
        bits = self.weight_bits if is_param else self.activation_bits
        scale = block.create_var(
            name=_scale_name(name), dtype="float32",
            shape=(var.shape[0],) if (is_param and len(var.shape) == 4)
            else (1,),
            persistable=True, stop_gradient=True)
        if is_param and len(var.shape) == 4:
            # conv filters: per-output-channel scales (reference
            # channel-wise path for OIHW weights)
            block.insert_op(
                idx, "fake_channel_wise_quantize_abs_max",
                {"X": [name]}, {"Out": [qdq.name], "OutScale": [scale.name]},
                {"bit_length": bits})
            return qdq.name
        if is_param or self.activation_quantize_type == "abs_max":
            block.insert_op(
                idx, "fake_quantize_abs_max",
                {"X": [name]}, {"Out": [qdq.name], "OutScale": [scale.name]},
                {"bit_length": bits})
            return qdq.name
        # running-range activation scale: persistable accum/state seeded
        # by the startup program
        accum = block.create_var(name=f"{name}.quant_accum", shape=(1,),
                                 dtype="float32", persistable=True,
                                 stop_gradient=True)
        state = block.create_var(name=f"{name}.quant_state", shape=(1,),
                                 dtype="float32", persistable=True,
                                 stop_gradient=True)
        sblock = startup.global_block
        for v in (scale, accum, state):
            sblock.create_var(name=v.name, shape=(1,), dtype="float32",
                              persistable=True)
            sblock.append_op("fill_constant", {}, {"Out": [v.name]},
                             {"shape": [1], "dtype": "float32",
                              "value": 0.0})
        block.insert_op(
            idx, "fake_quantize_moving_average_abs_max",
            {"X": [name], "InScale": [scale.name], "InAccum": [accum.name],
             "InState": [state.name]},
            {"Out": [qdq.name], "OutScale": [scale.name],
             "OutAccum": [accum.name], "OutState": [state.name]},
            {"bit_length": bits, "moving_rate": self.moving_rate})
        return qdq.name
