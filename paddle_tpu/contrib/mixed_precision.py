"""Low-precision inference transpiler (reference
``paddle/contrib/float16/float16_transpiler.py``): rewrite a trained
f32 inference program + its weights to run in half precision.

TPU-native difference: the target type is **bfloat16** (the MXU's native
half type — fp16 on TPU gains nothing and loses exponent range), and no
cast ops need inserting: variable dtypes drive weight conversion and feed
casting, and XLA fuses any remaining converts.  Batch-norm / layer-norm
statistics stay f32 (their kernels normalize in f32 and cast back, so the
declared dtype is honored).  Network outputs come back bfloat16 — cast on
the host if a consumer needs f32.
"""
from __future__ import annotations

from typing import Iterable, Optional, Set

import numpy as np

from ..core.executor import global_scope
from ..core.program import Program
from ..core.types import np_dtype

# vars feeding these slots keep f32 (running stats / normalization)
_KEEP_F32_INPUT_SLOTS = {
    "batch_norm": ("Scale", "Bias", "Mean", "Variance"),
    "layer_norm": ("Scale", "Bias"),
}

# attrs that carry a dtype and must follow the conversion
_DTYPE_ATTRS = ("dtype", "out_dtype", "in_dtype", "w_dtype")


class Float16Transpiler:
    """reference float16_transpiler.py, retargeted to bfloat16."""

    def transpile(self, program: Program, place=None, scope=None,
                  keep_vars: Optional[Iterable[str]] = None) -> Program:
        scope = scope or global_scope()
        bf16 = np_dtype("bfloat16")

        keep: Set[str] = set(keep_vars or ())
        for block in program.blocks:
            for op in block.ops:
                slots = _KEEP_F32_INPUT_SLOTS.get(op.type)
                if slots:
                    for slot in slots:
                        keep.update(op.input(slot))

        for block in program.blocks:
            for var in block.vars.values():
                if var.dtype == "float32" and var.name not in keep:
                    var.dtype = "bfloat16"
                    val = scope.find_var(var.name)
                    if val is not None and var.persistable:
                        scope.set_var(var.name,
                                      np.asarray(val).astype(bf16))
            for op in block.ops:
                if set(op.output_arg_names()) & keep:
                    continue
                for attr in _DTYPE_ATTRS:
                    if op.attr(attr) == "float32":
                        op.set_attr(attr, "bfloat16")
        program._version += 1
        return program


def transpile_to_bf16(program: Program, scope=None,
                      keep_vars: Optional[Iterable[str]] = None) -> Program:
    return Float16Transpiler().transpile(program, scope=scope,
                                         keep_vars=keep_vars)
