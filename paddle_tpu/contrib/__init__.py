"""High-level training API (reference python/paddle/fluid/contrib/)."""
from .trainer import (BeginEpochEvent, BeginStepEvent, CheckpointConfig,
                      EndEpochEvent, EndStepEvent, Trainer)
from .inferencer import Inferencer

__all__ = ["Trainer", "Inferencer", "BeginEpochEvent", "EndEpochEvent",
           "BeginStepEvent", "EndStepEvent", "CheckpointConfig"]
