"""High-level training API (reference python/paddle/fluid/contrib/)."""
from .trainer import (BeginEpochEvent, BeginStepEvent, CheckpointConfig,
                      EndEpochEvent, EndStepEvent, Trainer)
from .inferencer import Inferencer
from .mixed_precision import Float16Transpiler, transpile_to_bf16
from .quantize import QuantizeTranspiler
from .introspection import memory_usage, op_freq_statistic
from . import decoder  # noqa: F401  (InitState/StateCell/*Decoder)

__all__ = ["Trainer", "Inferencer", "BeginEpochEvent", "EndEpochEvent",
           "BeginStepEvent", "EndStepEvent", "CheckpointConfig",
           "Float16Transpiler", "transpile_to_bf16", "QuantizeTranspiler",
           "memory_usage", "op_freq_statistic"]
