"""Inferencer (reference contrib/inferencer.py): build the infer program
from ``infer_func``, load trained params, run batches."""
from __future__ import annotations

from typing import Callable, Dict, Optional

from .. import io as _io
from ..core import unique_name
from ..core.executor import Executor, Scope, scope_guard
from ..core.program import Program, program_guard
from ..inference.passes import apply_is_test


class Inferencer:
    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 parallel: bool = False):
        if parallel:
            raise NotImplementedError(
                "Inferencer(parallel=True) is not implemented; batch across "
                "the mesh with ParallelExecutor directly")
        self.scope = Scope()
        self.place = place
        self.startup_program = Program()
        self.inference_program = Program()
        with program_guard(self.inference_program, self.startup_program), \
                unique_name.guard():
            out = infer_func()
            self.predict_vars = list(out) if isinstance(out, (list, tuple)) \
                else [out]
        apply_is_test(self.inference_program)
        self.exe = Executor(place)
        self.exe.run(self.startup_program, scope=self.scope)
        with scope_guard(self.scope):
            _io.load_params(self.exe, param_path,
                            main_program=self.inference_program)

    def infer(self, inputs: Dict, return_numpy: bool = True):
        return self.exe.run(self.inference_program, feed=inputs,
                            fetch_list=self.predict_vars, scope=self.scope,
                            return_numpy=return_numpy)
