"""Fused / flash / ring attention — the framework's hot-op kernel story.

Reference precedent: the CPU JIT kernel library
(``paddle/fluid/operators/math/jit_kernel*`` — hand-tuned kernels behind a
dispatch layer) and the cuDNN library_type kernels.  Here the hot op is
attention; three implementations sit behind one function:

- ``xla``:    plain jnp einsum/softmax chain (XLA fuses; always available)
- ``pallas``: tiled online-softmax flash-attention kernel (MXU-sized tiles,
              VMEM accumulators; interpret mode off-TPU), with optional
              in-kernel attention-probability dropout (TPU PRNG seeded per
              (batch·head, q-block, k-block) tile — regenerated bit-exactly
              by the backward kernels, so no mask is ever materialized)
- ``ring``:   sequence-parallel attention over a mesh axis — K/V shards
              rotate around the ring via ``lax.ppermute``; every shard
              pair runs the SAME Pallas flash kernel and partials merge
              by log2 softmax mass, so a device never materializes more
              than a [block_q, block_k] tile: O(block) compute memory at
              any sequence length.  This is the long-context scaling
              mechanism (SURVEY.md §5: absent in the 2018 reference,
              required here as first-class).

Gradients: ``jax.custom_vjp``.  The Pallas path saves only (out, LSE) and
runs tiled backward kernels (dq accumulation over k-blocks; dk/dv
accumulation over q-blocks) — O(block) memory for training at any sequence
length, the FlashAttention-2 backward scheme.  Ring attention has its own
vjp that lifts the same decomposition to shard granularity: per-pair
``_pallas_bwd`` with the GLOBAL merged lse, dk/dv accumulators riding the
ring home (see ``ring_attention``).
"""
from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e30
LOG2E = 1.4426950408889634  # kernels run softmax in exp2 units (see below)


# ---------------------------------------------------------------------------
# plain XLA implementation (also the custom_vjp backward math)
# ---------------------------------------------------------------------------

def mha_xla(q, k, v, kv_mask=None, causal=False, sm_scale=None,
            q_offset=0, kv_offset=0, dropout_rate=0.0, dropout_seed=None):
    """q,k,v: [B,H,Tq|Tk,D]; kv_mask: [B,Tk] 1/0; returns [B,H,Tq,D].

    q_offset/kv_offset give global positions for causal masking when the
    sequence is sharded (ring attention).  ``dropout_rate`` applies
    attention-prob dropout keyed by ``dropout_seed`` (deterministic per
    seed, so a re-lowered backward sees the same mask; the bits differ
    from the pallas kernel's tile hash — same distribution, either path
    is self-consistent)."""
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, NEG_INF)
    if causal:
        qi = jnp.arange(q.shape[2])[:, None] + q_offset
        ki = jnp.arange(k.shape[2])[None, :] + kv_offset
        s = jnp.where(qi >= ki, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate and dropout_rate > 0.0:
        seed = (jnp.zeros((), jnp.int32) if dropout_seed is None
                else jnp.asarray(dropout_seed, jnp.int32).reshape(()))
        p = p * _hash_dropout(seed, q_offset * 131071 + kv_offset, p.shape,
                              dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _hash_dropout(seed, salt, shape, rate):
    """Counter-hash dropout multiplier for the XLA attention path — the
    jnp twin of the Pallas kernels' ``_tile_dropout``: ~10 integer VPU ops
    per element instead of a threefry invocation (jax.random.bernoulli
    cost a measured ~36% of the seq-256 Transformer step), and cheap
    enough for XLA to REMATERIALIZE in the backward rather than storing a
    [B,H,Tq,Tk] mask.  Deterministic per (seed, salt, element coords)."""
    b = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    h = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    q = jax.lax.broadcasted_iota(jnp.uint32, shape, 2)
    k = jax.lax.broadcasted_iota(jnp.uint32, shape, 3)
    x = (q * jnp.uint32(0x9E3779B1)) ^ (k * jnp.uint32(0x85EBCA77))
    x = x ^ (b * jnp.uint32(0xC2B2AE3D) + h * jnp.uint32(0x27D4EB2F))
    x = x ^ (seed.astype(jnp.uint32)
             + jnp.asarray(salt, jnp.uint32) * jnp.uint32(0x165667B1))
    return _finalize_dropout(x, rate)


# ---------------------------------------------------------------------------
# Pallas flash-attention forward kernel
# ---------------------------------------------------------------------------

def _scaled_q(q_ref, sm_scale):
    """Fold ``sm_scale * log2(e)`` into the q tile so the kernels never
    touch the [block_q, block_k] scores with a scale multiply AND run
    softmax in exp2 units (exp(x) lowers to exp2(x*log2e) on the VPU —
    pre-folding the multiplier saves one more op per score element).
    The [block_q, D] multiply is ~block_k/1 times cheaper than scaling s."""
    return (q_ref[:].astype(jnp.float32) * (sm_scale * LOG2E)
            ).astype(q_ref.dtype)


def _lane_pack_ok(D, dropout_rate):
    """Eligibility gate for the forward ones-lane denominator: V must
    leave output lanes idle (D < 128) and dropout must be off (l must
    accumulate UNdropped probability mass).  NOTE(perf A/B, r4): bf16
    score tiles were tried and REGRESSED (52.9->49.5 fwd TF, maxdiff
    2x) — Mosaic requires f32 matmul accumulators, so the downcast is
    an extra f32-width op; scores stay f32."""
    return D < 128 and not (dropout_rate and dropout_rate > 0.0)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct for a pallas_call out_shape that works under
    shard_map's varying-mesh-axes (vma) checking: outputs vary over the
    same mesh axes as the operand ``like`` (ring attention calls the
    kernels per shard inside shard_map)."""
    typeof = getattr(jax, "typeof", None)   # absent before jax 0.6
    vma = getattr(typeof(like), "vma", None) if typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _append_ones_lane(x):
    """Append a ones lane to the minor dim (the fwd kernel's softmax
    denominator rides it — see _flash_fwd_kernel)."""
    return jnp.concatenate(
        [x, jnp.ones(x.shape[:-1] + (1,), x.dtype)], axis=-1)


def _tile_scores(q, k_ref, mask_ref, qi, kb, *, causal,
                 block_q, block_k, has_mask=True):
    """Masked scores (in exp2 units — q pre-scaled by ``_scaled_q``) for
    one (q-block, k-block) tile.

    The dot runs in the INPUT dtype (bf16 on TPU) with an f32
    accumulator — upcasting q/k first would push the MXU into f32 mode
    at ~1/8 the bf16 rate."""
    s = jnp.dot(q, k_ref[:].T, preferred_element_type=jnp.float32)
    if has_mask:
        mask = mask_ref[0, :]
        s = jnp.where(mask[None, :] > 0, s, NEG_INF)
    if causal:
        # unconditional masking measured FASTER than branching per tile
        # (lax.cond on the diagonal predicate cost ~15% at T=8192 — the
        # branch breaks Mosaic's straight-line VPU pipelining).  With
        # square tiles the diagonal pattern is a CONSTANT triangular mask
        # (hoisted out of the grid loop by Mosaic) OR'd with the scalar
        # below-diagonal predicate — no per-tile iota arithmetic.
        if block_q == block_k:
            tri = (jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                   >= jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
            below = qi * block_q > kb * block_k  # strictly past the diagonal
            above = qi * block_q < kb * block_k  # fully masked (reachable
            # only as the degenerate clamped tile when Tk > Tq)
            keep = jnp.logical_and(jnp.logical_or(below, tri),
                                   jnp.logical_not(above))
            s = jnp.where(keep, s, NEG_INF)
        else:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return s


def _last_kb(qi, *, causal, block_q, block_k, num_kb):
    """Last k-block index intersecting the causal frontier of q-block qi
    (the whole k range when not causal)."""
    if not causal:
        return num_kb - 1
    return jnp.minimum(((qi + 1) * block_q - 1) // block_k, num_kb - 1)


def _first_qb(kb, *, causal, block_q, block_k, num_qb):
    """First q-block index at/below the causal frontier of k-block kb,
    clamped into range: a k-block entirely above the frontier (possible
    when Tk > Tq) degenerates to the last q-block, whose fully-masked
    tile contributes exact zeros — so dk/dv come out zero, not stale."""
    if not causal:
        return 0
    return jnp.minimum((kb * block_k) // block_q, num_qb - 1)


def _finalize_dropout(x, rate):
    """Shared murmur-finalizer tail of both dropout hashes (Pallas tile
    and XLA paths): mix -> top-24-bit uniform [0,1) -> keep/scale.  Kept
    in ONE place so the mask semantics of the two paths cannot diverge
    (test_dropout_engages_in_lowered_hlo anchors on the 0x7FEB352D
    constant).  The bitcast detour exists because mosaic lacks a direct
    uint32->f32 convert (values < 2^24 are sign-safe)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = (jax.lax.bitcast_convert_type(x >> 8, jnp.int32)
         .astype(jnp.float32) * jnp.float32(1.0 / (1 << 24)))
    keep = u >= jnp.float32(rate)
    return jnp.where(keep, 1.0 / (1.0 - rate), 0.0).astype(jnp.float32)


def _tile_dropout(seed_ref, bh, qi, kb, shape, rate: float):
    """Regenerable dropout multiplier for one tile: a counter-based hash of
    (base seed, tile coords, element coords) in plain vector ops — the same
    bits in compiled and interpret mode, so forward and both backward
    kernels reproduce the identical mask with nothing stored (reference
    dropout_op.cc's saved Mask, made unnecessary).  Murmur3-style finalizer
    over distinct odd multipliers per coordinate."""
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    x = rows * jnp.uint32(0x9E3779B1) ^ cols * jnp.uint32(0x85EBCA77)
    x = x ^ (seed_ref[0].astype(jnp.uint32)
             + jnp.uint32(bh).astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
             + jnp.uint32(qi).astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)
             + jnp.uint32(kb).astype(jnp.uint32) * jnp.uint32(0x165667B1))
    return _finalize_dropout(x, rate)


def _flash_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr, *,
                      sm_scale: float, causal: bool, dropout_rate: float,
                      block_q: int, block_k: int, num_kb: int,
                      has_mask: bool, ones_lane: bool, head_dim: int):
    """Grid (B*H, nq, nk); K/V stream through VMEM one block_k tile at a
    time (nk is the sequential minor grid axis on TPU, so the online-softmax
    state lives in VMEM scratch across k iterations — O(block) memory at any
    sequence length).  Emits the per-row logsumexp (base-2 units) for the
    backward pass.

    Causal tiles entirely above the diagonal are SKIPPED: no compute, and
    the K/V index maps clamp to the causal frontier so the pipeline issues
    no copies for them either — ~2x on long causal sequences.

    ``ones_lane`` (head_dim < 128, no dropout): V carries an appended ones
    column, so the PV dot accumulates the softmax denominator in an
    otherwise-idle MXU lane and the per-element VPU sum-reduce disappears
    (l rides acc_scr[:, head_dim]).  The kernel is VPU-bound (PERF.md §1);
    with the exp2/q-prescale folding this drops the per-score-element op
    count from ~8 to ~5."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    last = _last_kb(qi, causal=causal, block_q=block_q, block_k=block_k,
                    num_kb=num_kb)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(kb <= last)
    def _compute():
        qs = _scaled_q(q_ref, sm_scale)
        s = _tile_scores(qs, k_ref, mask_ref, qi, kb,
                         causal=causal, block_q=block_q, block_k=block_k,
                         has_mask=has_mask)
        v_blk = v_ref[:]

        m, acc = m_scr[:], acc_scr[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m - m_new)
        m_scr[:] = m_new
        if not ones_lane:
            l_scr[:] = (l_scr[:] * alpha.astype(jnp.float32)
                        + jnp.sum(p.astype(jnp.float32), axis=-1,
                                  keepdims=True))
        if dropout_rate > 0.0:
            # dropout applies to normalized probs; l accumulates undropped
            p = p * _tile_dropout(seed_ref, bh, qi, kb, p.shape,
                                  dropout_rate).astype(p.dtype)
        acc_scr[:] = acc * alpha.astype(jnp.float32) + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32)

    @pl.when(kb == last)
    def _finish():
        if ones_lane:
            l_fin = acc_scr[:, head_dim:head_dim + 1]
            out = acc_scr[:, :head_dim]
        else:
            l_fin = l_scr[:]
            out = acc_scr[:]
        o_ref[:] = (out / jnp.maximum(l_fin, 1e-30)).astype(o_ref.dtype)
        # rows with no unmasked keys (query padding): +inf LSE → p == 0
        # everywhere in the backward kernels, never NaN.  LSE rides a
        # whole-row [1, Tq] block (TPU tiling forbids 1D per-q-block
        # outputs); each q-block writes its slice.
        lse = jnp.where(l_fin > 0.0,
                        m_scr[:].astype(jnp.float32)
                        + jnp.log2(jnp.maximum(l_fin, 1e-30)),
                        jnp.float32(1e30))
        lse_ref[0, pl.dslice(qi * block_q, block_q)] = lse[:, 0].astype(lse_ref.dtype)


try:  # pallas import kept lazy-safe for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

# NOTE(perf A/B, r3): CompilerParams(dimension_semantics=("parallel",
# "parallel", "arbitrary")) measured ~20% SLOWER at T=8192 than the
# default on this chip, as did per-tile lax.cond causal-mask branching —
# both left out deliberately.


def _pad_to(x, multiple, axis):
    rem = x.shape[axis] % multiple
    if rem == 0:
        return x, 0
    pad = multiple - rem
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), pad


def _resolve_blocks(block_q, block_k, Tq, Tk):
    """Measured-best tile sizes on v5e (r3 K-sweep at T=8192 causal
    fwd+bwd: 512x1024 -> 46 ms, 1024x1024 -> 23 ms): big q-blocks cut
    K/V restreaming (streamed bytes scale with Tq/block_q), big k-blocks
    amortize VMEM pipelining; 2048-wide blocks fail to compile."""
    if block_q is None:
        block_q = 1024 if Tq >= 1024 else (512 if Tq >= 512 else 128)
    if block_k is None:
        block_k = 1024 if Tk >= 1024 else (512 if Tk >= 512 else 128)
    return block_q, block_k


def _prep_padded(q, k, v, kv_mask, block_q, block_k):
    """Pad to block multiples and flatten (B,H).  When ``kv_mask`` is None
    and no length padding was added, no mask array is materialized at all
    (``has_mask=False`` compiles the mask load + where out of the kernels)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    q4, _ = _pad_to(q, block_q, 2)
    k4, pad_k = _pad_to(k, block_k, 2)
    v4, _ = _pad_to(v, block_k, 2)
    Tq_p, Tk_p = q4.shape[2], k4.shape[2]
    qf = q4.reshape(B * H, Tq_p, D)
    kf = k4.reshape(B * H, Tk_p, D)
    vf = v4.reshape(B * H, Tk_p, D)
    if kv_mask is None and pad_k == 0:
        # never read (has_mask=False); one block wide — the mask index
        # map pins block (b, 0, 0), so no larger buffer is ever touched
        maskf = jnp.zeros((B * H, 1, block_k), jnp.float32)
        return qf, kf, vf, maskf, Tq_p, Tk_p, False
    if kv_mask is None:
        kv_mask = jnp.ones((B, Tk), jnp.float32)
    mask2, _ = _pad_to(kv_mask.astype(jnp.float32), block_k, 1)
    maskf = jnp.repeat(mask2[:, None, :], H, axis=1).reshape(B * H, 1, Tk_p)
    return qf, kf, vf, maskf, Tq_p, Tk_p, True


def _seed_arr(dropout_seed):
    if dropout_seed is None:
        return jnp.zeros((1,), jnp.int32)
    return jnp.asarray(dropout_seed, jnp.int32).reshape((1,))


def _fwd_maps(causal, has_mask, block_q, block_k, num_kb):
    """Index maps for K/V/mask blocks in q-major grids (fwd, dq): clamp
    skipped causal tiles to the frontier block (_last_kb), so the pipeline
    re-references the previous block and issues no copy for them."""
    def kv_map(b, i, j):
        j = _last_kb_clamp(j, i, causal, block_q, block_k)
        return (b, j, 0)

    def mask_map(b, i, j):
        if not has_mask:
            return (b, 0, 0)
        return (b, 0, _last_kb_clamp(j, i, causal, block_q, block_k))
    return kv_map, mask_map


def _last_kb_clamp(j, i, causal, block_q, block_k):
    if causal:
        j = jnp.minimum(j, ((i + 1) * block_q - 1) // block_k)
    return j


def _pallas_fwd(q, k, v, kv_mask, causal, sm_scale, dropout_rate=0.0,
                dropout_seed=None, block_q=None, block_k=None,
                interpret=None):
    block_q, block_k = _resolve_blocks(block_q, block_k,
                                       q.shape[2], k.shape[2])
    """Returns (out [B,H,Tq,D], lse [B*H, Tq_padded])."""
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, Tq, D = q.shape
    qf, kf, vf, maskf, Tq_p, Tk_p, has_mask = _prep_padded(
        q, k, v, kv_mask, block_q, block_k)
    num_kb = Tk_p // block_k
    # ones-lane denominator (measured +28% on the D=64 seq-8192 fwd)
    ones_lane = _lane_pack_ok(D, dropout_rate)
    D_v = D + 1 if ones_lane else D
    if ones_lane:
        vf = _append_ones_lane(vf)

    kv_map, mask_map = _fwd_maps(causal, has_mask, block_q, block_k, num_kb)
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, sm_scale=sm_scale,
        causal=causal, dropout_rate=float(dropout_rate),
        block_q=block_q, num_kb=num_kb, has_mask=has_mask,
        ones_lane=ones_lane, head_dim=D)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            _sds((B * H, Tq_p, D), q.dtype, qf),
            _sds((B * H, 1, Tq_p), jnp.float32, qf),
        ],
        grid=(B * H, Tq_p // block_q, num_kb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # seed
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), kv_map),
            pl.BlockSpec((None, block_k, D_v), kv_map),
            pl.BlockSpec((None, 1, block_k), mask_map),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, 1, Tq_p), lambda b, i, j: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D_v), jnp.float32),
        ],
        interpret=interpret,
    )(_seed_arr(dropout_seed), qf, kf, vf, maskf)
    return out.reshape(B, H, Tq_p, D)[:, :, :Tq, :], lse


def mha_pallas(q, k, v, kv_mask=None, causal=False, sm_scale=None,
               block_q=None, block_k=None, interpret=None,
               dropout_rate=0.0, dropout_seed=None):
    """Flash-attention forward via pallas_call; grid (B*H, Tq/block_q)."""
    if not _HAVE_PALLAS:
        return mha_xla(q, k, v, kv_mask, causal, sm_scale)
    out, _ = _pallas_fwd(q, k, v, kv_mask, causal, sm_scale, dropout_rate,
                         dropout_seed, block_q, block_k, interpret)
    return out


# ---------------------------------------------------------------------------
# Pallas flash-attention backward kernels (FlashAttention-2 scheme)
# ---------------------------------------------------------------------------

def _flash_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, do_ref,
                         lse_ref, delta_ref, dq_ref, dq_scr, *,
                         sm_scale, causal, dropout_rate,
                         block_q, block_k, num_kb, has_mask):
    """Grid (B*H, nq, nk): dq accumulates across k-blocks in VMEM.
    Causal tiles above the diagonal skipped (no compute, no copies).

    NOTE(perf A/B, r4): packing a ``-delta`` column into do against a
    ones column in V (so do@v.T emits dp-delta via an idle MXU lane)
    was tried and REVERTED: it forces delta through the activation
    dtype, inflating bf16 dq/dk error 5x (rel maxdiff 0.037 vs 0.0075
    against the XLA chain), for no measured full-step gain — the D<128
    backward is MXU-half-fill bound, not VPU bound (PERF.md par.1)."""
    bh, qi, kb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    last = _last_kb(qi, causal=causal, block_q=block_q, block_k=block_k,
                    num_kb=num_kb)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(kb <= last)
    def _compute():
        s = _tile_scores(_scaled_q(q_ref, sm_scale), k_ref, mask_ref, qi, kb,
                         causal=causal, block_q=block_q, block_k=block_k,
                         has_mask=has_mask)
        lse = lse_ref[0, pl.dslice(qi * block_q, block_q)]
        delta = delta_ref[0, pl.dslice(qi * block_q, block_q)]
        p = jnp.exp2(s - lse[:, None])                      # [bq, bk]
        do = do_ref[:]
        v_blk = v_ref[:]
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            dp = dp * _tile_dropout(seed_ref, bh, qi, kb, dp.shape,
                                    dropout_rate)
        # d/dq of s2 = (q*scale*log2e)@k.T with p = exp2(s2-lse2):
        # dL/ds2 = p*(dp-delta)*ln2; chain through the log2e fold and the
        # ln2/log2e product cancels — ds/dq math is IDENTICAL to natural
        # units, so plain sm_scale scales dq (and dk below)
        ds = (p * (dp - delta[:, None])).astype(k_ref.dtype)
        dq_scr[:] += jnp.dot(ds, k_ref[:],
                             preferred_element_type=jnp.float32) * sm_scale

    @pl.when(kb == last)
    def _finish():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, do_ref,
                          lse_ref, delta_ref, dk_ref, dv_ref,
                          dk_scr, dv_scr, *,
                          sm_scale, causal, dropout_rate,
                          block_q, block_k, num_qb, has_mask):
    """Grid (B*H, nk, nq): dk/dv accumulate across q-blocks in VMEM.
    Causal q-blocks entirely above this k-block's diagonal are skipped."""
    bh, kb, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    first = _first_qb(kb, causal=causal, block_q=block_q, block_k=block_k,
                      num_qb=num_qb)

    @pl.when(qi == first)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(qi >= first)
    def _compute():
        s = _tile_scores(_scaled_q(q_ref, sm_scale), k_ref, mask_ref, qi, kb,
                         causal=causal, block_q=block_q, block_k=block_k,
                         has_mask=has_mask)
        lse = lse_ref[0, pl.dslice(qi * block_q, block_q)]
        delta = delta_ref[0, pl.dslice(qi * block_q, block_q)]
        p = jnp.exp2(s - lse[:, None])                      # [bq, bk]
        do = do_ref[:]
        v_blk = v_ref[:]
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # same (bh, qi, kb) seeding as forward/dq → identical bits
            drop = _tile_dropout(seed_ref, bh, qi, kb, p.shape, dropout_rate)
            dv_scr[:] += jnp.dot((p * drop).astype(do.dtype).T, do,
                                 preferred_element_type=jnp.float32)
            dp = dp * drop
        else:
            dv_scr[:] += jnp.dot(p.astype(do.dtype).T, do,
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(q_ref.dtype)
        dk_scr[:] += jnp.dot(ds.T, q_ref[:],
                             preferred_element_type=jnp.float32) * sm_scale

    @pl.when(qi == num_qb - 1)
    def _finish():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _pallas_bwd(q, k, v, kv_mask, out, lse, g, causal, sm_scale,
                dropout_rate=0.0, dropout_seed=None,
                block_q=None, block_k=None, interpret=None, delta=None):
    block_q, block_k = _resolve_blocks(block_q, block_k,
                                       q.shape[2], k.shape[2])
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, Tq, D = q.shape
    qf, kf, vf, maskf, Tq_p, Tk_p, has_mask = _prep_padded(
        q, k, v, kv_mask, block_q, block_k)
    gof, _ = _pad_to(g.reshape(B * H, Tq, D), block_q, 1)
    if delta is None:  # [BH, 1, Tq_p]; ring bwd hoists it across pairs
        outf, _ = _pad_to(out.reshape(B * H, Tq, D), block_q, 1)
        delta = jnp.sum(gof.astype(jnp.float32) * outf.astype(jnp.float32),
                        axis=-1)[:, None, :]
    num_qb, num_kb = Tq_p // block_q, Tk_p // block_k
    seed = _seed_arr(dropout_seed)

    kv_map, mask_map = _fwd_maps(causal, has_mask, block_q, block_k, num_kb)
    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        dropout_rate=float(dropout_rate), block_q=block_q, block_k=block_k,
        num_kb=num_kb, has_mask=has_mask)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=_sds((B * H, Tq_p, D), q.dtype, qf),
        grid=(B * H, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), kv_map),
            pl.BlockSpec((None, block_k, D), kv_map),
            pl.BlockSpec((None, 1, block_k), mask_map),
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, 1, Tq_p), lambda b, i, j: (b, 0, 0)),
            pl.BlockSpec((None, 1, Tq_p), lambda b, i, j: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(seed, qf, kf, vf, maskf, gof, lse, delta)

    def q_map(b, j, i):
        # clamp skipped above-diagonal q-blocks to this k-block's frontier
        # (same clamp as _first_qb, incl. the num_qb bound for Tk > Tq)
        if causal:
            i = jnp.maximum(i, _first_qb(j, causal=causal, block_q=block_q,
                                         block_k=block_k, num_qb=num_qb))
        return (b, i, 0)

    def qmask_map(b, j, i):
        return (b, 0, 0) if not has_mask else (b, 0, j)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
        dropout_rate=float(dropout_rate), block_q=block_q, block_k=block_k,
        num_qb=num_qb, has_mask=has_mask)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[
            _sds((B * H, Tk_p, D), k.dtype, kf),
            _sds((B * H, Tk_p, D), v.dtype, kf),
        ],
        grid=(B * H, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((None, block_q, D), q_map),
            pl.BlockSpec((None, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, 1, block_k), qmask_map),
            pl.BlockSpec((None, block_q, D), q_map),
            pl.BlockSpec((None, 1, Tq_p), lambda b, j, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, Tq_p), lambda b, j, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(seed, qf, kf, vf, maskf, gof, lse, delta)

    Tk = k.shape[2]
    dq = dq.reshape(B, H, Tq_p, D)[:, :, :Tq, :]
    dk = dk.reshape(B, H, Tk_p, D)[:, :, :Tk, :]
    dv = dv.reshape(B, H, Tk_p, D)[:, :, :Tk, :]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper: pallas forward AND pallas backward (O(block) memory)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, kv_mask, causal=False, sm_scale=None,
                    dropout_rate=0.0, dropout_seed=None):
    """Flash attention with optional in-kernel attention-prob dropout.
    ``dropout_seed``: int32 scalar/array; required when dropout_rate > 0
    (vary it per training step for fresh masks)."""
    if not _HAVE_PALLAS:
        return mha_xla(q, k, v, kv_mask, causal, sm_scale,
                       dropout_rate=dropout_rate, dropout_seed=dropout_seed)
    return mha_pallas(q, k, v, kv_mask, causal, sm_scale,
                      dropout_rate=dropout_rate, dropout_seed=dropout_seed)


def _fa_fwd(q, k, v, kv_mask, causal, sm_scale, dropout_rate, dropout_seed):
    if not _HAVE_PALLAS:
        out = mha_xla(q, k, v, kv_mask, causal, sm_scale,
                      dropout_rate=dropout_rate, dropout_seed=dropout_seed)
        return out, (q, k, v, kv_mask, dropout_seed, out, None)
    out, lse = _pallas_fwd(q, k, v, kv_mask, causal, sm_scale,
                           dropout_rate, dropout_seed)
    return out, (q, k, v, kv_mask, dropout_seed, out, lse)


def _fa_bwd(causal, sm_scale, dropout_rate, res, g):
    q, k, v, kv_mask, dropout_seed, out, lse = res
    if lse is None:  # no-pallas fallback: XLA recompute, same seed
        def f(q, k, v):
            return mha_xla(q, k, v, kv_mask, causal, sm_scale,
                           dropout_rate=dropout_rate,
                           dropout_seed=dropout_seed)
        _, vjp_fn = jax.vjp(f, q, k, v)
        dq, dk, dv = vjp_fn(g)
        return dq, dk, dv, None, None
    dq, dk, dv = _pallas_bwd(q, k, v, kv_mask, out, lse, g, causal, sm_scale,
                             dropout_rate, dropout_seed)
    return dq, dk, dv, None, None


flash_attention.defvjp(_fa_fwd, _fa_bwd)


# ---------------------------------------------------------------------------
# Ring attention: sequence-parallel over a mesh axis
# ---------------------------------------------------------------------------

RING_PAIR_SALT = (1000003, 7919)  # distinct odd primes per (q, kv) shard


def _pair_seed(seed0, q_idx, kv_idx):
    """Per-ordered-shard-pair dropout seed: the pallas kernels key masks on
    LOCAL tile coords, so without a distinct seed every (q-shard, kv-shard)
    pair would repeat the same dropout bits.  int32 wraparound is fine
    (the hash finalizer mixes)."""
    a, b = RING_PAIR_SALT
    return (seed0 + q_idx.astype(jnp.int32) * a
            + kv_idx.astype(jnp.int32) * b)


def _pvary(x, axis_name):
    """Mark a freshly-created (replicated) array as varying over the ring
    axis so it can enter ppermute/scan carries under shard_map's vma
    checking; identity where pvary is unavailable."""
    try:
        return jax.lax.pvary(x, axis_name)
    except (AttributeError, TypeError):
        return x


def _mass_lse(lse):
    """Kernel empty-row sentinel (+1e30, makes backward p==0) -> merge
    identity (-1e30 == log2 of zero probability mass)."""
    return jnp.where(lse > 1e29, jnp.float32(-1e30), lse)


def _kernel_lse(lse):
    """Inverse of _mass_lse for feeding the merged LSE back to the
    backward kernels."""
    return jnp.where(lse < -1e29, jnp.float32(1e30), lse)


def _merge_partial(o_a, lse_a, o_p, lse_p):
    """Online merge of two normalized partial attentions via their log2
    probability masses (the flash-decoding combine): out = sum_i o_i *
    2^(lse_i - lse_tot)."""
    lse_t = jnp.logaddexp2(lse_a, lse_p)
    o_t = (o_a * jnp.exp2(lse_a - lse_t) + o_p * jnp.exp2(lse_p - lse_t))
    return o_t, lse_t


def _ring_pair_fwd(q, k_blk, v_blk, m_blk, causal, sm_scale, rate, seed):
    """One (q-shard, kv-shard) partial via the Pallas flash kernel:
    returns (normalized f32 out, [B,H,S,1] log2-mass lse)."""
    out, lse = _pallas_fwd(q, k_blk, v_blk, m_blk, causal, sm_scale,
                           rate, seed)
    B, H, S, D = q.shape
    lse = lse[:, 0, :S].reshape(B, H, S, 1)
    return out.astype(jnp.float32), _mass_lse(lse)


def ring_attention(q, k, v, kv_mask, axis_name: str, causal=False,
                   sm_scale=None, dropout_rate=0.0, dropout_seed=None):
    """Blockwise ring attention (called under shard_map with the sequence
    dimension of q/k/v sharded over ``axis_name``).  Dispatches to the
    flash-kernel ring (``_ring_flash``); builds without pallas fall back
    to the pure-jnp blockwise ring (``_ring_xla``, differentiates
    through shard_map/ppermute natively).

    See ``_ring_flash`` for the kernel-path design."""
    if not _HAVE_PALLAS:
        return _ring_xla(q, k, v, kv_mask, axis_name, causal, sm_scale,
                         dropout_rate, dropout_seed)
    return _ring_flash(q, k, v, kv_mask, axis_name, causal, sm_scale,
                       dropout_rate, dropout_seed)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_flash(q, k, v, kv_mask, axis_name: str, causal=False,
                sm_scale=None, dropout_rate=0.0, dropout_seed=None):
    """Flash-kernel ring attention.

    Each device holds local shards [B,H,S/sp,D].  The local (diagonal)
    pair runs the Pallas flash kernel with in-kernel causal tile skip;
    K/V then rotate around the ring via ``lax.ppermute`` and every
    off-diagonal pair runs the SAME flash kernel (causal pairs entirely
    above the diagonal are skipped via ``lax.cond`` — no compute, no
    kernel launch).  Partials merge by their log2 softmax masses
    (``_merge_partial``), so no device ever materializes more than the
    kernel's [block_q, block_k] score tile — O(block) memory inside each
    shard, O(S/sp) activations per device.  Dropout uses the kernels'
    counter-hash with a per-shard-pair seed (no threefry, nothing
    stored).

    Backward (``_ring_bwd``): the same decomposition the flash backward
    uses over k-blocks, lifted to shard granularity — each pair calls
    the tiled ``_pallas_bwd`` with the GLOBAL merged lse/out/do (so
    per-pair probabilities are exact global softmax values), dq
    accumulates locally, and dk/dv accumulators rotate around the ring
    WITH their k/v shards, arriving home after a final ppermute.
    Reference role: long-context sequence parallelism, absent in the
    2018 reference (SURVEY.md par.5, par.7) — here first-class.
    """
    out, _ = _ring_fwd(q, k, v, kv_mask, axis_name, causal, sm_scale,
                       dropout_rate, dropout_seed)
    return out


def _ring_fwd(q, k, v, kv_mask, axis_name, causal, sm_scale,
              dropout_rate, dropout_seed):
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    if kv_mask is None:
        # fresh arrays are replicated; the ppermute'd scan carry needs the
        # mask varying over the ring axis (shard_map vma check)
        kv_mask = _pvary(jnp.ones((q.shape[0], k.shape[2]), jnp.float32),
                         axis_name)
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    seed0 = (jnp.zeros((), jnp.int32) if dropout_seed is None
             else jnp.asarray(dropout_seed, jnp.int32).reshape(()))

    o, lse = _ring_pair_fwd(q, k, v, kv_mask, causal, sm_scale,
                            dropout_rate, _pair_seed(seed0, idx, idx))
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(carry, t):
        k_c, v_c, m_c, o_a, lse_a = carry
        k_c = lax.ppermute(k_c, axis_name, perm)
        v_c = lax.ppermute(v_c, axis_name, perm)
        m_c = lax.ppermute(m_c, axis_name, perm)
        kv_i = (idx - t) % sp

        def compute(_):
            return _ring_pair_fwd(q, k_c, v_c, m_c, False, sm_scale,
                                  dropout_rate,
                                  _pair_seed(seed0, idx, kv_i))

        def skip(_):
            return (jnp.zeros_like(o_a), jnp.full_like(lse_a, -1e30))

        if causal:
            o_p, lse_p = lax.cond(kv_i < idx, compute, skip, None)
        elif jax.default_backend() != "tpu":
            # interpret-mode pallas: a BARE pallas call inside this scan
            # makes XLA's SPMD partitioner reject the module with
            # "PartitionId instruction is not supported" (the causal
            # branch never hits it because its call sits under lax.cond).
            # Route through a cond with a traced always-true predicate so
            # the off-TPU lowering matches the shape XLA accepts; TPU
            # keeps the straight-line call.
            o_p, lse_p = lax.cond(kv_i >= 0, compute, skip, None)
        else:
            o_p, lse_p = compute(None)
        o_a, lse_a = _merge_partial(o_a, lse_a, o_p, lse_p)
        return (k_c, v_c, m_c, o_a, lse_a), None

    (k_c, v_c, m_c, o, lse), _ = lax.scan(
        step, (k, v, kv_mask, o, lse), jnp.arange(1, sp))
    return o.astype(q.dtype), lse


def _ring_vjp_fwd(q, k, v, kv_mask, axis_name, causal, sm_scale,
                  dropout_rate, dropout_seed):
    out, lse = _ring_fwd(q, k, v, kv_mask, axis_name, causal, sm_scale,
                         dropout_rate, dropout_seed)
    return out, (q, k, v, kv_mask, dropout_seed, out, lse)


def _ring_vjp_bwd(axis_name, causal, sm_scale, dropout_rate, res, g):
    q, k, v, kv_mask, dropout_seed, out, lse = res
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    if kv_mask is None:
        # fresh arrays are replicated; the ppermute'd scan carry needs the
        # mask varying over the ring axis (shard_map vma check)
        kv_mask = _pvary(jnp.ones((q.shape[0], k.shape[2]), jnp.float32),
                         axis_name)
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    seed0 = (jnp.zeros((), jnp.int32) if dropout_seed is None
             else jnp.asarray(dropout_seed, jnp.int32).reshape(()))
    B, H, S, D = q.shape
    # merged lse back to kernel form, padded the way _pallas_bwd blocks it
    # (padded q rows: sentinel 1e30 -> p == 0 -> zero contributions)
    block_q, _ = _resolve_blocks(None, None, S, k.shape[2])
    Tq_p = S + (-S) % block_q
    lse_k = jnp.full((B * H, 1, Tq_p), 1e30, jnp.float32)
    lse_k = lse_k.at[:, :, :S].set(
        _kernel_lse(lse).reshape(B * H, 1, S))
    # delta depends only on (g, out) — identical across all sp pairs
    delta = jnp.zeros((B * H, 1, Tq_p), jnp.float32)
    delta = delta.at[:, :, :S].set(
        jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1).reshape(B * H, 1, S))

    dq, dk, dv = _pallas_bwd(q, k, v, kv_mask, out, lse_k, g, causal,
                             sm_scale, dropout_rate,
                             _pair_seed(seed0, idx, idx), delta=delta)
    dq = dq.astype(jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(carry, t):
        k_c, v_c, m_c, dk_a, dv_a, dq_a = carry
        k_c = lax.ppermute(k_c, axis_name, perm)
        v_c = lax.ppermute(v_c, axis_name, perm)
        m_c = lax.ppermute(m_c, axis_name, perm)
        dk_a = lax.ppermute(dk_a, axis_name, perm)
        dv_a = lax.ppermute(dv_a, axis_name, perm)
        kv_i = (idx - t) % sp

        def compute(_):
            dq_p, dk_p, dv_p = _pallas_bwd(
                q, k_c, v_c, m_c, out, lse_k, g, False, sm_scale,
                dropout_rate, _pair_seed(seed0, idx, kv_i), delta=delta)
            return (dq_p.astype(jnp.float32), dk_p.astype(jnp.float32),
                    dv_p.astype(jnp.float32))

        def skip(_):
            return (jnp.zeros_like(dq_a), jnp.zeros_like(dk_a),
                    jnp.zeros_like(dv_a))

        if causal:
            dq_p, dk_p, dv_p = lax.cond(kv_i < idx, compute, skip, None)
        elif jax.default_backend() != "tpu":
            # same routing as the forward scan (PR 6): a BARE pallas call
            # inside this scan makes XLA's SPMD partitioner reject the
            # off-TPU module with "PartitionId instruction is not
            # supported"; a traced always-true cond lowers to the shape
            # XLA accepts.  TPU keeps the straight-line call.
            dq_p, dk_p, dv_p = lax.cond(kv_i >= 0, compute, skip, None)
        else:
            dq_p, dk_p, dv_p = compute(None)
        return (k_c, v_c, m_c, dk_a + dk_p, dv_a + dv_p, dq_a + dq_p), None

    carry = (k, v, kv_mask, dk.astype(jnp.float32), dv.astype(jnp.float32),
             dq)
    (k_c, v_c, m_c, dk_a, dv_a, dq_a), _ = lax.scan(
        step, carry, jnp.arange(1, sp))
    # one more hop brings each shard's dk/dv accumulator home
    dk = lax.ppermute(dk_a, axis_name, perm).astype(k.dtype)
    dv = lax.ppermute(dv_a, axis_name, perm).astype(v.dtype)
    return dq_a.astype(q.dtype), dk, dv, None, None


_ring_flash.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


# ---------------------------------------------------------------------------
# Paged decode attention: one query token per request against a paged KV
# cache (the decode plane's hot op — paddle_tpu/decode)
# ---------------------------------------------------------------------------

# int8 KV dequant factor: quantized cache blocks store
# round(x / s * 127) codes (the kernels/quant.py scale convention),
# so x ≈ code * s / 127
_INV_QMAX = 1.0 / 127.0


def _decode_attn_kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, *rest,
                        block_tokens: int, sm_scale: float,
                        quantized: bool = False):
    """Grid (S, max_blocks): slot-major, blocks sequential minor — the
    online-softmax state for one slot lives in VMEM scratch across its
    block iterations (the flash discipline applied to the block TABLE
    axis).  The K/V index maps read the scalar-prefetched block table,
    so each grid step streams exactly ONE cache block — the gathered
    block list is never materialized.  Blocks past the slot's context
    frontier are skipped (index maps clamp to the frontier block, so
    the pipeline issues no copies for them either).

    ``quantized``: the cache blocks are int8 codes and two extra [1, H]
    scale refs follow the v ref (per-block-per-head abs-max from the
    parallel scale pool, same block-table index map) — the block is
    dequantized IN VMEM right after the copy lands (``code * s/127``),
    so HBM traffic per block is halved while scores still run in f32.

    Scores run in f32 natural units (a decode step is dispatch-bound,
    not VPU-bound — the flash kernel's exp2/ones-lane folds buy nothing
    at one query row per slot and would cost clarity)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    s = pl.program_id(0)
    j = pl.program_id(1)
    cl = cl_ref[s]
    last = jnp.maximum((cl - 1) // block_tokens, 0)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(j <= last)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * sm_scale        # [H, D]
        k_blk = k_ref[0].astype(jnp.float32)               # [bs, H, D]
        v_blk = v_ref[0].astype(jnp.float32)
        if quantized:
            k_blk = k_blk * (ks_ref[0][None, :, None] * _INV_QMAX)
            v_blk = v_blk * (vs_ref[0][None, :, None] * _INV_QMAX)
        # per-head scores over this block's tokens: [H, bs]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        pos = j * block_tokens + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        scores = jnp.where(pos < cl, scores, NEG_INF)
        m, acc = m_scr[:], acc_scr[:]
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)                         # [H, bs]
        alpha = jnp.exp(m - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # [H, bs] @ [bs, H, D] batched over H -> [H, D]
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc * alpha + pv

    @pl.when(j == last)
    def _finish():
        o_ref[0] = (acc_scr[:]
                    / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def paged_attention_xla(q, k_cache, v_cache, block_tables, context_lens,
                        sm_scale=None, k_scale=None, v_scale=None):
    """XLA gather fallback for :func:`decode_attention` (always
    available; also the parity reference the kernel is pinned to).

    q: [S, H, D]; k_cache/v_cache: [N_blocks, bs, H, D] (one layer);
    block_tables: [S, MB] int32; context_lens: [S] int32 → [S, H, D].
    With ``k_scale``/``v_scale`` ([N_blocks, H] f32, the int8 cache's
    parallel scale pools) the gathered codes are dequantized before the
    softmax — same math as the kernel's VMEM dequant.
    """
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    S, H, D = q.shape
    bs = k_cache.shape[1]
    MB = block_tables.shape[1]
    k = k_cache[block_tables]                    # [S, MB, bs, H, D]
    v = v_cache[block_tables]
    if k_scale is not None:
        k = (k.astype(jnp.float32)
             * (k_scale[block_tables][:, :, None, :, None] * _INV_QMAX))
        v = (v.astype(jnp.float32)
             * (v_scale[block_tables][:, :, None, :, None] * _INV_QMAX))
    k = k.reshape(S, MB * bs, H, D)
    v = v.reshape(S, MB * bs, H, D)
    s = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    pos = jnp.arange(MB * bs, dtype=jnp.int32)
    s = jnp.where(pos[None, None, :] < context_lens[:, None, None],
                  s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("sht,sthd->shd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _paged_attn_pallas(q, k_cache, v_cache, block_tables, context_lens,
                       sm_scale, interpret, k_scale=None, v_scale=None):
    S, H, D = q.shape
    bs = k_cache.shape[1]
    MB = block_tables.shape[1]
    bt = block_tables.astype(jnp.int32)
    cl = context_lens.astype(jnp.int32)
    quantized = k_scale is not None

    def kv_map(s, j, bt, cl):
        # clamp skipped past-frontier blocks to the frontier block: the
        # pipeline re-references the previous block, no copy issued
        jc = jnp.minimum(j, jnp.maximum((cl[s] - 1) // bs, 0))
        return (bt[s, jc], 0, 0, 0)

    def scale_map(s, j, bt, cl):
        jc = jnp.minimum(j, jnp.maximum((cl[s] - 1) // bs, 0))
        return (bt[s, jc], 0)

    in_specs = [
        pl.BlockSpec((1, H, D), lambda s, j, bt, cl: (s, 0, 0)),
        pl.BlockSpec((1, bs, H, D), kv_map),
        pl.BlockSpec((1, bs, H, D), kv_map),
    ]
    operands = [bt, cl, q, k_cache, v_cache]
    if quantized:
        # per-block-per-head scale rows ride the same prefetched block
        # table as the code blocks they dequantize
        in_specs += [pl.BlockSpec((1, H), scale_map),
                     pl.BlockSpec((1, H), scale_map)]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, D), lambda s, j, bt, cl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_attn_kernel, block_tokens=bs,
                               sm_scale=sm_scale, quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        interpret=interpret,
    )(*operands)


def _count_decode(name: str, n: int = 1) -> None:
    from ..observability import stats as _obs_stats
    _obs_stats.scope("decode").counter(name).inc(n)


# trace-time latch: a build fault disables the kernel for the process
# (counted ONCE per fault site, like kernels/sparse.py's per-stage
# fallbacks — a kernel fault can never fail a decode step)
_decode_attn_broken = False


def decode_attention(q, k_cache, v_cache, block_tables, context_lens,
                     sm_scale=None, interpret=None, impl=None,
                     k_scale=None, v_scale=None):
    """Paged decode attention: one query token per request against its
    gathered block list (scalar-prefetch block tables — module doc,
    ``_decode_attn_kernel``).

    q: [S, H, D] (S decode slots); k_cache/v_cache: [N_blocks,
    block_tokens, H, D] for ONE layer; block_tables: [S, MB] int32
    cache-block ids per slot; context_lens: [S] int32 valid tokens per
    slot (positions ≥ context_len masked).  Returns [S, H, D].

    ``k_scale``/``v_scale``: [N_blocks, H] f32 per-block-per-head
    abs-max pools when the cache stores int8 codes
    (``FLAGS_decode_kv_dtype=int8``); both paths dequantize with
    ``code * s/127`` — the kernel in VMEM after the block copy lands,
    the XLA fallback after the gather.

    ``impl``: None (pallas with counted XLA fallback — the
    kernels/sparse.py contract), "xla" (force the gather path),
    "pallas" (no fallback; tests).  Off-TPU the kernel runs in Pallas
    interpret mode like the flash kernels."""
    global _decode_attn_broken
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    if impl == "pallas" and not _HAVE_PALLAS:
        # the no-fallback contract must not pass vacuously on a build
        # without pallas (a parity test would compare XLA to XLA)
        raise RuntimeError(
            "decode_attention(impl='pallas'): pallas is unavailable "
            "in this build")
    if impl == "xla" or not _HAVE_PALLAS or \
            (impl is None and _decode_attn_broken):
        return paged_attention_xla(q, k_cache, v_cache, block_tables,
                                   context_lens, sm_scale,
                                   k_scale=k_scale, v_scale=v_scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    try:
        return _paged_attn_pallas(q, k_cache, v_cache, block_tables,
                                  context_lens, sm_scale, interpret,
                                  k_scale=k_scale, v_scale=v_scale)
    except Exception:
        if impl == "pallas":
            raise
        _decode_attn_broken = True
        _count_decode("attn_fallbacks")
        return paged_attention_xla(q, k_cache, v_cache, block_tables,
                                   context_lens, sm_scale,
                                   k_scale=k_scale, v_scale=v_scale)


def _ring_xla(q, k, v, kv_mask, axis_name, causal=False, sm_scale=None,
              dropout_rate=0.0, dropout_seed=None):
    """Pure-jnp blockwise ring (no-pallas fallback): K/V rotate via
    ppermute with online-softmax merging; per-pair scores materialize as
    [B,H,S/sp,S/sp] f32 (still O(S/sp) per device).  Counter-hash
    dropout keyed per shard pair (same bits family as mha_xla)."""
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    if kv_mask is None:
        # fresh arrays are replicated; the ppermute'd scan carry needs the
        # mask varying over the ring axis (shard_map vma check)
        kv_mask = _pvary(jnp.ones((q.shape[0], k.shape[2]), jnp.float32),
                         axis_name)
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    S_local = q.shape[2]

    def partial_attn(k_blk, v_blk, m_blk, kv_idx):
        s = (jnp.einsum("bhqd,bhkd->bhqk", q, k_blk).astype(jnp.float32)
             * sm_scale)
        s = jnp.where(m_blk[:, None, None, :] > 0, s, NEG_INF)
        if causal:
            qi = jnp.arange(S_local)[:, None] + idx * S_local
            ki = jnp.arange(S_local)[None, :] + kv_idx * S_local
            s = jnp.where(qi >= ki, s, NEG_INF)
        m_new = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m_new)
        l_new = jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate and dropout_rate > 0.0:
            seed = (jnp.zeros((), jnp.int32) if dropout_seed is None
                    else jnp.asarray(dropout_seed, jnp.int32).reshape(()))
            p = p * _hash_dropout(
                seed, idx * 131071 + kv_idx, p.shape, dropout_rate)
        o_new = jnp.einsum("bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        return m_new, l_new, o_new

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(carry, _):
        m, l, o, k_cur, v_cur, mask_cur, kv_idx = carry
        m_p, l_p, o_p = partial_attn(k_cur, v_cur, mask_cur, kv_idx)
        m_new = jnp.maximum(m, m_p)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_p - m_new)
        l_new = l * alpha + l_p * beta
        o_new = o * alpha + o_p * beta
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = lax.ppermute(mask_cur, axis_name, perm)
        kv_nxt = lax.ppermute(kv_idx, axis_name, perm)
        return (m_new, l_new, o_new, k_nxt, v_nxt, mask_nxt, kv_nxt), None

    qf = q.astype(jnp.float32)
    m0 = jnp.full_like(qf[..., :1], NEG_INF)
    l0 = jnp.zeros_like(qf[..., :1])
    o0 = jnp.zeros_like(qf)
    carry = (m0, l0, o0, k, v, kv_mask, idx)
    (m, l, o, *_), _ = lax.scan(step, carry, None, length=sp)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)
