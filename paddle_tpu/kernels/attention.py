"""Fused / flash / ring attention — the framework's hot-op kernel story.

Reference precedent: the CPU JIT kernel library
(``paddle/fluid/operators/math/jit_kernel*`` — hand-tuned kernels behind a
dispatch layer) and the cuDNN library_type kernels.  Here the hot op is
attention; three implementations sit behind one function:

- ``xla``:    plain jnp einsum/softmax chain (XLA fuses; always available)
- ``pallas``: tiled online-softmax flash-attention kernel (MXU-sized tiles,
              VMEM accumulators; interpret mode off-TPU)
- ``ring``:   sequence-parallel attention over a mesh axis — K/V shards
              rotate around the ring via ``lax.ppermute`` with online
              softmax merging, so attention over sequence length S uses
              O(S/sp) memory per chip.  This is the long-context scaling
              mechanism (SURVEY.md §5: absent in the 2018 reference,
              required here as first-class).

Gradients: ``jax.custom_vjp`` — forward may run the Pallas kernel; backward
recomputes with the XLA math (flash-style recompute; a Pallas backward
kernel is a later optimization).  Ring attention differentiates through
shard_map/ppermute natively.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# plain XLA implementation (also the custom_vjp backward math)
# ---------------------------------------------------------------------------

def mha_xla(q, k, v, kv_mask=None, causal=False, sm_scale=None,
            q_offset=0, kv_offset=0):
    """q,k,v: [B,H,Tq|Tk,D]; kv_mask: [B,Tk] 1/0; returns [B,H,Tq,D].

    q_offset/kv_offset give global positions for causal masking when the
    sequence is sharded (ring attention)."""
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, NEG_INF)
    if causal:
        qi = jnp.arange(q.shape[2])[:, None] + q_offset
        ki = jnp.arange(k.shape[2])[None, :] + kv_offset
        s = jnp.where(qi >= ki, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas flash-attention forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref,
                      m_scr, l_scr, acc_scr, *,
                      sm_scale: float, causal: bool,
                      block_q: int, block_k: int, num_kb: int):
    """Grid (B*H, nq, nk); K/V stream through VMEM one block_k tile at a
    time (nk is the sequential minor grid axis on TPU, so the online-softmax
    state lives in VMEM scratch across k iterations — O(block) memory at any
    sequence length)."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[:].astype(jnp.float32) * sm_scale
    k_blk = k_ref[:].astype(jnp.float32)
    v_blk = v_ref[:].astype(jnp.float32)
    s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
    mask = mask_ref[0, :]
    s = jnp.where(mask[None, :] > 0, s, NEG_INF)
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

    m, l, acc = m_scr[:], l_scr[:], acc_scr[:]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    m_scr[:] = m_new
    l_scr[:] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc * alpha + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)

    @pl.when(kb == num_kb - 1)
    def _finish():
        o_ref[:] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


try:  # pallas import kept lazy-safe for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def _pad_to(x, multiple, axis):
    rem = x.shape[axis] % multiple
    if rem == 0:
        return x, 0
    pad = multiple - rem
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), pad


def mha_pallas(q, k, v, kv_mask=None, causal=False, sm_scale=None,
               block_q=128, block_k=128, interpret=None):
    """Flash-attention forward via pallas_call; grid (B*H, Tq/block_q)."""
    if not _HAVE_PALLAS:
        return mha_xla(q, k, v, kv_mask, causal, sm_scale)
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if kv_mask is None:
        kv_mask = jnp.ones((B, Tk), jnp.float32)

    q4, pad_q = _pad_to(q, block_q, 2)
    k4, pad_k = _pad_to(k, block_k, 2)
    v4, _ = _pad_to(v, block_k, 2)
    mask2, _ = _pad_to(kv_mask.astype(jnp.float32), block_k, 1)
    Tq_p, Tk_p = q4.shape[2], k4.shape[2]
    num_kb = Tk_p // block_k

    qf = q4.reshape(B * H, Tq_p, D)
    kf = k4.reshape(B * H, Tk_p, D)
    vf = v4.reshape(B * H, Tk_p, D)
    maskf = jnp.repeat(mask2[:, None, :], H, axis=1).reshape(B * H, 1, Tk_p)

    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, sm_scale=sm_scale,
        causal=causal, block_q=block_q, num_kb=num_kb)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
        grid=(B * H, Tq_p // block_q, num_kb),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, 1, block_k), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, maskf)
    out = out.reshape(B, H, Tq_p, D)
    return out[:, :, :Tq, :]


# ---------------------------------------------------------------------------
# custom-vjp wrapper: pallas forward, XLA-recompute backward
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_attention(q, k, v, kv_mask, causal=False, sm_scale=None):
    return mha_pallas(q, k, v, kv_mask, causal, sm_scale)


def _fa_fwd(q, k, v, kv_mask, causal, sm_scale):
    out = mha_pallas(q, k, v, kv_mask, causal, sm_scale)
    return out, (q, k, v, kv_mask)


def _fa_bwd(causal, sm_scale, res, g):
    q, k, v, kv_mask = res
    # recompute with the XLA math and differentiate it (flash recompute)
    def f(q, k, v):
        return mha_xla(q, k, v, kv_mask, causal, sm_scale)
    _, vjp_fn = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp_fn(g)
    return dq, dk, dv, None


flash_attention.defvjp(_fa_fwd, _fa_bwd)


# ---------------------------------------------------------------------------
# Ring attention: sequence-parallel over a mesh axis
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, kv_mask, axis_name: str, causal=False,
                   sm_scale=None):
    """Blockwise ring attention (to be called under shard_map with the
    sequence dimension sharded over ``axis_name``).

    Each device holds local q/k/v shards [B,H,S/sp,D].  K/V rotate around
    the ring; partial attention outputs merge with online softmax, so no
    device ever materializes full-sequence scores — O(S/sp) memory.
    """
    if sm_scale is None:
        sm_scale = float(1.0 / np.sqrt(q.shape[-1]))
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    S_local = q.shape[2]

    def partial_attn(k_blk, v_blk, m_blk, kv_idx):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk).astype(jnp.float32) * sm_scale
        s = jnp.where(m_blk[:, None, None, :] > 0, s, NEG_INF)
        if causal:
            qi = jnp.arange(S_local)[:, None] + idx * S_local
            ki = jnp.arange(S_local)[None, :] + kv_idx * S_local
            s = jnp.where(qi >= ki, s, NEG_INF)
        m_new = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m_new)
        l_new = jnp.sum(p, axis=-1, keepdims=True)
        o_new = jnp.einsum("bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        return m_new, l_new, o_new

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(carry, _):
        m, l, o, k_cur, v_cur, mask_cur, kv_idx = carry
        m_p, l_p, o_p = partial_attn(k_cur, v_cur, mask_cur, kv_idx)
        m_new = jnp.maximum(m, m_p)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_p - m_new)
        l_new = l * alpha + l_p * beta
        o_new = o * alpha + o_p * beta
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = lax.ppermute(mask_cur, axis_name, perm)
        kv_nxt = lax.ppermute(kv_idx, axis_name, perm)
        return (m_new, l_new, o_new, k_nxt, v_nxt, mask_nxt, kv_nxt), None

    B, H, S, D = q.shape
    # derive inits from q so shard_map's varying-axis inference matches the
    # ppermute-produced carries
    qf = q.astype(jnp.float32)
    m0 = jnp.full_like(qf[..., :1], NEG_INF)
    l0 = jnp.zeros_like(qf[..., :1])
    o0 = jnp.zeros_like(qf)
    carry = (m0, l0, o0, k, v, kv_mask, idx)
    (m, l, o, *_), _ = lax.scan(step, carry, None, length=sp)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)
