"""Low-precision serving kernels: fused-dequant int8 matmul + KV helpers.

ROADMAP item 3's serving legs.  QAT fake-quant (``ops/quant_ops.py``,
``contrib/quantize.py``) models int8 numerics during training but every
inference matmul still runs f32 — nothing is faster for having
quantized.  This module is where low precision starts paying rent:

- ``int8_fc``: ONE Pallas launch computes a calibrated FC layer as an
  int8 x int8 -> int32 MXU matmul with a fused dequant(+bias+activation)
  epilogue.  Weights arrive pre-quantized (per-out-channel abs-max
  scales, derived by the ``quantize_int8`` calibration pass in
  ``inference/passes.py``); activations quantize per dispatch with the
  QAT moving-average scale when one was calibrated, else dynamically
  from the batch abs-max (one traced reduction — no recompiles, the
  scale is data, not shape).
- ``Int8Plan``: the ``core/lowering.py`` peephole over calibrated
  mul/fused_fc ops (the ops the calibration pass stamped), mirroring
  the sparse-fusion plan contract: ``covers(pos)`` / ``lower(pos, env)``
  with per-op fallback to the untouched f32 lowering on any fault.
- KV-cache qdq helpers (``kv_quantize``/``kv_dequantize``/
  ``kv_head_amax``): ONE definition of the int8 round-trip shared by
  the paged cache writers (``decode/model.py``), the quantized paged
  decode-attention kernel (``kernels/attention.py``) and the tests, so
  the storage and compute planes can never disagree on scale semantics.

Scale semantics everywhere (the ``_qdq`` convention of
``ops/quant_ops.py``, r=127): ``q = clip(round(x / s * 127), -127, 127)``
and ``x ~= q * s / 127`` where ``s`` is a float abs-max.  A matmul of
two such codes dequantizes with ``s_x * s_w[j] / 127^2`` per out
channel j — exactly what the epilogue applies, so the kernel reproduces
the QAT fake-quant reference to f32 rounding.

Fallback contract (the ``kernels/sparse.py`` discipline): every entry
point degrades on any build/trace fault — ``int8_fc`` returns ``None``
(counted ``quant.matmul_fallbacks``) and the caller takes
``int8_fc_xla``, the same quantized math as plain XLA ops (counted
``quant.xla_dequant``); the peephole returns False (counted
``quant.lower_fallbacks``) to re-lower the op through the untouched f32
path.  A kernel fault can never fail a dispatch.  Off-TPU the kernel
runs in Pallas interpret mode (tier-1 CPU coverage).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..observability import stats as _obs_stats
from ..observability import trace as _obs_trace

try:  # pallas import kept lazy-safe for exotic builds
    from jax.experimental import pallas as pl  # noqa: F401
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

__all__ = [
    "enabled_for",
    "count_runtime_disable",
    "quantize_weight",
    "clip_fraction",
    "int8_fc",
    "int8_fc_xla",
    "plan_int8",
    "Int8Plan",
    "kv_quantize",
    "kv_dequantize",
    "kv_head_amax",
    "note_calibration",
    "calibrations",
    "note_kv_cache",
    "quantz",
]

# the qdq code range of ops/quant_ops.py (r = (1 << 7) - 1)
QMAX = 127
# floor on every scale so an all-zero channel/block divides cleanly
# (same epsilon _qdq uses)
SCALE_EPS = 1e-8

# activations the fused epilogue implements; anything else (or any act
# carrying attrs, e.g. leaky_relu alpha) falls back per-op
_EPILOGUE_ACTS = {
    "": lambda x: x,
    "relu": lambda x: jnp.maximum(x, 0.0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
}

# whole-operand VMEM budget for the single-launch kernel; bigger
# problems take the XLA dequantized path (still quantized math)
_VMEM_BUDGET_BYTES = 8 << 20

_telemetry_on = _obs_trace.flags_on

# pull-mirror of the quant.* counters so /quantz renders without
# scraping the metrics registry (and regardless of FLAGS_runtime_stats)
_COUNTERS: Dict[str, int] = {}


def _count(name: str, n: int = 1) -> None:
    _COUNTERS[name] = _COUNTERS.get(name, 0) + n
    if _telemetry_on():
        _obs_stats.scope("quant").counter(name).inc(n)


def enabled_for(ctx) -> bool:
    """Per-lowering gate for the int8 peephole.  Activation is driven by
    the op attrs the calibration pass stamped (so an uncalibrated
    program can never change), gated off under a mesh (GSPMD cannot
    partition the custom call) and on fault-recovery re-lowers (the
    executor sets ``ctx.disable_int8_fused`` when retrying a step whose
    compile died with the quant kernels in it)."""
    return (ctx.mesh is None
            and not getattr(ctx, "disable_int8_fused", False))


def count_runtime_disable() -> None:
    """A whole-step compile fault surfaced AFTER trace time (Mosaic/XLA,
    only reachable on a real TPU backend) is recovered by re-lowering
    without the int8 kernels; counted so the degrade is loud."""
    _count("runtime_disables")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# calibration (pass-time, numpy): per-out-channel weight quantization
# ---------------------------------------------------------------------------

def quantize_weight(w):
    """Quantize a 2-D [K, N] FC weight per OUT channel (per column).

    Returns ``(q, scales)``: ``q`` int8 [K, N], ``scales`` f32 [N]
    abs-max per column — the axis that factors out of ``x @ w`` so the
    dequant rides the epilogue, not the accumulation."""
    w = np.asarray(w, dtype=np.float32)
    if w.ndim != 2:
        raise ValueError(f"int8 FC weight must be 2-D, got {w.shape}")
    scales = np.maximum(np.max(np.abs(w), axis=0), SCALE_EPS)
    q = np.clip(np.round(w / scales[None, :] * QMAX),
                -QMAX, QMAX).astype(np.int8)
    return q, scales.astype(np.float32)


def clip_fraction(q) -> float:
    """Fraction of quantized codes at the clip boundary (|q| == 127) —
    the /quantz saturation signal: a high fraction means the abs-max
    scale is dominated by outliers and the layer deserves a look."""
    q = np.asarray(q)
    if q.size == 0:
        return 0.0
    return float(np.mean(np.abs(q.astype(np.int32)) >= QMAX))


# ---------------------------------------------------------------------------
# the fused-dequant int8 matmul
# ---------------------------------------------------------------------------

def _fc_kernel(x_ref, w_ref, dq_ref, b_ref, o_ref, *, act):
    # int8 x int8 -> int32 on the MXU, dequant+bias+act in the epilogue
    acc = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * dq_ref[:] + b_ref[:]
    o_ref[:] = _EPILOGUE_ACTS[act](out)


def _quantize_act(x, in_scale: float):
    """Per-dispatch activation quantization: the calibrated
    moving-average scale when the QAT stats provided one, else the
    batch abs-max (dynamic — a traced reduction, never a new shape)."""
    if in_scale and in_scale > 0.0:
        sx = jnp.float32(in_scale)
    else:
        sx = jnp.maximum(jnp.max(jnp.abs(x)).astype(jnp.float32),
                         SCALE_EPS)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx * QMAX),
                  -QMAX, QMAX).astype(jnp.int8)
    return xq, sx


def int8_fc(x, w_q, w_scale, in_scale: float = 0.0, bias=None,
            act: str = "", interpret=None):
    """Fused-dequant int8 FC: ONE Pallas launch, or ``None`` (counted)
    when the launch cannot be built — the caller then takes
    ``int8_fc_xla`` (same math, plain XLA ops).

    ``x`` f32 [M, K]; ``w_q`` int8 [K, N]; ``w_scale`` f32 [N];
    ``bias`` f32 [N] or None; ``act`` one of the epilogue set."""
    if not _HAVE_PALLAS:
        _count("matmul_fallbacks")
        return None
    try:
        if x.ndim != 2 or w_q.ndim != 2 or act not in _EPILOGUE_ACTS:
            raise ValueError("int8_fc needs 2-D operands / known act")
        m, k = int(x.shape[0]), int(x.shape[1])
        n = int(w_q.shape[1])
        if int(w_q.shape[0]) != k:
            raise ValueError("int8_fc shape mismatch")
        # whole-operand launch: int8 x + int8 w + f32 out (+ epilogue
        # vectors) must fit the VMEM budget; bigger shapes fall back
        if m * k + k * n + 4 * (m * n + 2 * n) > _VMEM_BUDGET_BYTES:
            raise ValueError("int8_fc operands exceed the VMEM budget")
        if interpret is None:
            interpret = _interpret()
        xq, sx = _quantize_act(x, in_scale)
        dq = (sx * w_scale.astype(jnp.float32) / (QMAX * QMAX))
        b = (bias.astype(jnp.float32) if bias is not None
             else jnp.zeros((n,), jnp.float32))
        out = pl.pallas_call(
            functools.partial(_fc_kernel, act=act),
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
            interpret=interpret,
        )(xq, w_q, dq.reshape(1, n), b.reshape(1, n))
        _count("matmul_launches")
        return out
    except Exception:
        _count("matmul_fallbacks")
        return None


def int8_fc_xla(x, w_q, w_scale, in_scale: float = 0.0, bias=None,
                act: str = ""):
    """The counted fallback: identical quantized math through plain XLA
    ops (int8 codes widened to f32 for the dot — XLA's portable int8
    story).  Also the dequantized reference the parity tests pin the
    kernel against."""
    xq, sx = _quantize_act(x, in_scale)
    acc = jnp.dot(xq.astype(jnp.float32), w_q.astype(jnp.float32))
    out = acc * (sx * w_scale.astype(jnp.float32) / (QMAX * QMAX))
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    _count("xla_dequant")
    return _EPILOGUE_ACTS[act](out)


# ---------------------------------------------------------------------------
# block-level peephole over calibrated mul / fused_fc ops
# ---------------------------------------------------------------------------

class Int8Plan:
    """Peephole plan for a block: ops the ``quantize_int8`` calibration
    pass stamped (``quant_int8`` attr + WInt8/WScale sidecar inputs)
    lower through the fused-dequant int8 matmul.

    ``core/lowering.py`` consults ``covers(pos)`` per op and calls
    ``lower(pos, env)`` — True fills the op's output into ``env``
    (Pallas launch, or the counted XLA dequantized path on a build
    fault); False (counted) lets the op lower through the untouched
    f32 path."""

    def __init__(self, positions):
        self._pos = dict(positions)  # block-op index -> op

    def covers(self, pos: int) -> bool:
        return pos in self._pos

    def lower(self, pos: int, env: dict) -> bool:
        op = self._pos[pos]
        try:
            if op.type == "fused_fc":
                x_name = op.inputs["X"][0]
                bias = env[op.inputs["Bias"][0]]
                act = op.attrs.get("act", "") or ""
                # op_role is bookkeeping every op carries, not a real
                # activation parameter
                if any(k != "op_role"
                       for k in (op.attrs.get("act_attrs") or {})):
                    raise ValueError("act attrs not in the epilogue set")
            else:  # mul
                x_name = op.inputs["X"][0]
                bias = None
                act = ""
            if act not in _EPILOGUE_ACTS:
                raise ValueError(f"unsupported epilogue act {act!r}")
            if int(op.attrs.get("y_num_col_dims", 1)) != 1:
                raise ValueError("int8 FC needs y_num_col_dims == 1")
            w_q = env[op.inputs["WInt8"][0]]
            w_scale = env[op.inputs["WScale"][0]]
            x = env[x_name]
            xnc = int(op.attrs.get("x_num_col_dims", 1))
            lead = tuple(int(d) for d in x.shape[:xnc])
            xm = x.reshape((int(np.prod(lead)) if lead else 1, -1))
            in_scale = float(op.attrs.get("in_scale", 0.0))
            if bias is not None:
                bias = bias.reshape(-1)
            out = int8_fc(xm, w_q, w_scale, in_scale, bias, act)
            if out is None:
                out = int8_fc_xla(xm, w_q, w_scale, in_scale, bias, act)
            n = int(w_q.shape[1])
            env[op.outputs["Out"][0]] = out.reshape(lead + (n,))
            return True
        except Exception:
            _count("lower_fallbacks")
            return False


def plan_int8(block):
    """Scan ``block`` for calibrated ops; an ``Int8Plan`` or None.  An
    op qualifies only with the full calibration stamp (attr + both
    sidecar inputs) — a half-stamped op lowers f32."""
    positions = []
    for pos, op in enumerate(block.ops):
        if op.type not in ("mul", "fused_fc"):
            continue
        if not op.attrs.get("quant_int8"):
            continue
        if not op.inputs.get("WInt8") or not op.inputs.get("WScale"):
            continue
        positions.append((pos, op))
    return Int8Plan(positions) if positions else None


# ---------------------------------------------------------------------------
# KV-cache int8 round-trip: ONE definition of the scale semantics
# ---------------------------------------------------------------------------

def kv_head_amax(rows):
    """Per-head abs-max of KV rows [..., H, D] -> [..., H] (the scale a
    block stores for each head)."""
    return jnp.maximum(jnp.max(jnp.abs(rows.astype(jnp.float32)),
                               axis=-1), SCALE_EPS)


def kv_quantize(rows, scales):
    """Quantize KV rows [..., H, D] with per-head scales [..., H] ->
    int8 codes (the storage form of the paged cache)."""
    s = jnp.maximum(scales.astype(jnp.float32), SCALE_EPS)[..., None]
    q = jnp.round(rows.astype(jnp.float32) / s * QMAX)
    return jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)


def kv_dequantize(q, scales):
    """Dequantize int8 KV codes [..., H, D] with per-head scales
    [..., H] -> f32 rows (what the attention kernel computes against)."""
    s = jnp.maximum(scales.astype(jnp.float32), SCALE_EPS)[..., None]
    return q.astype(jnp.float32) * s / QMAX


# ---------------------------------------------------------------------------
# /quantz observability payload
# ---------------------------------------------------------------------------

# per-layer calibration records appended by the quantize_int8 pass
# (bounded: one per calibrated op per pass run; reset on each pass run
# of the same program would double-count, so records carry the op's
# weight var name and the page shows the latest per name)
_CALIB: List[dict] = []
_CALIB_CAP = 256

# KV caches note their geometry here at construction (keyed by engine
# name) so /quantz shows the storage-plane dtype + bytes/block next to
# the compute-plane scales
_KV_INFO: Dict[str, dict] = {}


def note_calibration(rec: dict) -> None:
    _CALIB.append(dict(rec))
    del _CALIB[:-_CALIB_CAP]


def calibrations() -> List[dict]:
    return list(_CALIB)


def note_kv_cache(name: str, info: dict) -> None:
    _KV_INFO[name] = dict(info)


def quantz() -> dict:
    """The /quantz debug-page payload: per-layer calibration records
    (scales, clip fractions), the quant.* counter mirror, and every
    noted KV cache's dtype + bytes/block."""
    latest: Dict[str, dict] = {}
    for rec in _CALIB:
        latest[str(rec.get("weight", len(latest)))] = rec
    return {
        "calibrated_layers": list(latest.values()),
        "counters": dict(_COUNTERS),
        "kv_caches": {k: dict(v) for k, v in _KV_INFO.items()},
    }


def quantz_text() -> str:
    """Human rendering of :func:`quantz` (the ``?text=1`` form, the
    allocz/capacityz pattern)."""
    z = quantz()
    lines = ["== int8 calibration =="]
    if not z["calibrated_layers"]:
        lines.append("  (no calibrated layers)")
    for rec in z["calibrated_layers"]:
        lines.append(
            "  {op:<10} w={weight}  shape={shape}  act={act!r}  "
            "in_scale={in_scale:.6g}  w_scale=[{lo:.4g}, {hi:.4g}]  "
            "clip={clip:.4%}".format(
                op=rec.get("op", "?"), weight=rec.get("weight", "?"),
                shape=rec.get("shape"), act=rec.get("act", ""),
                in_scale=float(rec.get("in_scale", 0.0)),
                lo=float(rec.get("w_scale_min", 0.0)),
                hi=float(rec.get("w_scale_max", 0.0)),
                clip=float(rec.get("clip_fraction", 0.0))))
    lines.append("== quant.* counters ==")
    if not z["counters"]:
        lines.append("  (none)")
    for k in sorted(z["counters"]):
        lines.append(f"  {k:<24} {z['counters'][k]}")
    lines.append("== quantized KV caches ==")
    if not z["kv_caches"]:
        lines.append("  (none)")
    for name in sorted(z["kv_caches"]):
        info = z["kv_caches"][name]
        lines.append("  {n}: dtype={d}  blocks={b}  "
                     "bytes/block={bb}  pool={p}".format(
                         n=name, d=info.get("dtype"),
                         b=info.get("num_blocks"),
                         bb=info.get("bytes_per_block"),
                         p=info.get("pool_bytes")))
    return "\n".join(lines) + "\n"
