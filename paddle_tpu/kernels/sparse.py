"""Fused Pallas sparse-embedding kernels: multi-table gather + lazy update.

The DeepFM sparse path's binding term is the COUNT of scatter-class ops
(~1 ms flat each through the tunneled chip) plus the full-table HBM sweeps
of the masked-dense lazy update (PERF.md §5/§8).  This module is the
TPU-native analogue of the reference's ``SelectedRows`` CPU functors
(``operators/math/selected_rows_functor.cc``) — the same move the flash
attention path made for the hot attention op:

- ``fused_gather``: ONE Pallas launch gathers the same id batch from k
  tables (both DeepFM tables per step), amortizing the flat dispatch cost
  of per-table XLA gathers.  Grid = one sorted-position per id; each grid
  step's input block is selected by a scalar-prefetch dynamic index map
  (``PrefetchScalarGridSpec``), so the pipeline streams exactly the
  touched rows.
- ``fused_adam`` / ``fused_momentum`` / ``fused_adagrad``: ONE Pallas
  launch per table replaces the whole per-table update chain (sorted
  path: 3 gathers + 3 scatter-sets + argsort + 2 segment ops; masked
  dense: scatter-add + ~7 full-table HBM sweeps).  Ids are sorted on
  device (argsort + reorder gathers — no scatter-class ops anywhere),
  segment boundaries are marked with first/last flags, and the kernel
  walks the sorted positions accumulating duplicate rows in VMEM
  (the ``merge_rows`` segment-sum formulation, done in-kernel in the
  same left-to-right order) and, at each segment's last position,
  applies the duplicate-exact lazy moment math and writes params +
  moments back through ``input_output_aliases`` — untouched table rows
  are never read or written.

Index-map discipline (why the in-place aliasing is hazard-free): rows are
processed in sorted order, so output block indices are non-decreasing and
every row's block is visited by exactly one run of consecutive grid steps.
Within a run the block index does not change, so Mosaic's revisiting
semantics keep the block in VMEM (one write-back per touched row at the
index change); across runs, all future input rows are strictly greater
than all already-written rows, so prefetches can never race a write-back.

Semantics notes:
- duplicate handling is exact: per-row gradients sum once (in sorted ==
  original order for equal ids — ``jnp.argsort`` is stable), then the
  optimizer math applies once per unique row, matching
  ``merge_rows``-then-update bit-for-bit on f32 tables.
- out-of-range ids (they come from user FEED data — a data bug must
  fail loudly on either path): ``fused_gather`` matches ``jnp.take``
  mode="fill" — ids in [-H, H) wrap-then-gather, anything else yields
  a NaN row (float tables; integer tables clamp), so the PR-7 NaN
  sentinel fires exactly as it does flag-off.  The update kernel clamps
  a malformed id to an edge row instead of dropping it — but the NaN
  forward already poisoned that step's loss AND gradient rows, so the
  loud failure precedes any silently-misdirected update.
- every entry point degrades to ``None`` (caller falls back to the
  existing masked-dense / sorted paths) on any build/trace fault, with a
  ``sparse_fused.*_fallbacks`` counter — a kernel fault can never fail a
  step.  Off-TPU the kernels run in Pallas interpret mode (tier-1 CPU
  coverage), like ``kernels/attention.py``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import flags
from ..observability import stats as _obs_stats
from ..observability import trace as _obs_trace

try:  # pallas import kept lazy-safe for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

__all__ = [
    "fused_enabled",
    "enabled_for",
    "count_runtime_disable",
    "fused_gather",
    "fused_adam",
    "fused_momentum",
    "fused_adagrad",
    "plan_lookup_fusion",
    "LookupFusion",
    "jaxpr_census",
]


def jaxpr_census(jaxpr):
    """(scatter-class eqn count, pallas launch count) over ``jaxpr`` and
    every sub-jaxpr.  ONE definition on purpose: this census is both the
    ISSUE-10 acceptance pin (tests/test_sparse.py) and the structural
    evidence in the ``deepfm_fused`` bench analysis artifact — the two
    must never drift apart."""
    n_scatter = n_pallas = 0
    for eq in jaxpr.eqns:
        nm = str(eq.primitive)
        n_scatter += nm.startswith("scatter")
        n_pallas += nm == "pallas_call"
        for v in eq.params.values():
            for leaf in jax.tree_util.tree_leaves(
                    v, is_leaf=lambda x: hasattr(x, "eqns")
                    or hasattr(x, "jaxpr")):
                inner = getattr(leaf, "jaxpr", leaf)
                if hasattr(inner, "eqns"):
                    s, p = jaxpr_census(inner)
                    n_scatter += s
                    n_pallas += p
    return n_scatter, n_pallas

_telemetry_on = _obs_trace.flags_on


def _count(name: str, n: int = 1) -> None:
    if _telemetry_on():
        _obs_stats.scope("sparse_fused").counter(name).inc(n)


def fused_enabled() -> bool:
    """Trace-time gate: the flag is read when a program lowers, so cached
    executables keep the path they compiled with (same contract as
    FLAGS_sparse_dense_update_max_elems)."""
    if not _HAVE_PALLAS:
        return False
    return bool(flags.get_flags("sparse_fused_kernel"))


def enabled_for(ctx) -> bool:
    """Per-lowering gate: flag on, no mesh (GSPMD cannot partition the
    custom calls), and not a fault-recovery re-lower (the executor sets
    ``ctx.disable_sparse_fused`` when retrying a step whose compile died
    with the fused kernels in it — see Executor._recover_disk_entry)."""
    return (fused_enabled() and ctx.mesh is None
            and not getattr(ctx, "disable_sparse_fused", False))


def count_runtime_disable() -> None:
    """A whole-step compile fault surfaced AFTER trace time (Mosaic/XLA,
    only reachable on a real TPU backend) is recovered by the executor
    re-lowering without the fused kernels; counted here so the degrade
    is as loud as the trace-time fallbacks."""
    if _telemetry_on():
        _obs_stats.scope("sparse_fused").counter("runtime_disables").inc()


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# sorted segments: the merge_rows formulation without its scatter ops
# ---------------------------------------------------------------------------

def _sorted_segments(rows, vals):
    """Sort the id batch and mark duplicate-run boundaries.

    Returns ``(r, v, first, last)``: ``r`` the int32 sorted rows, ``v``
    the matching reordered value rows, ``first[i]``/``last[i]`` 1 iff
    position i starts/ends a run of equal rows.  Unlike ``merge_rows``
    this emits NO scatter-class ops (one sort + two reorder gathers +
    shifts); the segment SUM itself happens inside the update kernel, in
    the same left-to-right order ``jax.ops.segment_sum`` uses."""
    order = jnp.argsort(rows)
    r = rows[order].astype(jnp.int32)
    v = vals[order]
    neq = (r[1:] != r[:-1]).astype(jnp.int32)
    one = jnp.ones((1,), jnp.int32)
    first = jnp.concatenate([one, neq])
    last = jnp.concatenate([neq, one])
    return r, v, first, last


# ---------------------------------------------------------------------------
# fused multi-table gather
# ---------------------------------------------------------------------------

def _gather_kernel(*refs, k: int):
    # refs: k scalar-prefetch id vectors (consumed by the index maps),
    # then k table blocks, then k out blocks
    for t in range(k):
        refs[2 * k + t][:] = refs[k + t][:]


def fused_gather(tables, ids, interpret=None):
    """Gather ``table[ids]`` for every table in ONE Pallas launch.

    ``tables``: list of [H_t, D_t] arrays sharing the id batch; ``ids``:
    integer array of any shape.  Returns the per-table gathers shaped
    ``ids.shape + (D_t,)``, or ``None`` (counted fallback) if the launch
    cannot be built."""
    if not _HAVE_PALLAS or not tables:
        return None
    try:
        flat = ids.reshape(-1)
        n = int(flat.shape[0])
        if n == 0:
            return [jnp.zeros(ids.shape + (int(t.shape[1]),), t.dtype)
                    for t in tables]
        if any(t.ndim != 2 for t in tables):
            raise ValueError("fused_gather needs 2-D tables")
        if interpret is None:
            interpret = _interpret()
        k = len(tables)
        # jnp.take parity, including its LOUD out-of-range mode: ids in
        # [-H, H) wrap-then-gather; anything else DMAs a clamped edge
        # row but the output row is NaN-filled below (float tables) —
        # ids come from user feed data, and a data bug must fail the
        # same way on both paths (the PR-7 NaN sentinel fires instead
        # of silently training a clamped row)
        idx_args, valids = [], []
        for t in tables:
            h = int(t.shape[0])
            w = jnp.where(flat < 0, flat + h, flat)
            idx_args.append(jnp.clip(w, 0, h - 1).astype(jnp.int32))
            valids.append((flat >= -h) & (flat < h))

        def table_spec(t_pos, width):
            def imap(i, *idx):
                return (idx[t_pos][i], 0)
            return pl.BlockSpec((1, width), imap)

        def out_spec(width):
            return pl.BlockSpec((1, width), lambda i, *idx: (i, 0))

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=k,
            grid=(n,),
            in_specs=[table_spec(t, int(tb.shape[1]))
                      for t, tb in enumerate(tables)],
            out_specs=[out_spec(int(tb.shape[1])) for tb in tables],
        )
        outs = pl.pallas_call(
            functools.partial(_gather_kernel, k=k),
            grid_spec=grid_spec,
            out_shape=[jax.ShapeDtypeStruct((n, int(t.shape[1])), t.dtype)
                       for t in tables],
            interpret=interpret,
        )(*idx_args, *tables)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        filled = []
        for o, t, valid in zip(outs, tables, valids):
            if jnp.issubdtype(t.dtype, jnp.inexact):
                o = jnp.where(valid[:, None], o,
                              jnp.asarray(jnp.nan, t.dtype))
            filled.append(o.reshape(ids.shape + (int(t.shape[1]),)))
        _count("gather_launches")
        return filled
    except Exception:
        _count("gather_fallbacks")
        return None


# ---------------------------------------------------------------------------
# fused row-wise lazy optimizer update
# ---------------------------------------------------------------------------

def _update_kernel(r_ref, first_ref, last_ref, scal_ref, v_ref, *refs,
                   k: int, math_fn):
    """Grid = one sorted id position per step.  Duplicate rows accumulate
    into VMEM scratch; the segment's last position applies ``math_fn`` and
    writes the row's new param/moment blocks (aliased in place)."""
    del r_ref  # consumed by the index maps only
    i = pl.program_id(0)
    acc = refs[2 * k]

    @pl.when(first_ref[i] == 1)
    def _start():
        acc[:] = v_ref[:].astype(jnp.float32)

    @pl.when(first_ref[i] == 0)
    def _accumulate():
        acc[:] = acc[:] + v_ref[:].astype(jnp.float32)

    @pl.when(last_ref[i] == 1)
    def _apply():
        math_fn(acc[:], scal_ref, refs[:k], refs[k:2 * k])


def _rowwise_update(sr, tables, scalars, math_fn, interpret=None):
    """Run ``math_fn`` once per unique row of ``sr`` over ``tables`` in a
    single Pallas launch; returns the updated tables (same order).

    ``scalars``: 1-D f32 array of traced step scalars (lr, ...), SMEM-
    resident.  ``math_fn(g_sum, scal_ref, in_refs, out_refs)`` reads the
    merged f32 gradient row plus the tables' current rows and writes every
    output row (all tables share the [H, D] row shape of the values)."""
    rows, vals = sr.rows, sr.values
    n = int(rows.shape[0])
    if n == 0:
        return list(tables)
    if interpret is None:
        interpret = _interpret()
    d = int(vals.shape[1])
    h = int(sr.height)
    k = len(tables)
    # negative ids wrap (numpy/.at[] convention, same as fused_gather);
    # above-range ids clamp.  Program-produced ids are always in range —
    # this is belt-and-braces so a malformed id can at worst touch an
    # edge row, never fault the kernel.  Canonicalize BEFORE sorting:
    # ids that wrap onto the same row must land in ONE duplicate run
    # (exact accumulation), and sorted canonical rows keep the block
    # indices monotonic — the property the in-place aliasing relies on.
    rows = jnp.clip(jnp.where(rows < 0, rows + h, rows), 0, h - 1)
    r, v, first, last = _sorted_segments(rows, vals)

    row_spec = pl.BlockSpec((1, d), lambda i, r, f, l: (r[i], 0))
    slot_spec = pl.BlockSpec((1, d), lambda i, r, f, l: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),  # scalars
                  slot_spec] + [row_spec] * k,
        out_specs=[row_spec] * k,
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
    )
    # alias each table onto its output; operand numbering includes the 3
    # scalar-prefetch args + scalars + v ahead of the tables
    aliases = {5 + t: t for t in range(k)}
    outs = pl.pallas_call(
        functools.partial(_update_kernel, k=k, math_fn=math_fn),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((h, d), t.dtype) for t in tables],
        input_output_aliases=aliases,
        interpret=interpret,
    )(r, first, last, scalars.astype(jnp.float32).reshape(-1), v, *tables)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def _eligible(sr, tables):
    """The fused update reproduces the sorted reference bit-for-bit only
    when the merge and the moment math both run in f32 (the production
    embedding configuration); anything else falls back, counted."""
    if not _HAVE_PALLAS:
        return False
    if getattr(sr, "merged", False):
        return False  # sentinel-padded input: the sorted path owns it
    if sr.values.ndim != 2 or sr.values.dtype != jnp.float32:
        return False
    return all(t.ndim == 2 and t.shape[1] == sr.values.shape[1]
               for t in tables)


def _f32(x):
    return jnp.float32(x)


def _adam_math(g, scal_ref, ins, outs, *, beta1, beta2, eps):
    p_ref, m1_ref, m2_ref = ins
    po_ref, m1o_ref, m2o_ref = outs
    b1, b2, e = _f32(beta1), _f32(beta2), _f32(eps)
    one = _f32(1.0)
    m1n = b1 * m1_ref[:] + (one - b1) * g
    m2n = b2 * m2_ref[:] + (one - b2) * g * g
    step = scal_ref[0] * m1n / (jnp.sqrt(m2n) + e)
    po_ref[:] = (p_ref[:].astype(jnp.float32) - step).astype(po_ref.dtype)
    m1o_ref[:] = m1n
    m2o_ref[:] = m2n


def fused_adam(p, m1, m2, sr, lr_eff, beta1, beta2, eps):
    """Lazy sparse Adam in one launch: returns (p', m1', m2') or None.
    ``lr_eff`` is the bias-corrected step scalar the sorted path uses."""
    if not _eligible(sr, (m1, m2)) or m1.dtype != jnp.float32 \
            or m2.dtype != jnp.float32:
        _count("update_fallbacks")
        return None
    try:
        math = functools.partial(_adam_math, beta1=float(beta1),
                                 beta2=float(beta2), eps=float(eps))
        scal = jnp.reshape(lr_eff, (1,))
        p2, m1n, m2n = _rowwise_update(sr, [p, m1, m2], scal, math)
        _count("update_launches")
        return p2, m1n, m2n
    except Exception:
        _count("update_fallbacks")
        return None


def _momentum_math(g, scal_ref, ins, outs, *, mu, nesterov):
    p_ref, v_ref = ins
    po_ref, vo_ref = outs
    muf = _f32(mu)
    v_new = muf * v_ref[:] + g
    if nesterov:
        p_new = p_ref[:].astype(jnp.float32) - (g + muf * v_new) * scal_ref[0]
    else:
        p_new = p_ref[:].astype(jnp.float32) - scal_ref[0] * v_new
    po_ref[:] = p_new.astype(po_ref.dtype)
    vo_ref[:] = v_new


def fused_momentum(p, velocity, sr, lr, mu, nesterov):
    """Lazy sparse momentum in one launch: (p', velocity') or None."""
    if not _eligible(sr, (velocity,)) or velocity.dtype != jnp.float32:
        _count("update_fallbacks")
        return None
    try:
        math = functools.partial(_momentum_math, mu=float(mu),
                                 nesterov=bool(nesterov))
        scal = jnp.reshape(lr, (1,))
        p2, v2 = _rowwise_update(sr, [p, velocity], scal, math)
        _count("update_launches")
        return p2, v2
    except Exception:
        _count("update_fallbacks")
        return None


def _adagrad_math(g, scal_ref, ins, outs, *, eps):
    p_ref, mom_ref = ins
    po_ref, momo_ref = outs
    mom_new = mom_ref[:] + g * g
    step = scal_ref[0] * g / (jnp.sqrt(mom_new) + _f32(eps))
    po_ref[:] = (p_ref[:].astype(jnp.float32) - step).astype(po_ref.dtype)
    momo_ref[:] = mom_new


def fused_adagrad(p, moment, sr, lr, eps):
    """Lazy sparse adagrad in one launch: (p', moment') or None."""
    if not _eligible(sr, (moment,)) or moment.dtype != jnp.float32:
        _count("update_fallbacks")
        return None
    try:
        math = functools.partial(_adagrad_math, eps=float(eps))
        scal = jnp.reshape(lr, (1,))
        p2, mom2 = _rowwise_update(sr, [p, moment], scal, math)
        _count("update_launches")
        return p2, mom2
    except Exception:
        _count("update_fallbacks")
        return None


# ---------------------------------------------------------------------------
# block-level lookup_table gather fusion (used by core/lowering.py)
# ---------------------------------------------------------------------------

class LookupFusion:
    """Peephole plan for a block: groups of ``lookup_table`` ops that share
    one Ids input (the DeepFM shape — k tables gathered over the same id
    batch per step) are lowered through ONE ``fused_gather`` launch.

    Built by ``plan_lookup_fusion``; ``core/lowering.py`` consults
    ``covers(pos)`` per op and calls ``lower(pos, env)`` — which fills the
    whole group's outputs into ``env`` at its first member and returns
    True, or returns False (counted) to let every member lower normally."""

    def __init__(self, groups):
        # groups: list of [(pos, op), ...]; positions are block-op indices
        self._by_pos = {}
        self._groups = groups
        for g in groups:
            for pos, _ in g:
                self._by_pos[pos] = g
        self._done = {}   # id(group) -> {out_name: value} or None (dead)

    def covers(self, pos: int) -> bool:
        return pos in self._by_pos

    def lower(self, pos: int, env: dict) -> bool:
        group = self._by_pos[pos]
        key = id(group)
        if key not in self._done:
            self._done[key] = self._lower_group(group, env)
        outs = self._done[key]
        if outs is None:
            return False
        _, op = next(p for p in group if p[0] == pos)
        out_name = op.outputs["Out"][0]
        env[out_name] = outs[out_name]
        return True

    def _lower_group(self, group, env):
        try:
            ids_name = group[0][1].inputs["Ids"][0]
            w_names = [op.inputs["W"][0] for _, op in group]
            if ids_name not in env or any(w not in env for w in w_names):
                raise KeyError("fusion inputs not lowered yet")
            ids = env[ids_name]
            squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
            if squeeze_last:
                ids = ids.squeeze(-1)
            gathered = fused_gather([env[w] for w in w_names], ids)
            if gathered is None:
                return None
            outs = {}
            for (pos, op), out in zip(group, gathered):
                pad = op.attrs.get("padding_idx", -1)
                if pad is not None and pad != -1:
                    mask = (ids != pad)[..., None].astype(out.dtype)
                    out = out * mask
                outs[op.outputs["Out"][0]] = out
            return outs
        except Exception:
            _count("gather_fallbacks")
            return None


def plan_lookup_fusion(block):
    """Scan ``block`` for fusable ``lookup_table`` groups; returns a
    ``LookupFusion`` or None.  Only sparse-gradient lookups are grouped
    (the dense-table path is not the bottleneck this kernel exists for),
    and only groups of >= 2 sharing the same Ids var — a lone gather gains
    nothing from a fused launch."""
    if not fused_enabled():
        return None
    by_ids = {}
    for pos, op in enumerate(block.ops):
        if op.type != "lookup_table" or not op.attrs.get("is_sparse"):
            continue
        if not op.inputs.get("W") or not op.inputs.get("Ids"):
            continue
        w = op.inputs["W"]
        ids = op.inputs["Ids"]
        if len(w) != 1 or len(ids) != 1:
            continue
        by_ids.setdefault(ids[0], []).append((pos, op))
    groups = []
    for ids_name, g in by_ids.items():
        if len(g) < 2:
            continue
        # hoisting later members' table reads to the first member's
        # position is only sound if nothing BETWEEN the members writes a
        # grouped table or the Ids var — else the fused gather would read
        # stale values the per-op lowering would not.  Clobbered groups
        # fall back to per-op gathers (flag-off-identical semantics)
        member_pos = {pos for pos, _ in g}
        hazard = {ids_name} | {op.inputs["W"][0] for _, op in g}
        lo, hi = g[0][0], g[-1][0]
        clobbered = any(
            pos not in member_pos
            and any(n in hazard for n in block.ops[pos].output_arg_names())
            for pos in range(lo + 1, hi))
        if not clobbered:
            groups.append(g)
    return LookupFusion(groups) if groups else None
