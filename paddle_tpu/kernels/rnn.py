"""Fused LSTM cell — Pallas TPU kernels.

Reference precedent: the hand-fused CPU JIT RNN kernels
(``paddle/fluid/operators/math/jit_kernel_rnn.cc``, ``lstm_compute.h``) —
the reference fuses the cell's elementwise tail into the gate GEMM because
a naive per-step op chain is bandwidth-bound.  Same argument on TPU, so
the whole time loop IS the kernel here:

- grid = (T,): one sequential grid step per time step; the recurrent
  weights ride VMEM for the entire scan (constant index map — copied in
  once), h/c state lives in f32 VMEM scratch, never round-tripping HBM.
- forward stores ONLY hs/cs (the op's outputs); the backward kernel
  recomputes the gates from hs[t-1]/xproj[t] — one extra [B,4H] GEMM per
  step in exchange for not writing four [T,B,H] gate tensors in forward
  (the FlashAttention trade applied to the RNN cell).
- backward: reversed-time grid; dh/dc carries and the full dW
  accumulator live in VMEM scratch; emits per-step dX-projection and the
  initial-state grads.

Gradients are wired at the PROGRAM level (ops/nn_ops.py registers an
explicit ``lstm`` grad that calls :func:`lstm_fused_grad`), not via
``jax.custom_vjp`` — the axon PJRT plugin miscompiles custom_vjp bwd
closures under ``lax.scan`` (KeyError in the closed_call lowering cache),
and the explicit grad op is the framework's native mechanism anyway.

Length masking matches the XLA lowering (ops/nn_ops.py _lstm): finished
rows pass h/c through unchanged, so grads flow straight through masked
steps.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas import kept lazy-safe for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

if _HAVE_PALLAS:
    # w + dW output + dW scratch are ~4 MB each at H=512 — past the 16 MB
    # default scoped-vmem limit with double-buffered blocks; v5e has
    # 128 MB physical VMEM, so raise the cap for these kernels.
    # (jax renamed TPUCompilerParams -> CompilerParams; accept either
    # spelling so the kernel loads across the supported jax range)
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    _VMEM_PARAMS = _CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)
else:  # pragma: no cover
    _VMEM_PARAMS = None


def _gates(x_t, h, w):
    """[B,4H] pre-activations -> post-activation (i, f, g, o)."""
    H = h.shape[-1]
    pre = x_t.astype(jnp.float32) + jnp.dot(
        h.astype(w.dtype), w[:], preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(pre[:, :H])
    f = jax.nn.sigmoid(pre[:, H:2 * H])
    g = jnp.tanh(pre[:, 2 * H:3 * H])
    o = jax.nn.sigmoid(pre[:, 3 * H:])
    return i, f, g, o


def _lstm_fwd_kernel(xs_ref, w_ref, m_ref, h0_ref, c0_ref,
                     hs_ref, cs_ref, h_scr, c_scr, *, T: int):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h, c = h_scr[:], c_scr[:]
    i, f, g, o = _gates(xs_ref[0], h, w_ref)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    m = m_ref[0, 0][:, None].astype(jnp.float32)      # [B, 1]
    c_out = m * c_new + (1.0 - m) * c
    h_out = m * h_new + (1.0 - m) * h
    h_scr[:] = h_out
    c_scr[:] = c_out
    hs_ref[0] = h_out.astype(hs_ref.dtype)
    cs_ref[0] = c_out.astype(cs_ref.dtype)


def _lstm_bwd_kernel(xs_ref, w_ref, m_ref, h0_ref, c0_ref,
                     hsm1_ref, csm1_ref, cs_ref, dhs_ref, dcs_ref,
                     dxs_ref, dw_ref, dh0_ref, dc0_ref,
                     dh_scr, dc_scr, dw_scr, *, T: int):
    idx = pl.program_id(0)          # 0..T-1, walking time BACKWARD
    t = T - 1 - idx

    @pl.when(idx == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = jnp.zeros_like(dc_scr)
        dw_scr[:] = jnp.zeros_like(dw_scr)

    is_first = t == 0
    c_prev = jnp.where(is_first, c0_ref[:].astype(jnp.float32),
                       csm1_ref[0].astype(jnp.float32))
    h_prev = jnp.where(is_first, h0_ref[:].astype(jnp.float32),
                       hsm1_ref[0].astype(jnp.float32))
    i, f, g, o = _gates(xs_ref[0], h_prev, w_ref)     # recompute
    c_t = cs_ref[0].astype(jnp.float32)
    m = m_ref[0, 0][:, None].astype(jnp.float32)

    dh_total = dhs_ref[0].astype(jnp.float32) + dh_scr[:]
    dc_total = dcs_ref[0].astype(jnp.float32) + dc_scr[:]
    dh_new = m * dh_total
    dc_new = m * dc_total
    tc = jnp.tanh(c_t)
    do = dh_new * tc
    dc_new = dc_new + dh_new * o * (1.0 - tc * tc)
    di = dc_new * g
    df = dc_new * c_prev
    dg = dc_new * i
    dc_prev = dc_new * f + (1.0 - m) * dc_total
    dgates = jnp.concatenate(
        [di * i * (1.0 - i), df * f * (1.0 - f),
         dg * (1.0 - g * g), do * o * (1.0 - o)], axis=-1)  # [B, 4H]
    dxs_ref[0] = dgates.astype(dxs_ref.dtype)
    wd = w_ref[:]
    dh_prev = jnp.dot(dgates.astype(wd.dtype), wd.T,
                      preferred_element_type=jnp.float32) \
        + (1.0 - m) * dh_total
    dw_scr[:] += jnp.dot(h_prev.astype(wd.dtype).T, dgates.astype(wd.dtype),
                         preferred_element_type=jnp.float32)
    dh_scr[:] = dh_prev
    dc_scr[:] = dc_prev

    @pl.when(idx == T - 1)
    def _finish():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)
        dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)
        dc0_ref[:] = dc_scr[:].astype(dc0_ref.dtype)


def _tm(x):
    """[B,T,...] -> time-major [T,B,...]."""
    return jnp.swapaxes(x, 0, 1)


def _interpret_default():
    return jax.default_backend() != "tpu"


def lstm_fused(xproj, w, h0, c0, mask, interpret=None):
    """Fused LSTM scan (forward only — grads via :func:`lstm_fused_grad`).

    xproj [B,T,4H] (x·Wx+b), w [H,4H], h0/c0 [B,H], mask [B,T] (1.0 =
    live step).  Returns (hs [B,T,H], cs [B,T,H]).  Gate order i,f,c,o
    matches ops/nn_ops.py _lstm."""
    if interpret is None:
        interpret = _interpret_default()
    B, T, H4 = xproj.shape
    H = H4 // 4
    xs, ms = _tm(xproj), _tm(mask)[:, None, :]   # [T,1,B]: TPU-tileable
    kernel = functools.partial(_lstm_fwd_kernel, T=T)
    hs, cs = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((T, B, H), xproj.dtype),
                   jax.ShapeDtypeStruct((T, B, H), xproj.dtype)],
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0)),   # xs
            pl.BlockSpec((H, H4), lambda t: (0, 0)),         # w (resident)
            pl.BlockSpec((1, 1, B), lambda t: (t, 0, 0)),    # mask
            pl.BlockSpec((B, H), lambda t: (0, 0)),          # h0
            pl.BlockSpec((B, H), lambda t: (0, 0)),          # c0
        ],
        out_specs=[pl.BlockSpec((1, B, H), lambda t: (t, 0, 0))] * 2,
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32),
                        pltpu.VMEM((B, H), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(xs, w, ms, h0, c0)
    return _tm(hs), _tm(cs)


def lstm_fused_grad(xproj, w, h0, c0, mask, hs, cs, dhs, dcs,
                    interpret=None):
    """Backward of :func:`lstm_fused` — all batch-major [B,T,...] in/out.
    Returns (dxproj, dw, dh0, dc0)."""
    if interpret is None:
        interpret = _interpret_default()
    B, T, H4 = xproj.shape
    H = H4 // 4
    xs, ms = _tm(xproj), _tm(mask)[:, None, :]   # [T,1,B]
    hs_tm, cs_tm = _tm(hs), _tm(cs)
    dhs_tm = _tm(dhs).astype(xproj.dtype)
    dcs_tm = _tm(dcs).astype(xproj.dtype)
    kernel = functools.partial(_lstm_bwd_kernel, T=T)

    def rev(t):
        return (T - 1 - t, 0, 0)

    def revm1(t):
        # block t-1 (clamped to 0; kernel selects the initial state at t=0)
        return (jnp.maximum(T - 2 - t, 0), 0, 0)

    dxs, dw, dh0, dc0 = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((T, B, H4), xproj.dtype),
                   jax.ShapeDtypeStruct((H, H4), w.dtype),
                   jax.ShapeDtypeStruct((B, H), xproj.dtype),
                   jax.ShapeDtypeStruct((B, H), xproj.dtype)],
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), rev),                  # xs
            pl.BlockSpec((H, H4), lambda t: (0, 0)),        # w
            pl.BlockSpec((1, 1, B), rev),                   # mask
            pl.BlockSpec((B, H), lambda t: (0, 0)),         # h0
            pl.BlockSpec((B, H), lambda t: (0, 0)),         # c0
            pl.BlockSpec((1, B, H), revm1),                 # hs[t-1]
            pl.BlockSpec((1, B, H), revm1),                 # cs[t-1]
            pl.BlockSpec((1, B, H), rev),                   # cs[t]
            pl.BlockSpec((1, B, H), rev),                   # dhs
            pl.BlockSpec((1, B, H), rev),                   # dcs
        ],
        out_specs=[
            pl.BlockSpec((1, B, H4), rev),                  # dxs
            pl.BlockSpec((H, H4), lambda t: (0, 0)),        # dw
            pl.BlockSpec((B, H), lambda t: (0, 0)),         # dh0
            pl.BlockSpec((B, H), lambda t: (0, 0)),         # dc0
        ],
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32),
                        pltpu.VMEM((B, H), jnp.float32),
                        pltpu.VMEM((H, H4), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(xs, w, ms, h0, c0, hs_tm, cs_tm, cs_tm, dhs_tm, dcs_tm)
    return _tm(dxs), dw, dh0, dc0


def lstm_supported(B, T, H, dtype) -> bool:
    """Pallas path gate: MXU-friendly shapes whose VMEM-resident weight
    footprint fits (w + dW output block + dW f32 scratch ≈ 3·H·4H·4 B
    must stay well under the 100 MB cap); anything else takes the XLA
    scan lowering."""
    if not _HAVE_PALLAS:
        return False
    if 3 * H * 4 * H * 4 > 80 * 1024 * 1024:   # H > ~1290
        return False
    return H % 128 == 0 and B % 8 == 0 and T >= 1


# ---------------------------------------------------------------------------
# Fused GRU cell — same design as the LSTM above (jit_kernel_rnn.cc GRU
# precedent): grid=(T,), weights VMEM-resident, backward recomputes gates.
# Gate layout matches ops/nn_ops.py _gru: w = [update | reset | candidate].
# ---------------------------------------------------------------------------

def _gru_gates(x_t, h, w):
    """Returns (u, r, c) post-activation for one step."""
    H = h.shape[-1]
    w_uz, w_c = w[:, :2 * H], w[:, 2 * H:]
    a = x_t[:, :2 * H].astype(jnp.float32) + jnp.dot(
        h.astype(w.dtype), w_uz, preferred_element_type=jnp.float32)
    u = jax.nn.sigmoid(a[:, :H])
    r = jax.nn.sigmoid(a[:, H:])
    b = x_t[:, 2 * H:].astype(jnp.float32) + jnp.dot(
        (r * h).astype(w.dtype), w_c, preferred_element_type=jnp.float32)
    return u, r, jnp.tanh(b)


def _gru_fwd_kernel(xs_ref, w_ref, m_ref, h0_ref, hs_ref, h_scr, *, T: int):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    u, r, c = _gru_gates(xs_ref[0], h, w_ref)
    h_new = u * h + (1.0 - u) * c
    m = m_ref[0, 0][:, None].astype(jnp.float32)
    h_out = m * h_new + (1.0 - m) * h
    h_scr[:] = h_out
    hs_ref[0] = h_out.astype(hs_ref.dtype)


def _gru_bwd_kernel(xs_ref, w_ref, m_ref, h0_ref, hsm1_ref, dhs_ref,
                    dxs_ref, dw_ref, dh0_ref, dh_scr, dw_scr, *, T: int):
    idx = pl.program_id(0)
    t = T - 1 - idx

    @pl.when(idx == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dw_scr[:] = jnp.zeros_like(dw_scr)

    H = dh_scr.shape[-1]
    h_prev = jnp.where(t == 0, h0_ref[:].astype(jnp.float32),
                       hsm1_ref[0].astype(jnp.float32))
    u, r, c = _gru_gates(xs_ref[0], h_prev, w_ref)
    m = m_ref[0, 0][:, None].astype(jnp.float32)
    wd = w_ref[:]
    w_uz, w_c = wd[:, :2 * H], wd[:, 2 * H:]

    dh_total = dhs_ref[0].astype(jnp.float32) + dh_scr[:]
    din = m * dh_total
    du = din * (h_prev - c)
    dh_prev = din * u + (1.0 - m) * dh_total
    dc = din * (1.0 - u)
    db = dc * (1.0 - c * c)                          # [B,H]
    drh = jnp.dot(db.astype(wd.dtype), w_c.T,
                  preferred_element_type=jnp.float32)
    dr = drh * h_prev
    dh_prev = dh_prev + drh * r
    da = jnp.concatenate([du * u * (1.0 - u), dr * r * (1.0 - r)], axis=-1)
    dh_prev = dh_prev + jnp.dot(da.astype(wd.dtype), w_uz.T,
                                preferred_element_type=jnp.float32)
    dxs_ref[0] = jnp.concatenate([da, db], axis=-1).astype(dxs_ref.dtype)
    dw_scr[:, :2 * H] += jnp.dot(h_prev.astype(wd.dtype).T,
                                 da.astype(wd.dtype),
                                 preferred_element_type=jnp.float32)
    dw_scr[:, 2 * H:] += jnp.dot((r * h_prev).astype(wd.dtype).T,
                                 db.astype(wd.dtype),
                                 preferred_element_type=jnp.float32)
    dh_scr[:] = dh_prev

    @pl.when(idx == T - 1)
    def _finish():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)
        dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)


def gru_fused(xproj, w, h0, mask, interpret=None):
    """Fused GRU scan (forward; grads via :func:`gru_fused_grad`).
    xproj [B,T,3H], w [H,3H], h0 [B,H], mask [B,T] -> hs [B,T,H]."""
    if interpret is None:
        interpret = _interpret_default()
    B, T, H3 = xproj.shape
    H = H3 // 3
    xs, ms = _tm(xproj), _tm(mask)[:, None, :]
    kernel = functools.partial(_gru_fwd_kernel, T=T)
    hs = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((T, B, H), xproj.dtype),
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H3), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H3), lambda t: (0, 0)),
            pl.BlockSpec((1, 1, B), lambda t: (t, 0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(xs, w, ms, h0)
    return _tm(hs)


def gru_fused_grad(xproj, w, h0, mask, hs, dhs, interpret=None):
    """Backward of :func:`gru_fused`; returns (dxproj, dw, dh0)."""
    if interpret is None:
        interpret = _interpret_default()
    B, T, H3 = xproj.shape
    H = H3 // 3
    xs, ms = _tm(xproj), _tm(mask)[:, None, :]
    hs_tm = _tm(hs)
    dhs_tm = _tm(dhs).astype(xproj.dtype)
    kernel = functools.partial(_gru_bwd_kernel, T=T)

    def rev(t):
        return (T - 1 - t, 0, 0)

    def revm1(t):
        return (jnp.maximum(T - 2 - t, 0), 0, 0)

    dxs, dw, dh0 = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((T, B, H3), xproj.dtype),
                   jax.ShapeDtypeStruct((H, H3), w.dtype),
                   jax.ShapeDtypeStruct((B, H), xproj.dtype)],
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H3), rev),                  # xs
            pl.BlockSpec((H, H3), lambda t: (0, 0)),        # w
            pl.BlockSpec((1, 1, B), rev),                   # mask
            pl.BlockSpec((B, H), lambda t: (0, 0)),         # h0
            pl.BlockSpec((1, B, H), revm1),                 # hs[t-1]
            pl.BlockSpec((1, B, H), rev),                   # dhs
        ],
        out_specs=[
            pl.BlockSpec((1, B, H3), rev),
            pl.BlockSpec((H, H3), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32),
                        pltpu.VMEM((H, H3), jnp.float32)],
        compiler_params=_VMEM_PARAMS,
        interpret=interpret,
    )(xs, w, ms, h0, hs_tm, dhs_tm)
    return _tm(dxs), dw, dh0


def gru_supported(B, T, H, dtype) -> bool:
    if not _HAVE_PALLAS:
        return False
    if 3 * H * 3 * H * 4 > 80 * 1024 * 1024:
        return False
    return H % 128 == 0 and B % 8 == 0 and T >= 1
