"""Composite network helpers (reference python/paddle/fluid/nets.py):
compositions over the layer DSL, no new ops."""
from __future__ import annotations

import math

from . import layers
from .param_attr import ParamAttr

__all__ = [
    "switch_moe",
    "moe_sharding_rules",
    "simple_img_conv_pool",
    "sequence_conv_pool",
    "glu",
    "scaled_dot_product_attention",
    "img_conv_group",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(
        input, num_filters, filter_size, stride=conv_stride,
        padding=conv_padding, dilation=conv_dilation, groups=conv_groups,
        param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size, pool_type, pool_stride,
                         pool_padding, global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Stacked conv (+optional BN/dropout) blocks followed by one pool —
    the VGG building block."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def expand(v):
        return v if isinstance(v, (list, tuple)) \
            else [v] * len(conv_num_filter)

    conv_padding = expand(conv_padding)
    conv_filter_size = expand(conv_filter_size)
    param_attr = expand(param_attr) if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(conv_num_filter)
    conv_with_batchnorm = expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = expand(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            tmp, conv_num_filter[i], conv_filter_size[i],
            padding=conv_padding[i], param_attr=param_attr[i],
            act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp, conv_batchnorm_drop_rate[i])
    return layers.pool2d(tmp, pool_size, pool_type, pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    conv_out = layers.sequence_conv(input, num_filters, filter_size,
                                    param_attr=param_attr, act=act)
    return layers.sequence_pool(conv_out, pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    from .layers.ops import sigmoid
    return layers.elementwise_mul(a, sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention over [B, T, D] tensors
    (reference nets.py:333); returns [B, Tq, Dv]."""
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys must have the same hidden size")
    d_key = int(keys.shape[-1]) // num_heads

    def split_heads(x):
        if num_heads == 1:
            return x
        b, t, d = x.shape
        r = layers.reshape(x, [b, t, num_heads, d // num_heads])
        return layers.transpose(r, [0, 2, 1, 3])

    def combine_heads(x):
        if num_heads == 1:
            return x
        b, h, t, d = x.shape
        return layers.reshape(layers.transpose(x, [0, 2, 1, 3]),
                              [b, t, h * d])

    q, k, v = split_heads(queries), split_heads(keys), split_heads(values)
    scaled_q = layers.scale(q, scale=d_key ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    return combine_heads(ctx)


def switch_moe(input, num_experts, d_ffn, capacity_factor=1.25,
              capacity_per_expert=None, name_prefix=None,
              return_aux=False):
    """Switch-style top-1 mixture-of-experts FFN with expert parallelism
    (no reference analogue — the TPU-native §7 extension; GShard-pattern
    dispatch/combine einsums expressed as one-hot matmuls so GSPMD turns
    them into all-to-alls when the expert weight dim is sharded over an
    ``ep`` mesh axis via :func:`moe_sharding_rules`).

    input [N, D] -> output [N, D]; each token is routed to its top-1
    expert (capacity C = ceil(N/E * capacity_factor); overflow tokens
    drop to zero, the standard Switch contract), runs that expert's
    2-layer relu FFN, and is scaled by its gate probability (the
    gradient path that trains the router).

    ``name_prefix=None`` (default) generates a unique prefix per call so
    stacked MoE layers never share weights; pass an explicit prefix to
    share weights across programs (train/infer) — and the SAME prefix to
    :func:`moe_sharding_rules`.

    ``return_aux=True`` returns ``(output, aux_loss, dropped_frac)``:

    - ``aux_loss`` [scalar] — the standard Switch load-balancing loss,
      ``E * sum_e(f_e * P_e)`` with ``f_e`` the fraction of tokens
      routed to expert ``e`` (pre-capacity argmax routing) and ``P_e``
      the mean gate probability of ``e``.  Uniform routing gives 1.0;
      add a small multiple (Switch uses 0.01) to the training loss to
      regularize against router collapse.
    - ``dropped_frac`` [scalar] — the fraction of tokens dropped by the
      capacity limit this batch (overflow tokens pass through as
      zeros); a rising value means the router is hot-spotting or
      ``capacity_factor`` is too small.
    """
    from .core import unique_name

    if name_prefix is None:
        name_prefix = unique_name.generate("moe")
    N, D = int(input.shape[0]), int(input.shape[1])
    E = int(num_experts)
    if capacity_per_expert is not None:
        C = int(capacity_per_expert)
    elif N > 0:
        C = int(math.ceil(N / E * capacity_factor))
    else:
        raise ValueError(
            "switch_moe needs capacity_per_expert when the token/batch "
            "dim is dynamic (-1): the dispatch tensor's [E, C] extent "
            "must be static for XLA")

    gate_probs = layers.softmax(layers.fc(
        input, E, param_attr=ParamAttr(name=f"{name_prefix}.gate.w"),
        bias_attr=False))                                   # [N, E]
    expert_idx = layers.argmax(gate_probs, axis=-1)         # [N]
    mask = layers.one_hot(
        layers.unsqueeze(expert_idx, [1]), E)               # [N, E] f32
    gate = layers.reduce_sum(layers.elementwise_mul(gate_probs, mask),
                             dim=-1, keep_dim=True)         # [N, 1]

    if return_aux:
        # Switch load-balancing loss over the PRE-capacity routing
        # decisions (capacity drops are what the loss prevents, they
        # must not hide from it): E * <f_e, P_e>
        frac_routed = layers.reduce_mean(mask, dim=0)       # [E]
        mean_prob = layers.reduce_mean(gate_probs, dim=0)   # [E]
        aux_loss = layers.scale(
            layers.reduce_sum(
                layers.elementwise_mul(frac_routed, mean_prob)),
            scale=float(E))                                 # scalar
        # token count as a tensor (the batch dim may be dynamic; the
        # pre-capacity mask has exactly one 1 per token)
        total_tokens = layers.reduce_sum(mask)              # scalar

    # position of each token within its expert; tokens past capacity drop
    pos = layers.elementwise_mul(
        layers.cumsum(mask, axis=0, exclusive=True), mask)  # [N, E]
    keep = layers.cast(layers.less_than(
        pos, layers.fill_constant([1], "float32", float(C))), "float32")
    mask = layers.elementwise_mul(mask, keep)
    pos_ids = layers.cast(
        layers.reduce_sum(layers.elementwise_mul(pos, mask), dim=-1),
        "int64")                                            # [N]
    pos_hot = layers.one_hot(
        layers.unsqueeze(pos_ids, [1]), C)                  # [N, C] f32

    # dispatch [N, E, C] = mask[N,E] x pos_hot[N,C] (outer product)
    dispatch = layers.elementwise_mul(
        layers.unsqueeze(mask, [2]),
        layers.unsqueeze(pos_hot, [1]))                     # [N, E, C]
    disp_flat = layers.reshape(dispatch, [-1, E * C])

    # expert_in [E, C, D] = dispatch^T @ x — the GSPMD all-to-all site
    expert_in = layers.reshape(
        layers.matmul(layers.transpose(disp_flat, [1, 0]), input),
        [E, C, D])

    w1 = layers.create_parameter([E, D, d_ffn], "float32",
                                 name=f"{name_prefix}.w1")
    b1 = layers.create_parameter([E, 1, d_ffn], "float32",
                                 name=f"{name_prefix}.b1")  # per-expert
    w2 = layers.create_parameter([E, d_ffn, D], "float32",
                                 name=f"{name_prefix}.w2")
    h = layers.relu(layers.elementwise_add(
        layers.matmul(expert_in, w1), b1))                  # [E, C, F]
    expert_out = layers.matmul(h, w2)                       # [E, C, D]

    # combine [N, D] = dispatch @ expert_out, scaled by the gate prob
    out = layers.matmul(disp_flat,
                        layers.reshape(expert_out, [E * C, D]))
    out = layers.elementwise_mul(out, gate)
    if not return_aux:
        return out
    # dropped-token fraction: tokens whose dispatch row zeroed out at
    # the capacity cut (post-capacity mask sums to kept tokens)
    kept = layers.reduce_sum(mask)                          # scalar
    dropped_frac = layers.scale(
        layers.elementwise_div(kept, total_tokens), scale=-1.0, bias=1.0)
    # EP health observability: register both scalars as step-stat vars —
    # whenever a run FETCHES them (convergence loops, the ep dryrun
    # phase) and FLAGS_runtime_stats is on, the executor stamps them
    # into the StepStats record (/stepz) and same-named gauges
    # (/metrics); runlog picks scalar fetches up by name already
    prog = input.block.program
    prog.step_stat_vars[aux_loss.name] = f"moe.{name_prefix}.aux_loss"
    prog.step_stat_vars[dropped_frac.name] = \
        f"moe.{name_prefix}.dropped_frac"
    return out, aux_loss, dropped_frac


def moe_sharding_rules(name_prefix="moe"):
    """PartitionSpecs sharding every expert-batched weight over the
    ``ep`` mesh axis (use with BuildStrategy.sharding_rules; the
    dispatch/combine matmuls then carry the tokens across experts via
    GSPMD-inserted collectives)."""
    return [
        # trailing .* shards the Adam moment accumulators with their
        # expert weights (the deepfm.tp_sharding_rules precedent —
        # replicated moments would cost 2x the sharded weight bytes on
        # every device); scalar beta-pow accumulators stay replicated
        # via the divisibility guard
        (rf"{name_prefix}\.w1.*", ("ep", None, None)),
        (rf"{name_prefix}\.b1.*", ("ep", None, None)),
        (rf"{name_prefix}\.w2.*", ("ep", None, None)),
    ]
