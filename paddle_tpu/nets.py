"""Composite network helpers (reference python/paddle/fluid/nets.py):
compositions over the layer DSL, no new ops."""
from __future__ import annotations

from . import layers

__all__ = [
    "simple_img_conv_pool",
    "sequence_conv_pool",
    "glu",
    "scaled_dot_product_attention",
    "img_conv_group",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(
        input, num_filters, filter_size, stride=conv_stride,
        padding=conv_padding, dilation=conv_dilation, groups=conv_groups,
        param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size, pool_type, pool_stride,
                         pool_padding, global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Stacked conv (+optional BN/dropout) blocks followed by one pool —
    the VGG building block."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def expand(v):
        return v if isinstance(v, (list, tuple)) \
            else [v] * len(conv_num_filter)

    conv_padding = expand(conv_padding)
    conv_filter_size = expand(conv_filter_size)
    param_attr = expand(param_attr) if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(conv_num_filter)
    conv_with_batchnorm = expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = expand(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            tmp, conv_num_filter[i], conv_filter_size[i],
            padding=conv_padding[i], param_attr=param_attr[i],
            act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp, conv_batchnorm_drop_rate[i])
    return layers.pool2d(tmp, pool_size, pool_type, pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    conv_out = layers.sequence_conv(input, num_filters, filter_size,
                                    param_attr=param_attr, act=act)
    return layers.sequence_pool(conv_out, pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    from .layers.ops import sigmoid
    return layers.elementwise_mul(a, sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention over [B, T, D] tensors
    (reference nets.py:333); returns [B, Tq, Dv]."""
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys must have the same hidden size")
    d_key = int(keys.shape[-1]) // num_heads

    def split_heads(x):
        if num_heads == 1:
            return x
        b, t, d = x.shape
        r = layers.reshape(x, [b, t, num_heads, d // num_heads])
        return layers.transpose(r, [0, 2, 1, 3])

    def combine_heads(x):
        if num_heads == 1:
            return x
        b, h, t, d = x.shape
        return layers.reshape(layers.transpose(x, [0, 2, 1, 3]),
                              [b, t, h * d])

    q, k, v = split_heads(queries), split_heads(keys), split_heads(values)
    scaled_q = layers.scale(q, scale=d_key ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    return combine_heads(ctx)
