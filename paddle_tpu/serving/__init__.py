"""Multi-tenant model serving plane (continuous batching + hot-swap).

The path from a :class:`~paddle_tpu.inference.Predictor` to the
north-star "heavy traffic from millions of users": a model server built
entirely on this repo's own primitives — the framed-TCP transport
(:mod:`paddle_tpu.distributed.transport`), the TTL-lease registry
(:mod:`paddle_tpu.distributed.registry`), the persistent compile cache
(:meth:`Executor.warm_start`), and the observability plane.

Reference precedent: the standalone inference layer of the survey
(``paddle/fluid/inference/``, SURVEY.md § inference) ships a
predictor-per-thread API and stops there; serving it at scale was left
to external servers.  Here the serving loop is TPU-native by design —
on TPU, throughput is won by *never recompiling and never dispatching a
half-empty batch*:

- **Continuous dynamic batching** (:mod:`batcher`): concurrent requests
  coalesce into padded batches snapped to a bucket ladder
  (``FLAGS_serving_buckets``); a batch dispatches the moment the top
  bucket fills *or* the per-model max-queue-delay expires.  Pad rows are
  sliced off before the reply; every dispatch shape is on the warmed
  ladder, so the executor's shape-bucket cache never recompiles.
- **Versioned hot-swap** (:mod:`model_registry`): load version B next
  to A, warm B's whole bucket ladder (from the persistent compile cache
  when enabled), atomically flip the router, drain A — zero dropped and
  zero recompile-stalled requests during the flip.
- **Admission control**: bounded per-model queues and a queue-delay SLO;
  past either, requests are shed with a typed :class:`Overloaded` reply
  instead of silently queueing into timeout.
- **Replica groups** (:mod:`server` / :mod:`client`): servers announce
  ``(model, version, health)`` via registry leases; the thin client
  routes across replicas with health-gated failover.

Nothing here is imported by the core framework: a process that never
instantiates a server/batcher gets no new sockets, threads, or behavior.
"""
from __future__ import annotations

from .batcher import (BucketLadder, Draining,  # noqa: F401
                      DynamicBatcher, Overloaded, RequestTooLong)
from .model_registry import ModelManager, ServedModel  # noqa: F401
from .server import ModelServer, ServingService  # noqa: F401
from .client import ServingClient  # noqa: F401

__all__ = ["BucketLadder", "Draining", "DynamicBatcher", "Overloaded",
           "RequestTooLong", "ModelManager", "ServedModel",
           "ModelServer", "ServingService", "ServingClient"]
