"""Continuous dynamic batching: coalesce requests onto a bucket ladder.

The serving-plane hot loop.  Concurrent :meth:`DynamicBatcher.submit`
calls enqueue requests; one scheduler thread coalesces them into a
single padded batch snapped to the smallest bucket that fits
(:class:`BucketLadder`), dispatches through the model's
:class:`~paddle_tpu.inference.Predictor`, and a completion thread
slices the per-request rows back out (pad rows never leave the server).

Why buckets: the executor compiles one XLA executable per feed-shape
signature (``core/executor.py`` shape-bucket cache).  Free-form batch
sizes would recompile constantly; snapping every dispatch to a small
ladder (default 1/2/4/8/16/32) means a handful of executables cover all
traffic — warm them once (``ModelManager.load(warm=True)``) and the
server never compiles again.

Dispatch policy (the "continuous" part): a batch goes out as soon as
the TOP bucket fills *or* the oldest queued request has waited
``max_delay_ms`` — whichever comes first.  Low traffic pays at most the
delay SLO riding a small bucket; saturation runs back-to-back top
buckets with zero idle.

Pipelining: ``Predictor.run`` dispatches asynchronously (the executor
returns :class:`LazyFetch` handles), so while batch N executes on
device the scheduler thread is already assembling and feeding batch
N+1, and the completion thread materializes batch N's results — one
batched readback per dispatch — and completes the reply futures.

Admission control: a bounded queue (``max_queue_rows``) plus an
optional queue-delay SLO (``queue_delay_slo_ms``): when the backlog
times the observed per-batch service time says the SLO is unmeetable,
new requests are shed immediately with a typed :class:`Overloaded` —
a fast, honest overload reply beats a slow timeout.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import flags as _flags
from ..core.types import np_dtype
from ..distributed import faults as _faults
from ..observability import capacity as _capacity
from ..observability import debug_server as _debug_server
from ..observability import memory as _memory
from ..observability import phase as _phase
from ..observability import tenant as _tenant
from ..observability import stats as _obs_stats
from ..observability import trace as _obs_trace

# request lifecycle phases (FLAGS_phase_attribution; observability/
# phase.py): consecutive monotonic stamps, so the five sum EXACTLY to
# the request's end-to-end wall — a p99 regression names its phase
#   queue     submit accepted -> its batch starts assembling
#   assemble  coalesce + pad + feed build
#   dispatch  Predictor.run (async executor dispatch; lowering on miss;
#             injected dispatch faults — the PR-6 `delay:
#             serving_dispatch` rule — land here)
#   device    dispatch return -> batch materialized (device execution +
#             the one batched readback, incl. completion-queue wait)
#   reply     materialized -> this request's future completed
SERVING_PHASES = ("queue", "assemble", "dispatch", "device", "reply")
# capacity-tracked components: the phases that consume a worker
# thread's wall ("queue" is waiting, not busy — it never saturates)
SERVING_CAPACITY_COMPONENTS = ("assemble", "dispatch", "device", "reply")


class Overloaded(RuntimeError):
    """Typed load-shed reply: the request was NOT queued.

    Carried over the wire by :mod:`server`/:mod:`client` so a remote
    caller sees the same type with the same fields — clients should
    back off or fail over to another replica."""

    def __init__(self, model: str, queue_rows: int, limit_rows: int,
                 est_delay_ms: Optional[float] = None,
                 slo_ms: Optional[float] = None):
        self.model = model
        self.queue_rows = queue_rows
        self.limit_rows = limit_rows
        self.est_delay_ms = est_delay_ms
        self.slo_ms = slo_ms
        if est_delay_ms is not None:
            why = (f"estimated queue delay {est_delay_ms:.1f} ms exceeds "
                   f"SLO {slo_ms:.1f} ms")
        else:
            why = f"queue full ({queue_rows}/{limit_rows} rows)"
        super().__init__(f"model {model!r} overloaded: {why}")

    def to_dict(self) -> dict:
        return {"model": self.model, "queue_rows": self.queue_rows,
                "limit_rows": self.limit_rows,
                "est_delay_ms": self.est_delay_ms, "slo_ms": self.slo_ms}

    @classmethod
    def from_dict(cls, d: dict) -> "Overloaded":
        return cls(d.get("model", "?"), int(d.get("queue_rows", 0)),
                   int(d.get("limit_rows", 0)), d.get("est_delay_ms"),
                   d.get("slo_ms"))


class Draining(RuntimeError):
    """Typed graceful-shutdown rejection: the request was NOT queued.

    A replica that is draining (``ModelServer.stop(drain=True)`` /
    ``DecodeServer`` SIGTERM) has already DEREGISTERED its registry
    lease — discovery-based clients fail over before the socket ever
    dies — and answers any straggler submit with this instead of
    accepting work it would have to abandon.  In-flight requests still
    finish inside the drain bound.  Carried over the wire like
    :class:`Overloaded`; clients rotate to another replica (unlike
    :class:`RequestTooLong`, some other replica WILL take it)."""

    def __init__(self, model: str, endpoint: str = ""):
        self.model = model
        self.endpoint = endpoint
        where = f" at {endpoint}" if endpoint else ""
        super().__init__(
            f"model {model!r} replica{where} is draining (graceful "
            "shutdown); retry another replica")

    def to_dict(self) -> dict:
        return {"model": self.model, "endpoint": self.endpoint}

    @classmethod
    def from_dict(cls, d: dict) -> "Draining":
        return cls(d.get("model", "?"), d.get("endpoint", ""))


class RequestTooLong(ValueError):
    """Typed over-length rejection: the request was NOT queued.

    Raised at submit when a feed's sequence axis exceeds the model's
    ``max_seq_len`` (or a decode prompt+budget exceeds the engine's
    context bound) — BEFORE the request can poison its coalesced batch
    or force an off-ladder recompile.  Carried over the wire like
    :class:`Overloaded` so remote callers see the same type; unlike
    Overloaded it must NOT fail over to another replica — every replica
    of the model would reject it identically."""

    def __init__(self, model: str, feed: str, length: int, limit: int):
        self.model = model
        self.feed = feed
        self.length = int(length)
        self.limit = int(limit)
        super().__init__(
            f"model {model!r}: feed {feed!r} length {length} exceeds "
            f"max_seq_len {limit}")

    def to_dict(self) -> dict:
        return {"model": self.model, "feed": self.feed,
                "length": self.length, "limit": self.limit}

    @classmethod
    def from_dict(cls, d: dict) -> "RequestTooLong":
        return cls(d.get("model", "?"), d.get("feed", "?"),
                   int(d.get("length", 0)), int(d.get("limit", 0)))


class BucketLadder:
    """Sorted batch-size ladder; ``snap(n)`` is the smallest bucket
    ≥ n.  Requests larger than the top bucket are rejected at submit
    (dispatching off-ladder would recompile — the one thing the
    serving plane exists to never do)."""

    def __init__(self, buckets: Optional[Sequence[int]] = None):
        if buckets is None:
            buckets = self.flag_buckets()
        sizes = sorted({int(b) for b in buckets})
        if not sizes or sizes[0] < 1:
            raise ValueError(f"invalid bucket ladder: {buckets!r}")
        self.sizes = tuple(sizes)

    @staticmethod
    def parse(spec) -> List[int]:
        """The ladder-spec grammar ("1,2,4" / "1;2;4"), shared by the
        flag default and tools/serve.py's --buckets."""
        return [int(p) for p in str(spec).replace(";", ",").split(",")
                if p.strip()]

    @classmethod
    def flag_buckets(cls) -> List[int]:
        return cls.parse(_flags.get_flags("serving_buckets"))

    @property
    def max(self) -> int:
        return self.sizes[-1]

    def snap(self, n: int) -> int:
        for b in self.sizes:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} rows exceeds the top bucket {self.max}")

    def __repr__(self) -> str:
        return f"BucketLadder{self.sizes}"


class _Request:
    __slots__ = ("feed", "rows", "future", "t_enq", "tl", "tenant")

    def __init__(self, feed: Dict[str, np.ndarray], rows: int,
                 tenant: Optional[str] = None):
        self.feed = feed
        self.rows = rows
        self.tenant = tenant
        self.future: "Future" = Future()
        self.t_enq = time.monotonic()
        # phase timeline, sharing the enqueue stamp (flag-gated; None
        # keeps the flag-off path allocation-free)
        self.tl = (_phase.PhaseTimeline(t0=self.t_enq)
                   if _phase.enabled() else None)


class BatcherStats:
    """Per-model serving gauges for /servingz: QPS and latency
    percentiles over a bounded recent window, plus lifetime counters
    (which also land in the process stats registry as
    ``serving.<model>.*`` Prometheus series)."""

    _WINDOW = 512

    def __init__(self, model: str):
        self.model = model
        self._lock = threading.Lock()
        # (t_done_monotonic, latency_ms) of recent completed requests
        self._recent: deque = deque(maxlen=self._WINDOW)
        # per-request phase attribution (FLAGS_phase_attribution):
        # created on first observe so a flag-off process never
        # registers serving.<model>.phase.* series
        self._phases: Optional[_phase.PhaseRecorder] = None
        self.requests = 0
        self.rows = 0
        self.shed = 0
        self.batches = 0
        self.padded_rows = 0
        self.dispatched_rows = 0
        self.errors = 0
        sc = _obs_stats.scope(f"serving.{model}")
        self._c_requests = sc.counter("requests")
        self._c_rows = sc.counter("rows")
        self._c_shed = sc.counter(
            "shed", "requests refused by admission control (typed "
            "Overloaded reply; queue bound or queue-delay SLO)")
        self._c_batches = sc.counter("batches")
        self._c_padded = sc.counter(
            "padded_rows", "pad rows added to snap batches onto the "
            "bucket ladder (sliced off before the reply)")
        self._c_errors = sc.counter("errors")
        self._g_depth = sc.gauge("queue_rows")
        self._h_latency = sc.histogram("latency_ms")
        self._h_occupancy = sc.histogram(
            "batch_occupancy_pct",
            buckets=(10, 25, 50, 75, 90, 100))

    def note_submit(self, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows
        self._c_requests.inc()
        self._c_rows.inc(rows)

    def note_shed(self) -> None:
        with self._lock:
            self.shed += 1
        self._c_shed.inc()

    def note_batch(self, rows: int, bucket: int) -> None:
        with self._lock:
            self.batches += 1
            self.padded_rows += bucket - rows
            self.dispatched_rows += rows
        self._c_batches.inc()
        self._c_padded.inc(bucket - rows)
        self._h_occupancy.observe(100.0 * rows / bucket)

    def note_done(self, n_requests: int, latencies_ms: List[float],
                  error: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if error:
                self.errors += n_requests
            for lat in latencies_ms:
                self._recent.append((now, lat))
        if error:
            self._c_errors.inc(n_requests)
        for lat in latencies_ms:
            self._h_latency.observe(lat)

    def set_depth(self, rows: int) -> None:
        self._g_depth.set(rows)

    def note_phases(self, tl, trace_id=None) -> None:
        """Fold one finished request timeline into the per-phase
        histograms + sample ring (completion thread)."""
        with self._lock:
            rec = self._phases
            if rec is None:
                rec = self._phases = _phase.PhaseRecorder(
                    f"serving.{self.model}", SERVING_PHASES)
        rec.observe(tl, trace_id=trace_id)

    def phases(self) -> Optional[_phase.PhaseRecorder]:
        with self._lock:
            return self._phases

    def capacity_tracker(self) -> "_capacity.CapacityTracker":
        """Get-or-create this model's capacity tracker (callers gate on
        ``_capacity.enabled()`` so a flag-off process never registers
        ``serving.<model>.util.*`` series)."""
        return _capacity.tracker(f"serving.{self.model}",
                                 SERVING_CAPACITY_COMPONENTS)

    def capacity(self) -> Optional["_capacity.CapacityTracker"]:
        return _capacity.get(f"serving.{self.model}")

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            recent = list(self._recent)
            phases = self._phases
            out = {
                "requests": self.requests, "rows": self.rows,
                "shed": self.shed, "batches": self.batches,
                "padded_rows": self.padded_rows, "errors": self.errors,
                "avg_batch_occupancy": (
                    round(self.dispatched_rows
                          / max(self.dispatched_rows + self.padded_rows, 1),
                          3)),
            }
        if recent:
            span = max(now - recent[0][0], 1e-3)
            lats = sorted(lat for _, lat in recent)
            out.update({
                "qps": round(len(recent) / span, 1),
                # the SHARED raw-sample percentile (stats.py): small
                # windows now agree with the StepStats summaries
                "p50_ms": round(_obs_stats.percentile_sorted(lats, 0.50), 3),
                "p99_ms": round(_obs_stats.percentile_sorted(lats, 0.99), 3),
            })
        if phases is not None:
            out["phases"] = phases.snapshot()
        cap = self.capacity()
        if cap is not None:
            out["capacity"] = cap.snapshot()
        return out


def _pad_rows(arr: np.ndarray, pad: int) -> np.ndarray:
    """Pad ``arr`` to ``len(arr)+pad`` rows by repeating the last row:
    real in-range values keep every lowering numerically tame (an
    all-zero pad can divide-by-zero a normalization), and the pad rows
    are sliced off before any reply."""
    if pad == 0:
        return arr
    return np.concatenate([arr, np.repeat(arr[-1:], pad, axis=0)], axis=0)


class DynamicBatcher:
    """One model version's continuous-batching scheduler (module doc).

    ``predictor`` needs the Predictor surface: ``run(feed_dict)``,
    ``feed_names``, ``fetch_names``.  All feeds must share the same
    leading (batch) dimension; coalescing concatenates along it.
    """

    def __init__(self, predictor, name: str = "model",
                 buckets: Optional[Sequence[int]] = None,
                 max_delay_ms: Optional[float] = None,
                 max_queue_rows: Optional[int] = None,
                 queue_delay_slo_ms: Optional[float] = None,
                 max_seq_len: Optional[int] = None):
        self.predictor = predictor
        self.name = name
        # per-model sequence-length bound (padded sequence models):
        # a sequence feed whose axis-1 exceeds it is rejected ALONE at
        # submit with a typed RequestTooLong — before the first request
        # could latch an over-length sample shape into the feed
        # contract and force every later dispatch onto an off-ladder
        # executable.  An int applies to the feeds whose program
        # declaration does NOT pin a static sample shape (a statically
        # declared [B, 256] feature feed is not a sequence and must not
        # be measured against it); a dict names feed→limit explicitly.
        self.max_seq_len = max_seq_len if isinstance(max_seq_len, dict) \
            else (int(max_seq_len) if max_seq_len else None)
        self.ladder = (buckets if isinstance(buckets, BucketLadder)
                       else BucketLadder(buckets))
        self.max_delay_ms = (
            float(_flags.get_flags("serving_max_queue_delay_ms"))
            if max_delay_ms is None else float(max_delay_ms))
        self.max_queue_rows = (
            int(_flags.get_flags("serving_max_queue_rows"))
            if max_queue_rows is None else int(max_queue_rows))
        slo = (_flags.get_flags("serving_queue_delay_slo_ms")
               if queue_delay_slo_ms is None else queue_delay_slo_ms)
        self.queue_delay_slo_ms = float(slo) or None  # 0 ⇒ disabled
        self.stats = BatcherStats(name)
        # per-feed (sample_shape, dtype) contract each request must
        # match — a request with a wrong trailing shape must be
        # rejected ALONE at submit, not poison every innocent request
        # coalesced into its batch when np.concatenate throws.  Seeded
        # from the program's static feed declarations when the
        # predictor carries a program; feeds with symbolic dims (or
        # stub predictors) latch from the first accepted request.
        self._feed_contract: Dict[str, list] = {}
        prog = getattr(predictor, "program", None)
        block = prog().global_block if callable(prog) else None
        for n in predictor.feed_names:
            var = block.var_or_none(n) if block is not None else None
            if var is not None and var.shape is not None and \
                    not any(s < 0 for s in var.shape[1:]):
                self._feed_contract[n] = [tuple(var.shape[1:]),
                                          np.dtype(np_dtype(var.dtype))
                                          if var.dtype is not None else None]
            else:
                self._feed_contract[n] = [None, None]
        if isinstance(self.max_seq_len, dict):
            self._seq_limits = {n: int(v)
                                for n, v in self.max_seq_len.items()}
        elif self.max_seq_len:
            self._seq_limits = {n: int(self.max_seq_len)
                                for n, c in self._feed_contract.items()
                                if c[0] is None}
        else:
            self._seq_limits = {}

        self._cv = threading.Condition()
        self._q: deque = deque()
        self._rows_queued = 0
        self._inflight_batches = 0
        self._closed = False
        self._ewma_batch_ms: Optional[float] = None
        # one completion thread: materializes each batch's LazyFetch
        # results (one batched readback) and completes futures IN
        # DISPATCH ORDER while the scheduler assembles the next batch
        self._done_q: deque = deque()
        self._done_cv = threading.Condition()
        # memory anatomy (FLAGS_memory_attribution): batch staging —
        # queued request feeds plus batches awaiting completion — is a
        # host-side byte holder; flag off, no pool, no series
        self._mem_pool: Optional[str] = None
        if _memory.enabled():
            self._mem_pool = f"serving_staging.{name}"
            _memory.pool(self._mem_pool, "host",
                         self._mem_pool_snapshot)
        self._sched = threading.Thread(
            target=self._sched_loop, daemon=True,
            name=f"serving-sched-{name}")
        self._completer = threading.Thread(
            target=self._complete_loop, daemon=True,
            name=f"serving-complete-{name}")
        self._sched.start()
        self._completer.start()

    def _mem_pool_snapshot(self) -> dict:
        """MemoryLedger callback: bytes staged in the request queue
        plus batches dispatched but not yet completed (their request
        feeds are held until the reply slices out)."""
        with self._cv:
            queued = sum(sum(a.nbytes for a in r.feed.values())
                         for r in self._q)
            q_reqs = len(self._q)
        with self._done_cv:
            inflight = [t[0] for t in self._done_q]
        staged = sum(sum(a.nbytes for a in r.feed.values())
                     for take in inflight for r in take)
        return {"used": queued + staged, "queued_bytes": queued,
                "inflight_bytes": staged, "queued_requests": q_reqs}

    # -- request side ------------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               tenant: Optional[str] = None) -> "Future":
        """Enqueue one request; the Future resolves to the list of fetch
        arrays (leading dim = this request's rows).  ``tenant`` is an
        optional client-supplied id for per-tenant usage metering
        (``FLAGS_tenant_accounting``; ignored when off).  Raises
        :class:`Overloaded` (shed, never queued) or ``ValueError``
        (malformed feed / batch beyond the top bucket)."""
        arrs = {}
        rows = None
        for n in self.predictor.feed_names:
            if n not in feed:
                raise ValueError(f"request missing feed {n!r}")
            a = np.asarray(feed[n])
            if a.ndim == 0:
                raise ValueError(f"feed {n!r} must be batch-major")
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise ValueError(
                    f"feeds disagree on the batch dim: {n!r} has "
                    f"{a.shape[0]} rows, expected {rows}")
            lim = self._seq_limits.get(n)
            if lim is not None and a.ndim >= 2 and a.shape[1] > lim:
                self.stats.note_shed()
                raise RequestTooLong(self.name, n, a.shape[1], lim)
            contract = self._feed_contract[n]
            if contract[0] is not None and a.shape[1:] != contract[0]:
                raise ValueError(
                    f"feed {n!r} sample shape {a.shape[1:]} does not "
                    f"match this model's {contract[0]}")
            if contract[1] is not None and a.dtype != contract[1]:
                # cast HERE (the executor would cast anyway): a stray
                # float64 request must not promote the whole coalesced
                # batch through np.concatenate
                a = a.astype(contract[1])
            arrs[n] = a
        if not rows:
            raise ValueError("empty request (0 rows)")
        if rows > self.ladder.max:
            raise ValueError(
                f"request of {rows} rows exceeds the top bucket "
                f"{self.ladder.max}; split it client-side")
        req = _Request(arrs, rows, tenant=tenant)
        with self._cv:
            if self._closed:
                raise RuntimeError(f"batcher {self.name!r} is closed")
            for n, a in arrs.items():
                c = self._feed_contract[n]
                if c[0] is None:
                    # no static declaration: the first accepted request
                    # fixes the sample shape (coalescing concatenates
                    # along the batch dim, so mixed trailing shapes
                    # could never share a batch anyway)
                    c[0] = a.shape[1:]
                    if c[1] is None:
                        c[1] = a.dtype
                elif a.shape[1:] != c[0]:
                    raise ValueError(
                        f"feed {n!r} sample shape {a.shape[1:]} does "
                        f"not match this model's {c[0]}")
            depth = self._rows_queued
            if depth + rows > self.max_queue_rows:
                self.stats.note_shed()
                raise Overloaded(self.name, depth, self.max_queue_rows)
            if self.queue_delay_slo_ms is not None and \
                    self._ewma_batch_ms is not None:
                # delay the request would WAIT behind work already
                # accepted (not its own service time — an idle server
                # must admit): queued + in-flight batches, each costing
                # the observed per-batch service time
                backlog = (depth + self.ladder.max - 1) \
                    // self.ladder.max + self._inflight_batches
                est = backlog * self._ewma_batch_ms
                if est > self.queue_delay_slo_ms:
                    self.stats.note_shed()
                    raise Overloaded(self.name, depth, self.max_queue_rows,
                                     est, self.queue_delay_slo_ms)
            self._q.append(req)
            self._rows_queued += rows
            self.stats.set_depth(self._rows_queued)
            self._cv.notify_all()
        self.stats.note_submit(rows)
        if _tenant.enabled():
            _tenant.account(tenant, requests=1, rows=rows)
        return req.future

    def infer(self, feed: Dict[str, np.ndarray],
              timeout: Optional[float] = None,
              tenant: Optional[str] = None) -> List[np.ndarray]:
        """Blocking convenience over :meth:`submit`."""
        return self.submit(feed, tenant=tenant).result(timeout=timeout)

    # -- scheduler ---------------------------------------------------------
    def _sched_loop(self) -> None:
        while True:
            take, total = self._gather()
            if take is None:
                return
            self._dispatch(take, total)

    def _gather(self):
        """Block until a batch is due: top bucket full, the oldest
        request aged past max_delay_ms, or close."""
        max_rows = self.ladder.max
        delay_s = self.max_delay_ms / 1e3
        with self._cv:
            while True:
                if self._q:
                    if self._rows_queued >= max_rows or self._closed:
                        break
                    remaining = self._q[0].t_enq + delay_s - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                elif self._closed:
                    return None, 0
                else:
                    self._cv.wait()
            take, total = [], 0
            while self._q and total + self._q[0].rows <= max_rows:
                r = self._q.popleft()
                take.append(r)
                total += r.rows
            self._rows_queued -= total
            self._inflight_batches += 1
            self.stats.set_depth(self._rows_queued)
        return take, total

    def _dispatch(self, take: List[_Request], total: int) -> None:
        bucket = self.ladder.snap(total)
        t0 = time.monotonic()
        _debug_server.note_activity("serving")
        stamped = any(r.tl is not None for r in take)
        cap = (self.stats.capacity_tracker()
               if _capacity.enabled() else None)
        if stamped:
            # one clock read stamps the whole batch: queue ends when
            # its batch starts assembling
            for r in take:
                if r.tl is not None:
                    r.tl.stamp("queue", t=t0)
        trace_id = None
        t_asm = t_disp = None
        try:
            feed = {}
            for n in self.predictor.feed_names:
                a = (take[0].feed[n] if len(take) == 1
                     else np.concatenate([r.feed[n] for r in take], axis=0))
                feed[n] = _pad_rows(a, bucket - total)
            if stamped or cap is not None:
                t_asm = time.monotonic()
            if stamped:
                for r in take:
                    if r.tl is not None:
                        r.tl.stamp("assemble", t=t_asm)
            # chaos hook: a `delay:serving_dispatch` rule sleeps HERE,
            # inside the dispatch phase — the latency-anatomy test
            # injects a known-slow phase and asserts attribution names
            # it.  Flag-free path: one cheap active() guard.  An
            # `oom:serving_dispatch` rule raises RESOURCE_EXHAUSTED
            # where a real device allocation failure would, so the
            # forensics path below is drillable without HBM pressure
            _faults.event("serving_dispatch")
            _faults.oom_fault("serving_dispatch")
            with _obs_trace.start_span("serving::dispatch", cat="serving",
                                       root=False,
                                       tags={"model": self.name,
                                             "bucket": bucket,
                                             "rows": total}) as sp:
                outs = self.predictor.run(feed)
                trace_id = getattr(sp, "trace_id", None)
            if stamped or cap is not None:
                t_disp = time.monotonic()
            if stamped:
                for r in take:
                    if r.tl is not None:
                        r.tl.stamp("dispatch", t=t_disp)
            err = None
        except Exception as e:
            # OOM forensics: a RESOURCE_EXHAUSTED escaping the dispatch
            # dumps the full ledger + top holders + event tail BEFORE
            # the error re-raises through the request futures (no-op
            # unless FLAGS_memory_attribution and an actual OOM)
            _memory.oom_forensics(e, "serving_dispatch")
            outs, err = None, e
        if cap is not None and t_disp is not None:
            # the scheduler thread's busy legs: ONE span per batch
            # (batch members share the wall — per-request would
            # double-count), so windowed busy/wall is a utilization
            cap.note("assemble", (t_asm - t0) * 1e3)
            cap.note("dispatch", (t_disp - t_asm) * 1e3)
        self.stats.note_batch(total, bucket)
        with self._done_cv:
            self._done_q.append((take, outs, err, t0, trace_id, bucket))
            self._done_cv.notify()

    # -- completion --------------------------------------------------------
    def _complete_loop(self) -> None:
        while True:
            with self._done_cv:
                while not self._done_q:
                    # exit only once the scheduler is done for good: a
                    # momentarily idle in-flight count mid-close must
                    # not strand batches the scheduler is still packing
                    if self._closed and not self._sched.is_alive():
                        return
                    self._done_cv.wait(timeout=0.2)
                take, outs, err, t0, trace_id, bucket = \
                    self._done_q.popleft()
            now = time.monotonic()
            if err is not None:
                for r in take:
                    r.future.set_exception(err)
                self.stats.note_done(
                    len(take), [(now - r.t_enq) * 1e3 for r in take],
                    error=True)
            else:
                cap = (self.stats.capacity_tracker()
                       if _capacity.enabled() else None)
                # materializing the first array flushes the whole
                # batch's pending LazyFetch set in ONE device readback
                outs = [np.asarray(o) for o in outs]
                t_mat = time.monotonic()
                total = sum(r.rows for r in take)
                if cap is not None:
                    # device busy counts from popleft (`now`), not
                    # from dispatch: batches queue in _done_q behind
                    # prior materializations, and that wait is the
                    # PREVIOUS batch's device time
                    cap.note("device", (t_mat - now) * 1e3,
                             bucket=bucket, work=total)
                ten_on = _tenant.enabled()
                dev_ms = (t_mat - now) * 1e3 if ten_on else 0.0
                off = 0
                for r in take:
                    if r.tl is not None:
                        r.tl.stamp("device", t=t_mat)
                    r.future.set_result(
                        [o[off:off + r.rows] for o in outs])
                    if r.tl is not None:
                        # per-request reply stamp: slicing + future
                        # completion, the final leg of the wall
                        r.tl.stamp("reply")
                        self.stats.note_phases(r.tl, trace_id=trace_id)
                    off += r.rows
                now = time.monotonic()
                if cap is not None:
                    cap.note("reply", (now - t_mat) * 1e3)
                    cap.note_done(len(take))
                if ten_on:
                    # the shared batch's device wall splits by row
                    # share, so per-tenant device-ms sums to the
                    # measured wall by construction
                    for r in take:
                        _tenant.account(
                            r.tenant,
                            device_ms=dev_ms * (r.rows / max(total, 1)),
                            latency_ms=(now - r.t_enq) * 1e3)
                self.stats.note_done(
                    len(take), [(now - r.t_enq) * 1e3 for r in take])
            batch_ms = (now - t0) * 1e3
            with self._cv:
                self._inflight_batches -= 1
                e = self._ewma_batch_ms
                self._ewma_batch_ms = (batch_ms if e is None
                                       else 0.8 * e + 0.2 * batch_ms)
                self._cv.notify_all()

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until every accepted request has been answered (the
        hot-swap retire gate).  Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._q or self._rows_queued or self._inflight_batches \
                    or self._done_q:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=min(left, 0.2))
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting, drain what was accepted, join the threads."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        with self._done_cv:
            self._done_cv.notify_all()
        self._sched.join(timeout=timeout)
        self._completer.join(timeout=timeout)
        _capacity.unregister(f"serving.{self.stats.model}")
        if self._mem_pool is not None:
            _memory.unregister(self._mem_pool)

    def queue_rows(self) -> int:
        with self._cv:
            return self._rows_queued
