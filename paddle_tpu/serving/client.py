"""Thin serving client: replica discovery + health-gated failover.

The trainer-side counterpart of :class:`~paddle_tpu.serving.server
.ModelServer`.  Two addressing modes:

- **static**: ``ServingClient(endpoints=["host:port", ...])`` —
  round-robin over a fixed replica list;
- **registry**: ``ServingClient(registry_ep="host:port")`` — replicas
  are discovered from the serving leases
  (``serving/<model>/<replica>``) the servers announce, re-polled every
  ``refresh_s``; replicas whose fleet-health state is DEAD are never
  routed to (health gating), and a replica that refuses a connection is
  benched for ``cooldown_s`` before it is tried again.

Failover policy per request: connection failures rotate to the next
live replica (an INFER that never reached a server is safe to resend);
a typed :class:`Overloaded` reply also rotates — some other replica may
have queue headroom — and only surfaces to the caller when EVERY live
replica shed the request.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .batcher import Draining, Overloaded, RequestTooLong
from . import server as _server
from ..distributed import registry as _dist_registry
from ..distributed import serde, transport


class ServingClient:
    def __init__(self, endpoints: Optional[Sequence[str]] = None,
                 registry_ep: Optional[str] = None, trainer_id: int = 0,
                 refresh_s: float = 2.0, cooldown_s: float = 2.0,
                 connect_timeout_s: float = 5.0):
        if not endpoints and not registry_ep:
            raise ValueError("ServingClient needs endpoints or registry_ep")
        self._static = list(endpoints or [])
        self.registry_ep = registry_ep
        self.refresh_s = refresh_s
        self.cooldown_s = cooldown_s
        # interactive inference must not ride out the transport's
        # trainer-bring-up connect grace on a dead replica: bound each
        # connect attempt and let failover rotate instead
        self.connect_timeout_s = connect_timeout_s
        self._client = transport.RPCClient(trainer_id)
        self._lock = threading.Lock()
        self._rr: Dict[str, int] = {}            # model -> round-robin idx
        self._down: Dict[str, float] = {}        # endpoint -> benched-until
        self._cache: Dict[str, Tuple[float, List[str]]] = {}

    # -- discovery ---------------------------------------------------------
    def _discover(self, model: str) -> List[str]:
        """Live replica endpoints for ``model`` from the registry
        leases, DEAD replicas health-gated out.  Static mode returns
        the fixed list."""
        if not self.registry_ep:
            return list(self._static)
        with self._lock:
            ent = self._cache.get(model)
            if ent is not None and time.monotonic() < ent[0]:
                return list(ent[1])
        try:
            snap = _dist_registry.fetch_snapshot(self._client,
                                                 self.registry_ep)
        except Exception:
            # registry blip (restart, partition): the replicas are very
            # likely still serving — route on the last-known set rather
            # than failing the request on a discovery error
            with self._lock:
                ent = self._cache.get(model)
                if ent is not None and ent[1]:
                    return list(ent[1])
            raise
        try:
            health = _dist_registry.fetch_health(self._client,
                                                 self.registry_ep)
        except Exception:
            health = {}
        eps = []
        for logical, lease in sorted((snap.get("leases") or {}).items()):
            parsed = _server.parse_replica_key(logical)
            if parsed is None or parsed[0] != model:
                continue
            if (health.get(logical) or {}).get("state") == "DEAD":
                continue
            eps.append(lease["endpoint"])
        with self._lock:
            self._cache[model] = (time.monotonic() + self.refresh_s, eps)
        return eps

    def replicas(self, model: str) -> List[str]:
        """The endpoints a request for ``model`` may route to."""
        return self._discover(model)

    def _routable(self, model: str) -> List[str]:
        eps = self._discover(model)
        now = time.monotonic()
        with self._lock:
            live = [e for e in eps if self._down.get(e, 0.0) <= now]
            # every replica benched: desperation beats refusing outright
            return live or eps

    def _bench(self, endpoint: str) -> None:
        with self._lock:
            self._down[endpoint] = time.monotonic() + self.cooldown_s

    # -- inference ---------------------------------------------------------
    def infer_pairs(self, model: str,
                    feed: Dict[str, np.ndarray],
                    tenant: Optional[str] = None) -> List[Tuple[str, object]]:
        """One inference: returns the server's fetch ``(name, array)``
        pairs, failing over across replicas (module doc).  ``tenant``
        rides as a reserved serde pair ONLY when set — absent, the
        frame is byte-identical to tenant-unaware builds, and an old
        server ignores the extra feed (interop both ways)."""
        pairs = [(n, np.asarray(v)) for n, v in sorted(feed.items())]
        if tenant:
            pairs.append((_server.TENANT_FEED_KEY,
                          np.frombuffer(str(tenant).encode("utf-8"),
                                        np.uint8)))
        payload = serde.dumps_batch_vec(pairs)
        eps = self._routable(model)
        if not eps:
            raise RuntimeError(f"no live replicas for model {model!r}")
        with self._lock:
            start = self._rr.get(model, 0)
            self._rr[model] = start + 1
        last_exc: Optional[Exception] = None
        for i in range(len(eps)):
            ep = eps[(start + i) % len(eps)]
            try:
                body = self._client._raw_request(
                    ep, _server.INFER, model, payload,
                    connect_timeout=self.connect_timeout_s)
            except ConnectionError as e:
                self._bench(ep)
                last_exc = e
                continue
            body = memoryview(bytes(body)) if not isinstance(
                body, memoryview) else body
            tag, rest = bytes(body[:1]), body[1:]
            if tag == _server._TAG_OVERLOAD:
                last_exc = Overloaded.from_dict(
                    json.loads(bytes(rest).decode("utf-8")))
                continue  # another replica may have headroom
            if tag == _server._TAG_DRAINING:
                # graceful shutdown straggler: the replica already
                # deregistered — bench it so the next refresh window
                # doesn't re-route here, and rotate NOW
                self._bench(ep)
                last_exc = Draining.from_dict(
                    json.loads(bytes(rest).decode("utf-8")))
                continue
            if tag == _server._TAG_TOO_LONG:
                # terminal: every replica enforces the same max_seq_len,
                # so failing over would just repeat the rejection
                raise RequestTooLong.from_dict(
                    json.loads(bytes(rest).decode("utf-8")))
            return serde.loads_batch(rest, copy=True)
        raise last_exc if last_exc is not None else RuntimeError(
            f"no replica answered for model {model!r}")

    def infer(self, model: str,
              feed: Dict[str, np.ndarray],
              tenant: Optional[str] = None) -> List[np.ndarray]:
        """Fetch arrays in the server's fetch order."""
        return [np.asarray(v)
                for _, v in self.infer_pairs(model, feed, tenant=tenant)]

    # -- admin -------------------------------------------------------------
    def admin(self, endpoint: str, command: dict) -> dict:
        """One SERVING_ADMIN command against a specific server (status,
        load, swap, activate, retire — see :mod:`server`)."""
        out = self._client._raw_request(
            endpoint, _server.SERVING_ADMIN, command.get("cmd", ""),
            json.dumps(command).encode("utf-8"))
        return json.loads(bytes(out).decode("utf-8"))
