"""Model server: the serving plane's RPC front door + replica announce.

Rides the repo's own framed-TCP transport (one more
:class:`~paddle_tpu.distributed.transport.RPCServer` service, like the
pserver/master/registry): an ``INFER`` frame carries a request's feed
tensors in the zero-copy batched serde, the reply streams the fetch
tensors back scatter-gather, and the existing per-request distributed
tracing (PR 4) stitches client → server → ``serving::dispatch`` →
``executor::step`` spans end to end with no new wire format.

Wire protocol (both payloads ride the PR-3 batched serde):

- ``INFER`` (msg 21): ``name`` = model name, payload =
  ``serde.dumps_batch`` of the feed ``(name, array)`` pairs.  Reply
  payload is 1 tag byte + body: ``R`` + serde batch of fetch pairs on
  success, ``O`` + JSON :class:`Overloaded` detail on load-shed (typed,
  never a generic error).  Anything else (unknown model, bad feed)
  surfaces as the transport's ERR frame.
- ``SERVING_ADMIN`` (msg 22): JSON command — ``{"cmd": "status"}``,
  ``{"cmd": "swap"|"load", "model":, "version":, "model_dir":, ...}``,
  ``{"cmd": "activate"|"retire", ...}`` — JSON reply.  This is what
  ``tools/serve.py --swap`` drives.

Replica groups: ``registry_ep`` set ⇒ the server announces one TTL
lease per served model under the logical key
``serving/<model>/<replica_id>`` (value: this server's endpoint), with
the active version + live QPS riding the lease's data payload and the
fleet health plane seeing a ``SERVING``-role heartbeat.  The thin
:class:`~paddle_tpu.serving.client.ServingClient` discovers replicas
from the same registry and fails over health-gated.  No registry ⇒ no
lease traffic, a plain static-endpoint server.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional

from ..observability import flight as _flight

from .batcher import Draining, Overloaded, RequestTooLong
from .model_registry import ModelManager
from ..distributed import faults as _faults
from ..distributed import registry as _registry
from ..distributed import serde, transport
from ..observability import audit as _audit
from ..observability import canary as _canary
from ..observability import debug_server as _debug_server
from ..observability import memory as _memory

# message types: 21/22 keep the one-namespace msg-type space clear of
# transport 1-14, master 16-20, and the observability pulls 24/25
INFER = 21
SERVING_ADMIN = 22

transport.MSG_NAMES.update({INFER: "infer",
                            SERVING_ADMIN: "serving_admin"})

# INFER reply tag bytes (first payload byte)
# reserved serde feed name carrying an optional utf-8 tenant id
# (uint8 bytes); never a real model feed, popped before validation
TENANT_FEED_KEY = "__tenant__"

_TAG_RESULT = b"R"
_TAG_OVERLOAD = b"O"
_TAG_TOO_LONG = b"L"
_TAG_DRAINING = b"D"


def replica_key(model: str, replica_id: str) -> str:
    """The registry lease key a serving replica announces under."""
    return f"serving/{model}/{replica_id}"


def parse_replica_key(logical: str):
    """``(model, replica_id)`` from a serving lease key, else None."""
    parts = logical.split("/", 2)
    if len(parts) == 3 and parts[0] == "serving":
        return parts[1], parts[2]
    return None


class ServingService:
    """``handle()`` contract of transport.RPCServer services."""

    def __init__(self, manager: ModelManager, on_change=None,
                 endpoint: str = "", replica_id: str = ""):
        self.manager = manager
        # server hook: re-announce registry leases after admin changes
        self._on_change = on_change
        self.endpoint = endpoint
        # replica-qualifies the corrupt-fault site so chaos can hit
        # exactly one replica (``corrupt:serving_reply@r1``)
        self.replica_id = replica_id
        # graceful drain: once set, new INFERs get a typed Draining
        # reply (the lease is already deregistered — only stragglers
        # racing the deregistration land here) while accepted requests
        # keep flowing to completion
        self.draining = False
        # in-flight handler count: stop(drain=True) waits for it to
        # reach zero AFTER the batcher drains — a handler still between
        # its future resolving and returning the reply must not have
        # its connection severed by the socket close (the reply would
        # be lost AFTER the batcher swore the request was answered)
        self._inflight = 0
        self._inflight_cv = threading.Condition()

    def handle(self, msg_type, trainer_id, name, payload):
        with self._inflight_cv:
            self._inflight += 1
        try:
            return self._handle(msg_type, trainer_id, name, payload)
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def wait_idle(self, timeout: float) -> bool:
        """Block until no handler is inside :meth:`handle` (bounded)."""
        deadline = time.monotonic() + timeout
        with self._inflight_cv:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._inflight_cv.wait(timeout=min(left, 0.2))
        return True

    def _handle(self, msg_type, trainer_id, name, payload):
        if msg_type == INFER:
            if self.draining:
                e = Draining(name, self.endpoint)
                return transport.OK, [
                    _TAG_DRAINING + json.dumps(e.to_dict()).encode("utf-8")]
            feed = dict(serde.loads_batch(payload, copy=False))
            # wire-optional tenant id: a reserved serde pair the client
            # appends ONLY when set (absent ⇒ frames byte-identical to
            # tenant-unaware builds; old servers ignore the extra feed)
            tenant = None
            t_arr = feed.pop(TENANT_FEED_KEY, None)
            if t_arr is not None:
                import numpy as _np
                tenant = bytes(_np.asarray(t_arr, _np.uint8)).decode(
                    "utf-8", "replace") or None
            try:
                fut, sm = self.manager.serve_request(name, feed,
                                                     tenant=tenant)
            except Overloaded as e:
                return transport.OK, [
                    _TAG_OVERLOAD + json.dumps(e.to_dict()).encode("utf-8")]
            except RequestTooLong as e:
                # typed like Overloaded, but terminal: no replica would
                # accept this request, so the client must NOT fail over
                return transport.OK, [
                    _TAG_TOO_LONG + json.dumps(e.to_dict()).encode("utf-8")]
            # bounded wait: a wedged batcher must surface as an ERR frame
            # to this client, not a connection thread parked forever
            from ..core import flags as _flags
            outs = fut.result(timeout=float(_flags.get_flags("rpc_deadline")))
            # reply names come from the model that ANSWERED — a re-route
            # for names could race a hot-swap onto a different version
            pairs = list(zip(sm.predictor.fetch_names, outs))
            if _faults.active():
                # silent-data-corruption chaos site: applied BEFORE the
                # divergence digest, so an injected SDC looks to the
                # sentinel exactly like a real one (wrong bytes leave
                # the replica, digest and all)
                nbits = _faults.corrupt_fault(
                    f"serving_reply@{self.replica_id}", "serving_reply")
                if nbits and pairs:
                    fname, fval = pairs[0]
                    pairs[0] = (fname, _faults.corrupt_array(fval, nbits))
            if _audit.enabled():
                _audit.note_reply(name, str(sm.version),
                                  _audit.request_hash(feed),
                                  _audit.digest_pairs(pairs))
            return transport.OK, [_TAG_RESULT] + serde.dumps_batch_vec(pairs)
        if msg_type == SERVING_ADMIN:
            body = json.loads(bytes(payload).decode("utf-8"))
            out = self._admin(body)
            return transport.OK, json.dumps(out, default=repr).encode("utf-8")
        return transport.ERR, f"serving: unknown msg {msg_type}".encode()

    def _admin(self, body: dict) -> dict:
        cmd = body.get("cmd")
        m = self.manager
        if cmd == "status":
            return m.servingz()
        if cmd in ("load", "swap"):
            kw = {k: body[k] for k in
                  ("model_dir", "buckets", "sample_shapes", "max_delay_ms",
                   "max_queue_rows", "queue_delay_slo_ms", "max_seq_len")
                  if k in body}
            if cmd == "load":
                sm = m.load(body["model"], body["version"],
                            activate=bool(body.get("activate", True)), **kw)
                out = {"loaded": f"{sm.name}@{sm.version}",
                       "warm": sm.warm_info}
            else:
                out = m.swap(body["model"], body["version"], **kw)
            if self._on_change is not None:
                self._on_change()
            return out
        if cmd == "activate":
            m.activate(body["model"], body["version"])
            return {"active": m.active_version(body["model"])}
        if cmd == "retire":
            m.retire(body["model"], body["version"])
            return {"retired": f"{body['model']}@{body['version']}"}
        raise ValueError(f"serving_admin: unknown cmd {cmd!r}")


class ModelServer:
    """One serving process: RPC endpoint + model manager + announces.

    ``registry_ep`` (optional) turns on replica-group membership; with
    it unset the server opens exactly one listening socket and nothing
    else.  ``manager`` may be shared/prebuilt (in-process tests);
    otherwise the server owns one and closes it on :meth:`stop`.
    """

    def __init__(self, endpoint: str = "127.0.0.1:0",
                 manager: Optional[ModelManager] = None,
                 registry_ep: Optional[str] = None,
                 replica_id: Optional[str] = None,
                 lease_ttl: float = _registry.DEFAULT_TTL):
        self._own_manager = manager is None
        self.manager = manager if manager is not None else ModelManager()
        self.service = ServingService(self.manager,
                                      on_change=self._sync_announcements)
        self._server = transport.RPCServer(endpoint, self.service)
        self.registry_ep = registry_ep
        self.lease_ttl = lease_ttl
        self.replica_id = replica_id or f"{self.endpoint}"
        self.service.replica_id = self.replica_id
        self._canary_client: Optional[transport.RPCClient] = None
        self._hb_lock = threading.Lock()
        self._heartbeats: Dict[str, _registry.Heartbeat] = {}
        self._started = False

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def endpoint(self) -> str:
        host = self._server.endpoint.rsplit(":", 1)[0]
        return f"{host}:{self.port}"

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._server.start()
        self._started = True
        self.service.endpoint = self.endpoint
        _debug_server.register_servingz(self.endpoint,
                                        self.manager.servingz)
        # correctness plane: the golden prober self-arms in any serving
        # process (no-op with FLAGS_canary_probe off)
        _canary.maybe_start_from_flags()
        self._sync_announcements()
        self._sync_canary_targets()

    def stop(self, drain: bool = False, drain_timeout: float = 30.0
             ) -> None:
        """Shut the replica down.  ``drain=True`` is the graceful
        sequence — ordered so a discovery-based client NEVER loses a
        request to the shutdown:

        1. deregister the registry leases FIRST (``bye=True``): clients
           fail over to the remaining replicas before this socket dies;
        2. flip the service to draining: a straggler INFER that raced
           the deregistration gets a typed :class:`Draining` reply (it
           rotates, like Overloaded) instead of being accepted into a
           batcher about to close;
        3. finish every in-flight batch within ``drain_timeout`` (the
           batcher drain gate), THEN close the socket and the manager.

        ``drain=False`` keeps the old immediate-stop behavior."""
        # before draining the heartbeats: an admin-swap handler thread
        # finishing after stop() calls _sync_announcements, which must
        # not re-announce leases for a dead server
        self._started = False
        with self._hb_lock:
            hbs, self._heartbeats = dict(self._heartbeats), {}
        for hb in hbs.values():
            hb.stop(bye=True)
        if drain:
            self.service.draining = True
            deadline = time.monotonic() + drain_timeout
            for sm in self.manager.models():
                left = max(0.1, deadline - time.monotonic())
                if not sm.batcher.drain(timeout=left):
                    _flight.note("serving_drain_timeout",
                                 model=f"{sm.name}@{sm.version}",
                                 endpoint=self.endpoint)
            # the batcher resolving every future is necessary but not
            # sufficient: a handler thread can still be BETWEEN its
            # future resolving and writing the reply when the socket
            # close below severs its connection (seen as a flaky
            # ConnectionError on the very request drain promised to
            # answer).  Wait for the handlers themselves
            if not self.service.wait_idle(
                    max(0.1, deadline - time.monotonic())):
                _flight.note("serving_drain_handler_timeout",
                             endpoint=self.endpoint)
        _debug_server.unregister_servingz(self.endpoint)
        for sm in self.manager.models():
            _canary.unregister_target(replica_key(sm.name, self.replica_id))
        # drain: the transport grants mid-reply connections a bounded
        # grace so the last replies reach the wire before severing
        self._server.stop(graceful_s=2.0 if drain else 0.0)
        if self._own_manager:
            self.manager.close()

    # -- convenience passthroughs (announce-aware) -------------------------
    def load(self, *args, **kw):
        sm = self.manager.load(*args, **kw)
        self._sync_announcements()
        self._sync_canary_targets()
        return sm

    def swap(self, *args, **kw):
        out = self.manager.swap(*args, **kw)
        self._sync_announcements()
        self._sync_canary_targets()
        return out

    # -- registry announce -------------------------------------------------
    def _model_health(self, model: str):
        def probe() -> dict:
            sm = None
            try:
                sm = self.manager._route(model)  # active version
            except KeyError:
                pass
            if sm is None:
                return {"step": 0}
            snap = sm.batcher.stats.snapshot()
            return {"step": snap.get("requests", 0)}
        return probe

    def _model_data(self, model: str):
        def data() -> dict:
            version = self.manager.active_version(model)
            out = {"model": model, "version": version,
                   "endpoint": self.endpoint}
            try:
                sm = self.manager._route(model)
                snap = sm.batcher.stats.snapshot()
                out["qps"] = snap.get("qps", 0.0)
                out["queue_rows"] = sm.batcher.queue_rows()
                if "p99_ms" in snap:
                    out["p99_ms"] = snap["p99_ms"]
                # latency anatomy rides the lease payload (present iff
                # FLAGS_phase_attribution): the fleet sees WHERE each
                # replica's tail goes, not just that it grew
                ph = snap.get("phases")
                if ph and ph.get("slowest_phase"):
                    out["slowest_phase"] = ph["slowest_phase"]
                    out["phase_total_p99_ms"] = ph.get("total_p99_ms")
                # capacity headroom rides the same lease payload
                # (present iff FLAGS_capacity_attribution and the
                # tracker has completed work): a drained-but-saturated
                # replica reads differently from an idle one fleet-wide
                cap = sm.batcher.stats.capacity()
                if cap is not None:
                    hr = cap.headroom()
                    if hr is not None:
                        out.update(hr)
            except KeyError:
                pass
            # correctness plane rides the same lease (canary streaks
            # present iff FLAGS_canary_probe and this replica is a
            # probed target; reply digests present iff
            # FLAGS_divergence_check) — the supervisor's sentinel
            # groups digests ACROSS replicas with zero new RPCs
            can = _canary.lease_rider(replica_key(model, self.replica_id))
            if can is not None:
                out["canary"] = can
            dig = _audit.recent_digests()
            if dig is not None and model in dig:
                out["digests"] = {model: dig[model]}
            # memory anatomy rides the same lease (present iff
            # FLAGS_memory_attribution and pools registered): the
            # ElasticController reads measured byte headroom per role
            mem = _memory.lease_rider()
            if mem is not None:
                out.update(mem)
            return out
        return data

    # -- golden canary targets ---------------------------------------------
    def _canary_submit(self, model: str):
        """A probe submit fn taking the REAL path: loopback RPC through
        the wire INFER handler, so serde, batcher, device, reply
        assembly — and any silent corruption on the way — are all
        inside the probed surface."""
        def submit(feeds: dict, tenant: Optional[str]):
            import numpy as np
            pairs = [(n, np.asarray(v)) for n, v in sorted(feeds.items())]
            if tenant:
                pairs.append((TENANT_FEED_KEY,
                              np.frombuffer(str(tenant).encode("utf-8"),
                                            np.uint8)))
            if self._canary_client is None:
                self._canary_client = transport.RPCClient(0)
            body = self._canary_client._raw_request(
                self.endpoint, INFER, model, serde.dumps_batch_vec(pairs))
            body = memoryview(bytes(body)) if not isinstance(
                body, memoryview) else body
            tag, rest = bytes(body[:1]), body[1:]
            if tag != _TAG_RESULT:
                raise RuntimeError(f"canary probe got reply tag {tag!r}")
            return serde.loads_batch(rest, copy=True)
        return submit

    def _sync_canary_targets(self) -> None:
        """Mirror :meth:`_sync_announcements` for the prober's target
        registry (works registry-less too) — a no-op unless armed."""
        if not _canary.enabled() or not self._started:
            return
        for sm in self.manager.models():
            if sm.state not in ("RETIRED",):
                _canary.register_target(
                    replica_key(sm.name, self.replica_id), sm.name,
                    self._canary_submit(sm.name))

    def _sync_announcements(self) -> None:
        """One registry heartbeat per served MODEL NAME: the lease
        (``serving/<model>/<replica>`` → this endpoint) is the replica
        group membership; its data payload carries the live version so
        a hot-swap is visible fleet-wide within one lease refresh."""
        if not self.registry_ep or not self._started:
            return
        names = {sm.name for sm in self.manager.models()
                 if sm.state not in ("RETIRED",)}
        with self._hb_lock:
            for model in sorted(names - set(self._heartbeats)):
                hb = _registry.Heartbeat(
                    self.registry_ep, replica_key(model, self.replica_id),
                    self.endpoint, ttl=self.lease_ttl, role="SERVING",
                    health_fn=self._model_health(model),
                    data_fn=self._model_data(model))
                hb.start()
                self._heartbeats[model] = hb
            for model in sorted(set(self._heartbeats) - names):
                self._heartbeats.pop(model).stop(bye=True)
