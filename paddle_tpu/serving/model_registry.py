"""Per-server model registry: versioned load, warm pool, atomic hot-swap.

One :class:`ModelManager` owns every model a server process serves.
Each ``(name, version)`` is a :class:`ServedModel` — a Predictor plus
its :class:`~paddle_tpu.serving.batcher.DynamicBatcher` — and a router
maps model name → active version.  The hot-swap sequence
(:meth:`ModelManager.swap`) is the zero-downtime deploy primitive:

1. **load** version B next to the serving version A (own scope, own
   executor — A keeps serving untouched);
2. **warm** B's whole bucket ladder: one
   :meth:`Executor.warm_start` precompile per bucket size, hydrated
   from the persistent compile cache when ``FLAGS_compile_cache_dir``
   is set (an elastic redeploy pays ZERO XLA compiles) — so B's first
   live request never stalls on a compile;
3. **flip** the router atomically — requests arriving after the flip
   route to B, requests already queued on A stay on A;
4. **drain** A (every accepted request answered) and retire it.

No request is dropped and no dispatch leaves the warmed ladder, which
is the measured acceptance (`bench.py serving`: zero dropped, zero
recompiles during a swap under load).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .batcher import BucketLadder, DynamicBatcher

# router states a ServedModel moves through (one-way)
LOADING = "LOADING"
SERVING = "SERVING"
DRAINING = "DRAINING"
RETIRED = "RETIRED"


class ServedModel:
    """One (model, version): predictor + batcher + lifecycle state."""

    def __init__(self, name: str, version: str, predictor,
                 batcher: DynamicBatcher):
        self.name = name
        self.version = str(version)
        self.predictor = predictor
        self.batcher = batcher
        self.state = LOADING
        self.loaded_ts = time.time()
        self.warm_info: Optional[dict] = None

    def snapshot(self) -> dict:
        out = {"version": self.version, "state": self.state,
               "loaded_ts": round(self.loaded_ts, 3),
               "buckets": list(self.batcher.ladder.sizes),
               "max_delay_ms": self.batcher.max_delay_ms,
               "max_queue_rows": self.batcher.max_queue_rows,
               "queue_delay_slo_ms": self.batcher.queue_delay_slo_ms,
               "max_seq_len": self.batcher.max_seq_len}
        if self.warm_info is not None:
            out["warm"] = self.warm_info
        out.update(self.batcher.stats.snapshot())
        return out


def ladder_feed_specs(predictor, ladder: BucketLadder,
                      sample_shapes: Optional[Dict[str, Sequence[int]]]
                      = None) -> List[Dict[str, tuple]]:
    """One warm_start feed-spec dict per bucket size, shapes derived
    from the program's static feed declarations
    (:meth:`Predictor.feed_specs_for_batch`); ``sample_shapes``
    overrides/fills feeds whose declarations have symbolic non-batch
    dims (padded sequence models)."""
    return [predictor.feed_specs_for_batch(b, sample_shapes)
            for b in ladder.sizes]


class ModelManager:
    """The server-side model table + router (module doc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[Tuple[str, str], ServedModel] = {}
        self._active: Dict[str, str] = {}        # name -> active version
        self._loading: set = set()   # (name, version) builds in flight

    # -- load / warm -------------------------------------------------------
    def load(self, name: str, version: str, model_dir: Optional[str] = None,
             predictor=None, config=None, warm: bool = True,
             buckets: Optional[Sequence[int]] = None,
             sample_shapes: Optional[Dict[str, Sequence[int]]] = None,
             activate: bool = False, **batcher_kw) -> ServedModel:
        """Load ``(name, version)`` from ``model_dir`` (or take a
        prebuilt ``predictor``), build its batcher, and warm the bucket
        ladder.  ``activate=True`` additionally flips the router (the
        first version of a model usually loads this way)."""
        version = str(version)
        key = (name, version)
        with self._lock:
            # reserve the key under ONE lock hold: two concurrent admin
            # loads of the same version must not both build (the loser's
            # batcher threads would leak when its insert is overwritten)
            if (key in self._models and
                    self._models[key].state != RETIRED) or \
                    key in self._loading:
                raise ValueError(f"model {name}@{version} already loaded")
            self._loading.add(key)
        try:
            if predictor is None:
                if not model_dir:
                    raise ValueError("load needs model_dir or predictor")
                from ..inference.predictor import AnalysisConfig, \
                    create_predictor
                if config is None:
                    config = AnalysisConfig(model_dir)
                else:
                    config.set_model(model_dir)
                predictor = create_predictor(config)
            ladder = (buckets if isinstance(buckets, BucketLadder)
                      else BucketLadder(buckets))
            # warm BEFORE spinning up the batcher threads: a failed warm
            # (unresolvable feed shapes, bad specs) must not leak a
            # scheduler/completer pair parked on an empty queue
            warm_info = (self._warm(predictor, ladder, sample_shapes)
                         if warm else None)
            batcher = DynamicBatcher(predictor, name=f"{name}@{version}",
                                     buckets=ladder, **batcher_kw)
            sm = ServedModel(name, version, predictor, batcher)
            sm.warm_info = warm_info
            with self._lock:
                self._models[key] = sm
        finally:
            with self._lock:
                self._loading.discard(key)
        if activate:
            self.activate(name, version)
        return sm

    @staticmethod
    def _warm(predictor, ladder: BucketLadder, sample_shapes) -> dict:
        """Precompile the whole bucket ladder (the warm pool): one
        executable per bucket, disk-hydrated when the persistent
        compile cache is enabled.  After this, serving traffic can
        only HIT the executor cache."""
        t0 = time.perf_counter()
        specs = ladder_feed_specs(predictor, ladder, sample_shapes)
        info = predictor.warm_start(specs)
        info["ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        info["buckets"] = list(ladder.sizes)
        return info

    # -- router ------------------------------------------------------------
    def activate(self, name: str, version: str) -> Optional[ServedModel]:
        """Atomically flip the router to ``version``; returns the
        previously active ServedModel (now DRAINING), or None."""
        version = str(version)
        old = None
        with self._lock:
            sm = self._models.get((name, version))
            if sm is None or sm.state in (DRAINING, RETIRED):
                raise KeyError(f"model {name}@{version} is not loaded")
            prev = self._active.get(name)
            self._active[name] = version
            sm.state = SERVING
            if prev is not None and prev != version:
                old = self._models.get((name, prev))
                if old is not None:
                    old.state = DRAINING
        return old

    def swap(self, name: str, version: str, model_dir: Optional[str] = None,
             predictor=None, config=None,
             buckets: Optional[Sequence[int]] = None,
             sample_shapes: Optional[Dict[str, Sequence[int]]] = None,
             drain_timeout: float = 30.0, **batcher_kw) -> dict:
        """The full hot-swap sequence: load+warm B, flip, drain+retire A.
        Serving continues on A until the flip; the flip is one dict
        write under the router lock."""
        t0 = time.perf_counter()
        sm = self.load(name, version, model_dir=model_dir,
                       predictor=predictor, config=config, warm=True,
                       buckets=buckets, sample_shapes=sample_shapes,
                       **batcher_kw)
        old = self.activate(name, version)
        drained = True
        if old is not None:
            drained = old.batcher.drain(timeout=drain_timeout)
            self.retire(name, old.version)
        return {"model": name, "version": version,
                "previous": old.version if old is not None else None,
                "drained": drained, "warm": sm.warm_info,
                "ms": round((time.perf_counter() - t0) * 1e3, 1)}

    def retire(self, name: str, version: str) -> None:
        """Close a drained version's batcher and drop its executables."""
        with self._lock:
            sm = self._models.get((name, str(version)))
            if sm is None:
                return
            if self._active.get(name) == sm.version:
                raise ValueError(
                    f"cannot retire the ACTIVE version {name}@{version}")
            sm.state = RETIRED
        sm.batcher.close()

    # -- serving -----------------------------------------------------------
    def _route(self, name: str) -> ServedModel:
        with self._lock:
            version = self._active.get(name)
            if version is None:
                raise KeyError(f"no active version for model {name!r}")
            return self._models[(name, version)]

    def serve_request(self, name: str, feed, tenant: Optional[str] = None):
        """Route + submit ONE request: ``(future, served_model)``.
        The ServedModel is the one the future will answer from — reply
        metadata (fetch names) must come from it, not from a re-route
        that a concurrent hot-swap may have flipped."""
        sm = self._route(name)
        try:
            return sm.batcher.submit(feed, tenant=tenant), sm
        except RuntimeError as e:
            # lost the race with a hot-swap: routed to the draining
            # version in the instant before its batcher closed — the
            # router has flipped by now, so ONE re-route answers on the
            # new version instead of dropping the request
            if "closed" not in str(e):
                raise
            sm = self._route(name)
            return sm.batcher.submit(feed, tenant=tenant), sm

    def submit(self, name: str, feed, tenant: Optional[str] = None):
        return self.serve_request(name, feed, tenant=tenant)[0]

    def infer(self, name: str, feed,
              timeout: Optional[float] = None,
              tenant: Optional[str] = None) -> List[np.ndarray]:
        return self.submit(name, feed,
                           tenant=tenant).result(timeout=timeout)

    def fetch_names(self, name: str) -> List[str]:
        return list(self._route(name).predictor.fetch_names)

    def active_version(self, name: str) -> Optional[str]:
        with self._lock:
            return self._active.get(name)

    def models(self) -> List[ServedModel]:
        with self._lock:
            return list(self._models.values())

    # -- observability -----------------------------------------------------
    def servingz(self) -> dict:
        """The /servingz payload: router + per-version gauges."""
        with self._lock:
            active = dict(self._active)
            models = dict(self._models)
        return {
            "active": active,
            "models": {f"{n}@{v}": sm.snapshot()
                       for (n, v), sm in sorted(models.items())},
        }

    def close(self) -> None:
        with self._lock:
            models = list(self._models.values())
            self._models.clear()
            self._active.clear()
        for sm in models:
            sm.state = RETIRED
            sm.batcher.close()
