"""DecodeEngine: token-level continuous batching over the paged cache.

The decode-plane hot loop.  One scheduler thread drives two kinds of
dispatch against one :class:`~paddle_tpu.core.executor.Executor`:

- **prefill** — one dispatch per JOINING request, prompt padded to the
  smallest bucket on the prefill ladder (``FLAGS_decode_prefill_buckets``
  — the serving batcher's bucket discipline applied to the time axis).
  It writes the prompt's K/V into the request's cache blocks and samples
  the first token, so a joining stream emits immediately.  Prefill is a
  SEPARATE executable from the decode step: a long new prompt costs the
  in-flight streams exactly one prefill dispatch of latency, never a
  recompile or a batch-shape change.
- **decode step** — ONE dispatch advances every active slot by one
  token: fixed ``[max_slots]`` shapes, inactive slots ride along into
  the reserved trash block.  Requests join (slot assigned at admission)
  and leave (slot freed the moment eos/length finishes it) at token
  granularity — the running batch never drains to reshape.

Both dispatches ride ``Executor.run_callable`` with the cache arrays as
donated cache-resident state, so the executor's compile counters cover
the decode plane: after the ladder + step are warm, a mixed join/leave
load of varying prompt and output lengths is ZERO compiles — the
acceptance pin.

Admission control (the batcher discipline): a bounded pending queue
(``FLAGS_decode_max_queue``) sheds with the serving plane's typed
:class:`Overloaded`; an over-budget prompt/output (off the ladder, or
past the block-table context bound) is a typed
:class:`RequestTooLong`.  Block reservation happens at admission —
``ceil((prompt+max_new)/block_tokens)`` blocks up front — so a running
stream can never hit cache OOM mid-generation.

Two latched flags rebuild the block lifecycle on the refcounted
allocator (:mod:`paddle_tpu.decode.cache`); both off (default) keeps
every code path, allocation order and metric series byte-identical to
the legacy engine:

- ``FLAGS_decode_prefix_cache`` — admission walks the prompt's
  block-aligned prefix against a content-addressed
  :class:`~paddle_tpu.decode.cache.PrefixCache` and ADOPTS hits as
  refcounted references, so a shared system prompt prefills once and
  later requests dispatch only a suffix prefill
  (:meth:`TransformerLM.prefill_suffix`).  Full prompt blocks register
  after prefill; zero-ref cached blocks park in an LRU reclaimed under
  pool pressure.  Hits are capped one block short of the prompt so the
  suffix is never empty (the last position's logits seed the stream).
- ``FLAGS_decode_overcommit`` — admission reserves only
  ``ceil((P+1)/block_tokens)`` blocks and the decode step grows one
  block as a stream crosses each block boundary; when growth cannot
  allocate, the NEWEST running stream is preempted (blocks decref'd,
  generated tokens kept host-side on its handle) and re-queued
  head-of-line for re-prefill of ``prompt + generated[:-1]`` — the
  counter-hash sampler is positional, so a resumed stream's remaining
  tokens are identical to an uninterrupted run.  The oldest stream is
  never evicted: it finishes, frees blocks, and the FIFO head (the
  preempted request) re-admits — no livelock.

Writes into a block that is shared (refcount > 1) or advertised by the
prefix cache fork it first — device block-copy plus a block-table
remap (copy-on-write).  Inside this engine streams only ever append
past their adopted prefix, so forks are the beam decoder's path
(:mod:`paddle_tpu.decode.beam`); the step-side check is the safety
invariant that makes that true by construction.

Observability: ``decode.<name>.*`` counters/gauges/histograms plus the
``/decodez`` debug page (:func:`DecodeEngine.decodez`).
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .cache import PagedKVCache, PrefixCache, blocks_for
from .model import TransformerLM
from ..core import flags as _flags
from ..core.executor import Executor
from ..distributed import faults as _faults
from ..kernels import quant as _quant_kernels
from ..observability import audit as _audit
from ..observability import capacity as _capacity
from ..observability import debug_server as _debug_server
from ..observability import memory as _memory
from ..observability import phase as _phase
from ..observability import stats as _obs_stats
from ..observability import tenant as _tenant
from ..serving.batcher import BucketLadder, Overloaded, RequestTooLong

# decode request phases (FLAGS_phase_attribution): queue = submit ->
# slot claimed, prefill = slot -> first token emitted (the TTFT tail
# minus queue wait), decode = first token -> stream finished.  The
# three sum to the request's end-to-end wall by construction
DECODE_PHASES = ("queue", "prefill", "decode")


class SamplingParams:
    """Per-request sampling config.  ``temperature <= 0`` is greedy;
    ``top_k == 0`` samples the full vocab (under the compiled
    ``TOPK_MAX`` ceiling)."""

    def __init__(self, temperature: float = 0.0, top_k: int = 0,
                 max_new_tokens: int = 32, eos_id: Optional[int] = None,
                 seed: int = 0):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.seed = int(seed)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")

    def to_dict(self) -> dict:
        return {"temperature": self.temperature, "top_k": self.top_k,
                "max_new_tokens": self.max_new_tokens,
                "eos_id": self.eos_id, "seed": self.seed}

    @classmethod
    def from_dict(cls, d: dict) -> "SamplingParams":
        return cls(temperature=d.get("temperature", 0.0),
                   top_k=d.get("top_k", 0),
                   max_new_tokens=d.get("max_new_tokens", 32),
                   eos_id=d.get("eos_id"), seed=d.get("seed", 0) or 0)


class DecodeRequest:
    __slots__ = ("rid", "prompt", "sampling", "t_enq", "handle", "tl",
                 "tenant", "resume_tokens")

    def __init__(self, rid: int, prompt: np.ndarray,
                 sampling: SamplingParams,
                 tenant: Optional[str] = None):
        self.rid = rid
        self.prompt = prompt
        self.sampling = sampling
        self.tenant = tenant
        # set by preemption: the tokens generated before eviction; a
        # non-None value marks a queued request as a RESUME (re-prefill
        # prompt + resume_tokens[:-1], then continue token-exact)
        self.resume_tokens: Optional[List[int]] = None
        self.t_enq = time.monotonic()
        self.handle = DecodeHandle(rid)
        # phase timeline sharing the enqueue stamp (flag-gated; None
        # keeps the flag-off path allocation-free)
        self.tl = (_phase.PhaseTimeline(t0=self.t_enq)
                   if _phase.enabled() else None)


class DecodeHandle:
    """Client-side view of one generation: iterate for the token
    stream, or :meth:`result` for the aggregate."""

    _DONE = object()

    def __init__(self, rid: int):
        self.rid = rid
        self._q: "queue.Queue" = queue.Queue()
        self._tokens: List[int] = []
        self._logits: List[np.ndarray] = []   # capture_logits engines only
        self._final: Optional[dict] = None
        self._err: Optional[BaseException] = None
        self._done = threading.Event()
        self._cancelled = threading.Event()

    # -- engine side -------------------------------------------------------
    def _emit(self, token: int, logits: Optional[np.ndarray]) -> None:
        self._tokens.append(int(token))
        if logits is not None:
            self._logits.append(logits)
        self._q.put(int(token))

    def _finish(self, reason: str) -> None:
        self._final = {"tokens": list(self._tokens), "finish": reason,
                       "n_tokens": len(self._tokens)}
        self._done.set()
        self._q.put(self._DONE)

    def _fail(self, exc: BaseException) -> None:
        self._err = exc
        self._done.set()
        self._q.put(self._DONE)

    # -- client side -------------------------------------------------------
    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._DONE:
                if self._err is not None:
                    raise self._err
                return
            yield item

    def next_token(self, timeout: Optional[float] = None):
        """One token id, or None when the stream is finished; raises
        TimeoutError if the engine produces nothing for ``timeout``
        seconds (the streaming server's bounded wait — a wedged engine
        must surface as a typed error frame, not a parked connection)."""
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"decode request {self.rid}: no token within {timeout}s")
        if item is self._DONE:
            if self._err is not None:
                raise self._err
            return None
        return item

    def cancel(self) -> None:
        """Abandon the generation: the engine retires the request's
        slot (freeing its cache blocks) at the next step boundary, or
        drops it from the pending queue at the next admission sweep.
        No-op once the stream already finished.  Called by the
        streaming server when a client disconnects mid-stream — a
        vanished reader must not keep generating into the void."""
        if not self._done.is_set():
            self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def result(self, timeout: Optional[float] = None) -> dict:
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(f"decode request {self.rid} still running")
        if self._err is not None:
            raise self._err
        return dict(self._final)

    @property
    def tokens(self) -> List[int]:
        return list(self._tokens)

    @property
    def logits(self) -> List[np.ndarray]:
        return list(self._logits)


class _Slot:
    __slots__ = ("req", "blocks", "pos_next", "n_generated", "last_token",
                 "t_last", "cached_tokens", "seq")

    def __init__(self, req: DecodeRequest, blocks: List[int],
                 prompt_len: int, first_token: int,
                 cached_tokens: int = 0, seq: Optional[np.ndarray] = None):
        self.req = req
        self.blocks = blocks
        self.pos_next = prompt_len   # where the last sampled token's
        self.n_generated = 1         # K/V lands on the next step
        self.last_token = first_token
        self.t_last = time.monotonic()
        # prefix-cache / resume bookkeeping (0 / None on the legacy
        # path): positions [0, cached_tokens) are already resident in
        # adopted blocks; ``seq`` is the full token sequence prefill
        # must make resident (prompt, or prompt+generated[:-1] on a
        # preemption resume)
        self.cached_tokens = cached_tokens
        self.seq = seq


class _LatencyStats:
    """The flag-gated token-level latency + goodput bundle
    (``FLAGS_phase_attribution``): created on first use so a flag-off
    process never registers these series.

    - ``ttft_ms``: submit -> first token emitted (queue + prefill; what
      a streaming client perceives as time-to-first-token);
    - ``tbt_ms``: per-stream inter-token interval (time between
      tokens), the token-level tail SLO metric — an SLO rule on
      ``decode.<name>.ttft_ms:p99`` / ``tbt_ms:p99`` reads these;
    - goodput accounting: every decode-step lane is either useful
      (live stream) or padding (inactive slot riding into the trash
      block), every prefill token either real prompt or bucket pad,
      and cancelled streams generated into the void — the counters
      say how much of the device time bought tokens a client kept.
      (Preemption re-prefill compute is accounted separately in
      :class:`_PrefixStats` — ``preempt_reprefill_tokens``.)
    """

    def __init__(self, name: str):
        sc = _obs_stats.scope(f"decode.{name}")
        self.ttft_ms = sc.histogram(
            "ttft_ms", help_str="time to first token: submit -> first "
            "token emitted (queue wait + prefill dispatch)")
        self.tbt_ms = sc.histogram(
            "tbt_ms", help_str="time between tokens, per stream (the "
            "client-perceived per-token latency)")
        self.live_slot_steps = sc.counter(
            "goodput_live_slot_steps", "decode-step lanes that advanced "
            "a live stream (useful device work)")
        self.pad_slot_steps = sc.counter(
            "goodput_pad_slot_steps", "decode-step lanes dispatched for "
            "INACTIVE slots (padding riding into the trash block)")
        self.prefill_tokens = sc.counter(
            "goodput_prefill_tokens", "real prompt tokens prefilled")
        self.pad_prefill_tokens = sc.counter(
            "goodput_pad_prefill_tokens", "pad tokens added snapping "
            "prompts onto the prefill bucket ladder")
        self.cancelled = sc.counter(
            "cancelled", "streams abandoned by their client (engine "
            "retired the slot / dropped the queued request)")
        self.cancelled_tokens = sc.counter(
            "cancelled_tokens", "tokens generated for streams later "
            "cancelled (device work no client kept)")
        self.phases = _phase.PhaseRecorder(f"decode.{name}",
                                           DECODE_PHASES)

    def goodput(self) -> dict:
        live = self.live_slot_steps.value
        pad = self.pad_slot_steps.value
        pre = self.prefill_tokens.value
        pre_pad = self.pad_prefill_tokens.value
        return {
            "live_slot_steps": live, "pad_slot_steps": pad,
            "slot_utilization": round(live / max(live + pad, 1), 4),
            "prefill_tokens": pre, "pad_prefill_tokens": pre_pad,
            "prefill_efficiency": round(pre / max(pre + pre_pad, 1), 4),
            "cancelled": self.cancelled.value,
            "cancelled_tokens": self.cancelled_tokens.value,
        }


class _PrefixStats:
    """Refcounted-pool metric bundle: prefix-cache hit accounting,
    copy-on-write forks, preemption/resume accounting and the pool
    leak invariant.  Created only when ``FLAGS_decode_prefix_cache``
    or ``FLAGS_decode_overcommit`` latched on at engine construction,
    so a flags-off process registers none of these series (the
    byte-identical metric-surface pin)."""

    def __init__(self, name: str):
        sc = _obs_stats.scope(f"decode.{name}")
        self.prefix_lookups = sc.counter(
            "prefix_lookups", "full prompt blocks walked against the "
            "prefix cache at admission (the hit-rate denominator)")
        self.prefix_hits = sc.counter(
            "prefix_hits", "blocks adopted from the prefix cache — "
            "prompt positions that did NOT re-prefill")
        self.prefix_inserts = sc.counter(
            "prefix_inserts", "freshly prefilled full blocks registered "
            "into the prefix cache")
        self.prefix_evictions = sc.counter(
            "prefix_evictions", "parked zero-ref cached blocks reclaimed "
            "to the free list under pool pressure (LRU order)")
        self.prefix_collisions = sc.counter(
            "prefix_collisions", "hash hits rejected by the full "
            "token-id verify (served as a miss, never as wrong K/V)")
        self.saved_prefill_tokens = sc.counter(
            "prefix_saved_prefill_tokens", "prompt tokens whose prefill "
            "compute was skipped via adopted cached blocks")
        self.cow_forks = sc.counter(
            "cow_forks", "shared blocks forked (device block-copy + "
            "table remap) on the first divergent write")
        self.preempts = sc.counter(
            "preempts", "running streams evicted by overcommit pressure "
            "(blocks freed, generated tokens kept host-side)")
        self.preempt_resumes = sc.counter(
            "preempt_resumes", "preempted streams re-admitted via "
            "re-prefill")
        self.reprefill_tokens = sc.counter(
            "preempt_reprefill_tokens", "tokens re-prefilled resuming "
            "preempted streams (overcommit's compute cost)")
        self.blocks_referenced = sc.gauge("blocks_referenced")
        self.blocks_cached = sc.gauge("blocks_cached")
        self.blocks_leaked = sc.gauge(
            "blocks_leaked", "pool invariant: usable blocks neither "
            "free, referenced nor cached — MUST be zero")


class _EngineStats:
    def __init__(self, name: str):
        self._name = name
        self._lat_lock = threading.Lock()
        self._lat: Optional[_LatencyStats] = None
        sc = _obs_stats.scope(f"decode.{name}")
        self.tokens = sc.counter("tokens", "generated tokens (all streams)")
        self.prefills = sc.counter("prefills")
        self.joins = sc.counter(
            "joins", "requests admitted into the running decode batch")
        self.leaves = sc.counter(
            "leaves", "requests retired from the running batch (eos/length)")
        self.shed = sc.counter(
            "shed", "requests refused by admission control (typed "
            "Overloaded/RequestTooLong)")
        self.steps = sc.counter("steps", "decode-step dispatches")
        self.queue = sc.gauge("queue_depth")
        self.active = sc.gauge("slots_active")
        self.blocks_free = sc.gauge("blocks_free")
        self.step_ms = sc.histogram("step_ms")
        self.prefill_ms = sc.histogram("prefill_ms")
        self.token_ms = sc.histogram(
            "token_ms",
            help_str="per-stream inter-token interval (what a client "
                     "perceives as per-token latency)")

    def latency(self) -> _LatencyStats:
        """The flag-gated bundle (lazy: see :class:`_LatencyStats`)."""
        with self._lat_lock:
            if self._lat is None:
                self._lat = _LatencyStats(self._name)
            return self._lat

    @property
    def lat(self) -> Optional[_LatencyStats]:
        return self._lat

    def capacity_tracker(self) -> "_capacity.CapacityTracker":
        """Get-or-create this engine's capacity tracker (callers gate
        on ``_capacity.enabled()`` so a flag-off process never
        registers ``decode.<name>.util.*`` series)."""
        return _capacity.tracker(f"decode.{self._name}",
                                 ("prefill", "decode"))

    def capacity(self) -> Optional["_capacity.CapacityTracker"]:
        return _capacity.get(f"decode.{self._name}")


class DecodeEngine:
    """One model's stateful generative scheduler (module doc)."""

    def __init__(self, model: TransformerLM, params: Dict,
                 name: str = "lm",
                 max_slots: Optional[int] = None,
                 block_tokens: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefill_buckets=None,
                 max_queue: Optional[int] = None,
                 executor: Optional[Executor] = None,
                 capture_logits: bool = False,
                 attn_impl: Optional[str] = None,
                 cache_dtype: Optional[str] = None,
                 prefix_cache: Optional[bool] = None,
                 overcommit: Optional[bool] = None):
        self.model = model
        self.name = name
        cfg = model.config
        self.max_slots = int(_flags.get_flags("decode_max_slots")
                             if max_slots is None else max_slots)
        self.max_queue = int(_flags.get_flags("decode_max_queue")
                             if max_queue is None else max_queue)
        bs = int(_flags.get_flags("decode_block_tokens")
                 if block_tokens is None else block_tokens)
        # block TABLE width: enough blocks per slot for a full-length
        # context — a compiled shape, so it derives from max_seq_len
        self.max_blocks_per_seq = blocks_for(cfg.max_seq_len, bs)
        if num_blocks is None:
            num_blocks = 1 + self.max_slots * self.max_blocks_per_seq
        # KV storage dtype latches at engine build (the compiled state
        # shape): ctor arg wins, else FLAGS_decode_kv_dtype; the
        # "float32" default keeps the flags-off pool byte-identical
        if cache_dtype is None:
            cache_dtype = str(_flags.get_flags("decode_kv_dtype"))
        self.cache = PagedKVCache(cfg.n_layer, cfg.n_head, cfg.head_dim,
                                  num_blocks, bs, dtype=cache_dtype)
        ladder = (prefill_buckets if prefill_buckets is not None
                  else BucketLadder.parse(
                      _flags.get_flags("decode_prefill_buckets")))
        sizes = sorted({int(b) for b in
                        (ladder.sizes if isinstance(ladder, BucketLadder)
                         else ladder) if int(b) <= cfg.max_seq_len})
        if not sizes:
            sizes = [cfg.max_seq_len]
        self.prefill_ladder = BucketLadder(sizes)
        self.capture_logits = capture_logits
        self._attn_impl = attn_impl
        self._exe = executor if executor is not None \
            else Executor(training=False)
        self._plist = model.param_list(params)
        self.stats = _EngineStats(name)
        # refcounted block lifecycle (module doc) — latched here; both
        # flags off keeps the legacy single-owner paths byte-identical
        self._prefix_on = bool(_flags.get_flags("decode_prefix_cache")
                               if prefix_cache is None else prefix_cache)
        self._overcommit_on = bool(_flags.get_flags("decode_overcommit")
                                   if overcommit is None else overcommit)
        self._refc = self._prefix_on or self._overcommit_on
        self.prefix = (PrefixCache(
            self.cache.allocator, bs,
            model_key=f"{name}/{cfg.vocab}x{cfg.d_model}x{cfg.n_layer}")
            if self._prefix_on else None)
        self._pstats = _PrefixStats(name) if self._refc else None
        if self._refc:
            # suffix / resume bucket ladder: a prefix-hit suffix (or a
            # preemption re-prefill, whose length can exceed the
            # prefill ladder) snaps onto block-size doublings so a
            # handful of executables cover every residual length
            limit = self.max_context()
            sizes2 = set(self.prefill_ladder.sizes)
            b2 = bs
            while b2 < limit:
                sizes2.add(b2)
                b2 *= 2
            sizes2.add(limit)
            self._resume_ladder = BucketLadder(sorted(sizes2))

        self._lock = threading.Condition()
        self._pending: List[DecodeRequest] = []
        self._slots: List[Optional[_Slot]] = [None] * self.max_slots
        # decode-step feed rows (host mirrors of the fixed-shape feeds)
        self._tables = np.zeros((self.max_slots, self.max_blocks_per_seq),
                                np.int32)
        self._rid = itertools.count(1)
        self._closed = False
        # memory anatomy (FLAGS_memory_attribution): the KV block pool
        # registers on the process MemoryLedger — pool bytes, per-state
        # block counts (incl. parked LRU blocks), bytes-per-resident-
        # stream — and its refcount invariant feeds the leak sentinel.
        # Flag off: no pool, no series, no thread, _mem_pool stays None
        # so every event-filing site is one attribute check
        self._block_bytes = self.cache.nbytes // max(self.cache.num_blocks,
                                                     1)
        if self.cache.quantized:
            # /quantz: advertise the quantized pool (dtype-aware bytes
            # per block INCLUDING the parallel scale pools)
            _quant_kernels.note_kv_cache(name, {
                "dtype": self.cache.dtype,
                "num_blocks": self.cache.num_blocks,
                "block_tokens": bs,
                "bytes_per_block": self._block_bytes,
                "pool_bytes": self.cache.nbytes,
            })
        self._mem_pool: Optional[str] = None
        if _memory.enabled():
            self._mem_pool = f"decode_kv.{name}"
            _memory.pool(self._mem_pool, "device",
                         self._mem_pool_snapshot,
                         audit=self._mem_pool_audit)
            _memory.maybe_start_sentinel()
        _debug_server.register_decodez(name, self.decodez)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"decode-sched-{name}")
        self._thread.start()

    # -- admission ---------------------------------------------------------
    def max_context(self) -> int:
        return min(self.model.config.max_seq_len,
                   self.cache.max_context(self.max_blocks_per_seq))

    def submit(self, prompt, sampling: Optional[SamplingParams] = None,
               tenant: Optional[str] = None) -> DecodeHandle:
        """Enqueue one generation.  ``tenant`` is an optional
        client-supplied id for per-tenant usage metering
        (``FLAGS_tenant_accounting``; ignored when off).  Raises
        :class:`RequestTooLong` (prompt off the prefill ladder or
        prompt+budget past the context bound) or :class:`Overloaded`
        (queue bound) — both typed, never queued."""
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        limit = self.max_context()
        if prompt.size > self.prefill_ladder.max:
            self.stats.shed.inc()
            raise RequestTooLong(self.name, "prompt", prompt.size,
                                 self.prefill_ladder.max)
        if prompt.size + sampling.max_new_tokens > limit:
            self.stats.shed.inc()
            raise RequestTooLong(
                self.name, "prompt+max_new_tokens",
                prompt.size + sampling.max_new_tokens, limit)
        need = blocks_for(prompt.size + sampling.max_new_tokens,
                          self.cache.block_tokens)
        if need > self.cache.num_blocks - 1:
            # could never be admitted even with the pool idle — typed
            # rejection now, not a head-of-line livelock later
            self.stats.shed.inc()
            raise RequestTooLong(
                self.name, "blocks",
                need * self.cache.block_tokens,
                (self.cache.num_blocks - 1) * self.cache.block_tokens)
        req = DecodeRequest(next(self._rid), prompt, sampling,
                            tenant=tenant)
        if _tenant.enabled():
            _tenant.account(tenant, requests=1)
        with self._lock:
            if self._closed:
                raise RuntimeError(f"decode engine {self.name!r} is closed")
            if len(self._pending) >= self.max_queue:
                self.stats.shed.inc()
                raise Overloaded(self.name, len(self._pending),
                                 self.max_queue)
            self._pending.append(req)
            self.stats.queue.set(len(self._pending))
            self._lock.notify_all()
        return req.handle

    def generate(self, prompt, timeout: Optional[float] = 120.0,
                 **sampling_kw) -> dict:
        """Blocking convenience over :meth:`submit`."""
        return self.submit(
            prompt, SamplingParams(**sampling_kw)).result(timeout=timeout)

    # -- scheduler loop ----------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._closed and not self._pending and \
                        not any(self._slots):
                    self._lock.wait()
                if self._closed:
                    pending = self._pending
                    self._pending = []
                    break_slots = [s for s in self._slots if s is not None]
                    break
                admit = self._admissible_locked()
            for req in admit:
                try:
                    self._prefill(req)
                except Exception as e:   # noqa: BLE001 — fail ONE stream
                    _memory.oom_forensics(e, "decode_prefill")
                    self._release(req, None, error=e)
            if any(s is not None for s in self._slots):
                try:
                    self._decode_step()
                except Exception as e:   # noqa: BLE001
                    if not self._recover_oom(e):
                        self._fail_all(e)
        for req in pending:
            req.handle._fail(RuntimeError("decode engine closed"))
        for slot in break_slots:
            slot.req.handle._fail(RuntimeError("decode engine closed"))

    def _admissible_locked(self) -> List[DecodeRequest]:
        """Pop every pending request that has a free slot AND a full
        block reservation right now (called under the lock)."""
        out = []
        # cancelled-before-admission requests drop from the queue head
        # (a vanished client must not hold a queue slot); they never
        # joined, so they count neither join nor leave
        while self._pending and self._pending[0].handle.cancelled:
            dropped = self._pending.pop(0)
            if dropped.tl is not None:
                self.stats.latency().cancelled.inc()
            dropped.handle._finish("cancelled")
        bs = self.cache.block_tokens
        for i, slot in enumerate(self._slots):
            if slot is not None or not self._pending:
                continue
            req = self._pending[0]
            resume = req.resume_tokens is not None
            if resume and len(req.resume_tokens) > 1:
                # re-prefill target: prompt + generated[:-1]; the LAST
                # generated token's K/V is written by the next decode
                # step (exactly the post-prefill slot contract)
                seq = np.concatenate(
                    [req.prompt,
                     np.asarray(req.resume_tokens[:-1], np.int32)])
            else:
                seq = req.prompt
            L = int(seq.size)
            if self._overcommit_on:
                # lazy reservation: enough for the resident sequence
                # plus the next write position; the decode step grows
                # one block per boundary crossing (or preempts)
                need = blocks_for(L + 1, bs)
                if self._mem_pool is not None and \
                        not self._admit_headroom_ok(need):
                    break   # measured bytes say no room: FIFO head
            else:           # waits for a release, like an alloc miss
                need = blocks_for(
                    req.prompt.size + req.sampling.max_new_tokens, bs)
            acquired: List[int] = []
            start = 0
            if self.prefix is not None:
                # cap one block short of the sequence: prefill must
                # compute >= 1 real position (the stream's next logits)
                cap = min((L - 1) // bs, need)
                if cap > 0:
                    c0 = self.prefix.collisions
                    hits = self.prefix.match(seq, cap)
                    self._pstats.prefix_lookups.inc(cap)
                    dc = self.prefix.collisions - c0
                    if dc:
                        self._pstats.prefix_collisions.inc(dc)
                    # acquire BEFORE the fresh alloc: a referenced hit
                    # cannot be stolen by the LRU reclaim that alloc
                    # may trigger under pressure
                    acquired = [self.prefix.acquire(k) for k, _ in hits]
                    start = len(acquired) * bs
            blocks = self._alloc_blocks(need - len(acquired))
            if blocks is None:
                for b in acquired:       # re-park the hits; FIFO head
                    self.cache.allocator.decref(b)   # waits for blocks
                break
            if self._mem_pool is not None and blocks:
                _memory.note_event("alloc", self._mem_pool,
                                   len(blocks) * self._block_bytes,
                                   rid=req.rid)
            blocks = acquired + blocks
            if _tenant.enabled():
                # resident KV attribution: the stream now holds a ref
                # on every one of its blocks (prefix hits included);
                # the matching negative delta files at retire/preempt
                _tenant.account(req.tenant, resident_kv_bytes=(
                    len(blocks) * self._block_bytes))
            if start:
                self._pstats.prefix_hits.inc(len(acquired))
                self._pstats.saved_prefill_tokens.inc(start)
            self._pending.pop(0)
            # the slot is claimed NOW (table row filled) so a later
            # admission in the same sweep can't take it
            row = self._tables[i]
            row[:] = 0
            row[:len(blocks)] = blocks
            self._slots[i] = _Slot(req, blocks, L,
                                   first_token=-1,   # token set by prefill
                                   cached_tokens=start,
                                   seq=seq if (start or resume) else None)
            if req.tl is not None and not resume:
                req.tl.stamp("queue")   # queue wait ends at slot claim
            if not resume:
                self.stats.joins.inc()   # every join has a matching
            out.append(req)              # leave through _retire
        self.stats.queue.set(len(self._pending))
        self.stats.blocks_free.set(self.cache.allocator.free_blocks)
        self.stats.active.set(sum(s is not None for s in self._slots))
        if self._refc:
            self._update_pool_gauges()
        return out

    def _alloc_blocks(self, n: int) -> Optional[List[int]]:
        """Allocator alloc with prefix-cache backpressure: a miss
        reclaims parked (zero-ref cached) blocks LRU-first and retries
        — a cached block is only ever a loan from the free pool."""
        got = self.cache.allocator.alloc(n)
        if got is None and self.prefix is not None:
            freed = self.prefix.reclaim(
                n - self.cache.allocator.free_blocks)
            if freed:
                self._pstats.prefix_evictions.inc(freed)
                if self._mem_pool is not None:
                    _memory.note_event("reclaim", self._mem_pool,
                                       freed * self._block_bytes)
                got = self.cache.allocator.alloc(n)
        return got

    def _slot_of(self, req: DecodeRequest):
        for i, s in enumerate(self._slots):
            if s is not None and s.req is req:
                return i, s
        raise KeyError(f"request {req.rid} has no slot")

    # -- dispatches --------------------------------------------------------
    def _prefill(self, req: DecodeRequest) -> None:
        t0 = time.perf_counter()
        i, slot = self._slot_of(req)
        if req.handle.cancelled:   # client vanished between admit and here
            self._retire(i, slot, "cancelled")
            return
        resume = req.resume_tokens is not None
        start = slot.cached_tokens
        if resume or start > 0:
            self._prefill_partial(i, slot, req, t0)
            return
        P = req.prompt.size
        bucket = self.prefill_ladder.snap(P)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :P] = req.prompt
        model, quantized = self.model, self.cache.quantized

        def build():
            def fn(feed, state, const):
                if quantized:
                    kc, vc, ks, vs, tok, logits = model.prefill(
                        const, state[0], state[1], *feed,
                        ks=state[2], vs=state[3])
                    return [tok, logits], [kc, vc, ks, vs]
                kc, vc, tok, logits = model.prefill(
                    const, state[0], state[1], *feed)
                return [tok, logits], [kc, vc]
            return fn

        feed = [tokens,
                np.int32(P),
                self._tables[i].copy(),
                np.uint32(req.sampling.seed & 0xFFFFFFFF),
                np.float32(req.sampling.temperature),
                np.int32(req.sampling.top_k)]
        _debug_server.note_activity("decode")
        # chaos hook: `delay:decode_prefill` sleeps here, inside the
        # prefill phase / TTFT window (the SLO-watchdog test's lever)
        _faults.event("decode_prefill")
        (tok, logits), new_state = self._exe.run_callable(
            f"decode/{self.name}/prefill/{bucket}", build, feed,
            state=self.cache.state(), const=self._plist)
        self.cache.update(new_state)
        first = int(np.asarray(tok))
        slot.last_token = first
        slot.t_last = time.monotonic()
        self.stats.prefills.inc()
        self.stats.tokens.inc()
        prefill_ms = (time.perf_counter() - t0) * 1e3
        self.stats.prefill_ms.observe(prefill_ms)
        if _capacity.enabled():
            # the engine thread is serial: prefill wall IS busy time
            self.stats.capacity_tracker().note(
                "prefill", prefill_ms, bucket=bucket, work=1)
        if _tenant.enabled():
            # a prefill serves exactly one request: its whole wall is
            # that tenant's device time
            _tenant.account(req.tenant, prefill_tokens=P,
                            device_ms=prefill_ms)
        if req.tl is not None:
            req.tl.stamp("prefill", t=slot.t_last)
            lat = self.stats.latency()
            lat.ttft_ms.observe((slot.t_last - req.t_enq) * 1e3)
            lat.prefill_tokens.inc(P)
            lat.pad_prefill_tokens.inc(bucket - P)
        self._register_prefix(slot, req.prompt)
        req.handle._emit(
            first, np.asarray(logits) if self.capture_logits else None)
        self._maybe_finish(i, slot, first)

    def _prefill_partial(self, i: int, slot: _Slot, req: DecodeRequest,
                         t0: float) -> None:
        """Prefill with a resident prefix (prefix-cache hits) and/or a
        preemption resume: only positions [start, L) dispatch, via
        :meth:`TransformerLM.prefill_suffix` (a full re-prefill when
        start == 0 rides the dense :meth:`TransformerLM.prefill` on
        the wider resume ladder).  On resume the sampled token is
        DISCARDED and the slot restored to its pre-eviction state —
        the next decode step re-samples token index n_generated, which
        the positional counter-hash makes identical to the token the
        stream would have produced uninterrupted."""
        resume = req.resume_tokens is not None
        seq = slot.seq if slot.seq is not None else req.prompt
        L = int(seq.size)
        start = slot.cached_tokens
        model, quantized = self.model, self.cache.quantized
        if start > 0:
            n = L - start
            bucket = self._resume_ladder.snap(n)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :n] = seq[start:]

            def build():
                def fn(feed, state, const):
                    if quantized:
                        kc, vc, ks, vs, tok, logits = \
                            model.prefill_suffix(
                                const, state[0], state[1], *feed,
                                ks=state[2], vs=state[3])
                        return [tok, logits], [kc, vc, ks, vs]
                    kc, vc, tok, logits = model.prefill_suffix(
                        const, state[0], state[1], *feed)
                    return [tok, logits], [kc, vc]
                return fn

            feed = [tokens,
                    np.int32(start),
                    np.int32(L),
                    self._tables[i].copy(),
                    np.uint32(req.sampling.seed & 0xFFFFFFFF),
                    np.float32(req.sampling.temperature),
                    np.int32(req.sampling.top_k)]
            key = f"decode/{self.name}/prefill_sfx/{bucket}"
        else:
            n = L
            bucket = self._resume_ladder.snap(L)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :L] = seq

            def build():
                def fn(feed, state, const):
                    if quantized:
                        kc, vc, ks, vs, tok, logits = model.prefill(
                            const, state[0], state[1], *feed,
                            ks=state[2], vs=state[3])
                        return [tok, logits], [kc, vc, ks, vs]
                    kc, vc, tok, logits = model.prefill(
                        const, state[0], state[1], *feed)
                    return [tok, logits], [kc, vc]
                return fn

            feed = [tokens,
                    np.int32(L),
                    self._tables[i].copy(),
                    np.uint32(req.sampling.seed & 0xFFFFFFFF),
                    np.float32(req.sampling.temperature),
                    np.int32(req.sampling.top_k)]
            key = f"decode/{self.name}/prefill/{bucket}"
        _debug_server.note_activity("decode")
        _faults.event("decode_prefill")
        (tok, logits), new_state = self._exe.run_callable(
            key, build, feed, state=self.cache.state(), const=self._plist)
        self.cache.update(new_state)
        slot.t_last = time.monotonic()
        self.stats.prefills.inc()
        prefill_ms = (time.perf_counter() - t0) * 1e3
        self.stats.prefill_ms.observe(prefill_ms)
        if _capacity.enabled():
            self.stats.capacity_tracker().note(
                "prefill", prefill_ms, bucket=bucket, work=1)
        if _tenant.enabled():
            _tenant.account(req.tenant, prefill_tokens=n,
                            device_ms=prefill_ms)
        if req.tl is not None and not resume:
            req.tl.stamp("prefill", t=slot.t_last)
            lat = self.stats.latency()
            lat.ttft_ms.observe((slot.t_last - req.t_enq) * 1e3)
            lat.prefill_tokens.inc(n)
            lat.pad_prefill_tokens.inc(bucket - n)
        self._register_prefix(slot, seq)
        if resume:
            # restore the evicted stream's exact slot state; the
            # freshly sampled token is a DISCARD (it re-derives
            # resume_tokens[start's] successor which the client
            # already has)
            gen = req.resume_tokens
            slot.pos_next = L
            slot.n_generated = len(gen)
            slot.last_token = int(gen[-1])
            req.resume_tokens = None
            self._pstats.preempt_resumes.inc()
            self._pstats.reprefill_tokens.inc(n)
            return
        first = int(np.asarray(tok))
        slot.last_token = first
        self.stats.tokens.inc()
        req.handle._emit(
            first, np.asarray(logits) if self.capture_logits else None)
        self._maybe_finish(i, slot, first)

    def _register_prefix(self, slot: _Slot, seq: np.ndarray) -> None:
        """Advertise the slot's freshly prefilled FULL blocks in the
        prefix cache (content is immutable from here: the stream only
        ever appends past them).  Hit blocks [0, cached_tokens) are
        already registered."""
        if self.prefix is None:
            return
        bs = self.cache.block_tokens
        toks = [int(t) for t in seq]
        keys = self.prefix.chain_keys(toks)
        inserted = 0
        for bi in range(slot.cached_tokens // bs, len(seq) // bs):
            if self.prefix.insert(keys[bi], toks[:(bi + 1) * bs],
                                  slot.blocks[bi]):
                inserted += 1
        if inserted:
            self._pstats.prefix_inserts.inc(inserted)

    def _decode_step(self) -> None:
        t0 = time.perf_counter()
        # retire cancelled slots FIRST: their blocks free before this
        # step's admission sweep ran, and they must not burn a batch
        # lane generating for a vanished reader
        for i, slot in enumerate(self._slots):
            if slot is not None and slot.req.handle.cancelled:
                self._retire(i, slot, "cancelled")
        if self._refc:
            # overcommit growth + copy-on-write forks (may preempt)
            self._ensure_blocks()
        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        seeds = np.zeros((self.max_slots,), np.uint32)
        steps = np.zeros((self.max_slots,), np.int32)
        temps = np.zeros((self.max_slots,), np.float32)
        topks = np.zeros((self.max_slots,), np.int32)
        tables = self._tables.copy()
        live = []
        for i, slot in enumerate(self._slots):
            if slot is None:
                tables[i, :] = 0   # trash block: masked garbage
                continue
            live.append(i)
            tokens[i] = slot.last_token
            positions[i] = slot.pos_next
            seeds[i] = slot.req.sampling.seed & 0xFFFFFFFF
            steps[i] = slot.n_generated   # this dispatch samples token
            temps[i] = slot.req.sampling.temperature  # index n_generated
            topks[i] = slot.req.sampling.top_k
        if not live:
            return
        model, impl = self.model, self._attn_impl
        quantized = self.cache.quantized

        def build():
            def fn(feed, state, const):
                if quantized:
                    kc, vc, ks, vs, toks, logits = model.decode_step(
                        const, state[0], state[1], *feed,
                        attn_impl=impl, ks=state[2], vs=state[3])
                    return [toks, logits], [kc, vc, ks, vs]
                kc, vc, toks, logits = model.decode_step(
                    const, state[0], state[1], *feed, attn_impl=impl)
                return [toks, logits], [kc, vc]
            return fn

        _debug_server.note_activity("decode")
        # chaos hook: `delay:decode_step` sleeps inside the decode
        # phase (per-token latency); cheap active() guard when off.
        # `oom:decode_step` raises a realistic RESOURCE_EXHAUSTED here
        # — exactly where a real allocation failure would surface — so
        # the OOM-forensics + preempt-and-recover path is drillable
        # without real HBM pressure
        _faults.event("decode_step")
        _faults.oom_fault("decode_step")
        (toks, logits), new_state = self._exe.run_callable(
            f"decode/{self.name}/step", build,
            [tokens, positions, tables, seeds, steps, temps, topks],
            state=self.cache.state(), const=self._plist)
        self.cache.update(new_state)
        toks_np = np.asarray(toks)
        logits_np = np.asarray(logits) if self.capture_logits else None
        now = time.monotonic()
        self.stats.steps.inc()
        step_ms = (time.perf_counter() - t0) * 1e3
        self.stats.step_ms.observe(step_ms)
        if _capacity.enabled():
            self.stats.capacity_tracker().note(
                "decode", step_ms, work=len(live))
        if _tenant.enabled():
            # the fixed-width step's wall splits evenly over the LIVE
            # slots (pad lanes belong to nobody), so per-tenant
            # device-ms sums to the measured step wall
            share = step_ms / len(live)
            for i in live:
                _tenant.account(self._slots[i].req.tenant,
                                decode_tokens=1, device_ms=share)
        lat = self.stats.latency() if _phase.enabled() else None
        if lat is not None:
            lat.live_slot_steps.inc(len(live))
            lat.pad_slot_steps.inc(self.max_slots - len(live))
        for i in live:
            slot = self._slots[i]
            tok = int(toks_np[i])
            slot.pos_next += 1
            slot.n_generated += 1
            slot.last_token = tok
            self.stats.tokens.inc()
            self.stats.token_ms.observe((now - slot.t_last) * 1e3)
            if lat is not None:
                lat.tbt_ms.observe((now - slot.t_last) * 1e3)
            slot.t_last = now
            slot.req.handle._emit(
                tok, logits_np[i] if logits_np is not None else None)
            self._maybe_finish(i, slot, tok)

    # -- refcounted block lifecycle (prefix cache / overcommit) ------------
    def _ensure_blocks(self) -> None:
        """Make every live slot's write-target block PRESENT (overcommit
        growth: one block per boundary crossing) and PRIVATE (fork a
        block that is shared or advertised by the prefix cache before
        writing into it).  Runs before each step dispatch; allocation
        failure preempts the newest stream and retries — bounded by the
        live-slot count, and the oldest stream is never evicted, so the
        engine always makes forward progress."""
        bs = self.cache.block_tokens
        alloc = self.cache.allocator
        for i in range(self.max_slots):
            slot = self._slots[i]
            if slot is None:
                continue
            j = slot.pos_next // bs
            while j >= len(slot.blocks):
                got = self._alloc_blocks(1)
                if got is not None:
                    with self._lock:
                        slot.blocks.append(got[0])
                        self._tables[i, len(slot.blocks) - 1] = got[0]
                    if self._mem_pool is not None:
                        _memory.note_event("alloc", self._mem_pool,
                                           self._block_bytes,
                                           rid=slot.req.rid, grow=True)
                    if _tenant.enabled():
                        _tenant.account(slot.req.tenant,
                                        resident_kv_bytes=self._block_bytes)
                    break
                self._preempt_newest()
                if self._slots[i] is None:   # preempted itself
                    break
            slot = self._slots[i]
            if slot is None or j >= len(slot.blocks):
                continue
            b = slot.blocks[j]
            if alloc.refcount(b) > 1 or (self.prefix is not None
                                         and self.prefix.holds(b)):
                nb: Optional[int] = None
                while nb is None:
                    got = self._alloc_blocks(1)
                    if got is not None:
                        nb = got[0]
                        break
                    self._preempt_newest()
                    if self._slots[i] is None:
                        break
                if self._slots[i] is None or nb is None:
                    continue
                self._copy_block(b, nb)
                with self._lock:
                    slot.blocks[j] = nb
                    self._tables[i, j] = nb
                alloc.decref(b)
                self._pstats.cow_forks.inc()
                if self._mem_pool is not None:
                    # net-zero for the tenant (block swap), but the
                    # timeline names the fork
                    _memory.note_event("alloc", self._mem_pool,
                                       self._block_bytes,
                                       rid=slot.req.rid, cow=True)
        self._update_pool_gauges()

    def _preempt_newest(self) -> None:
        """Evict the NEWEST (highest rid) live stream: free its blocks,
        keep its generated tokens host-side on the handle, and requeue
        it head-of-line for re-prefill.  Newest-victim keeps the oldest
        stream running to completion — freed blocks then admit the FIFO
        head (the preempted request), the no-livelock argument."""
        v = None
        for j, s in enumerate(self._slots):
            if s is not None and (v is None or
                                  s.req.rid > self._slots[v].req.rid):
                v = j
        if v is None:
            return
        slot = self._slots[v]
        req = slot.req
        # chaos hook: `kill_after:decode_preempt` dies HERE, mid-
        # eviction — the replica vanishes with the pool half-mutated;
        # the supervisor-respawned replica must come back with a clean
        # pool invariant (the chaos_lite pin)
        _faults.event("decode_preempt")
        parked_before = (self.prefix.parked_blocks
                         if self.prefix is not None else 0)
        with self._lock:
            self._slots[v] = None
            self.cache.allocator.release(slot.blocks)
            self._tables[v, :] = 0
            req.resume_tokens = list(req.handle._tokens)
            self._pending.insert(0, req)
            self.stats.queue.set(len(self._pending))
            self.stats.active.set(
                sum(s is not None for s in self._slots))
            self.stats.blocks_free.set(self.cache.allocator.free_blocks)
            self._lock.notify_all()
        self._pstats.preempts.inc()
        self._note_blocks_released(len(slot.blocks), parked_before,
                                   "preempt", rid=req.rid)
        if _tenant.enabled():
            _tenant.account(req.tenant, resident_kv_bytes=-(
                len(slot.blocks) * self._block_bytes))

    def _copy_block(self, src: int, dst: int) -> None:
        """Device block-copy (the COW fork): one tiny jitted callable
        on the donated cache state — K/V never round-trip to host.
        Every cache pool (codes AND, when quantized, the per-block
        scale pools) keeps its block axis at dim 1, so one generic
        loop forks them all — a forked block carries its scales."""
        def build():
            def fn(feed, state, const):
                s, d = feed
                return [], [a.at[:, d].set(a[:, s]) for a in state]
            return fn

        _, new_state = self._exe.run_callable(
            f"decode/{self.name}/blkcopy", build,
            [np.int32(src), np.int32(dst)],
            state=self.cache.state(), const=[])
        self.cache.update(new_state)

    def _update_pool_gauges(self) -> None:
        if not self._refc:
            return
        alloc = self.cache.allocator
        parked = self.prefix.parked_blocks if self.prefix is not None else 0
        self._pstats.blocks_referenced.set(alloc.referenced_blocks)
        self._pstats.blocks_cached.set(parked)
        self._pstats.blocks_leaked.set(alloc.leaked(parked))

    # -- retirement --------------------------------------------------------
    def _maybe_finish(self, i: int, slot: _Slot, token: int) -> None:
        s = slot.req.sampling
        if s.eos_id is not None and token == s.eos_id:
            self._retire(i, slot, "eos")
        elif slot.n_generated >= s.max_new_tokens:
            self._retire(i, slot, "length")

    def _retire(self, i: int, slot: _Slot, reason: str) -> None:
        """Free the slot + its cache blocks and finish the stream
        (eos / length / cancelled all leave through here)."""
        parked_before = (self.prefix.parked_blocks
                         if self.prefix is not None else 0)
        with self._lock:
            self._slots[i] = None
            self.cache.allocator.release(slot.blocks)
            self._tables[i, :] = 0
            self.stats.leaves.inc()
            self.stats.active.set(sum(x is not None for x in self._slots))
            self.stats.blocks_free.set(self.cache.allocator.free_blocks)
            self._update_pool_gauges()
            self._lock.notify_all()   # blocks freed: admit the queue head
        req = slot.req
        self._note_blocks_released(len(slot.blocks), parked_before,
                                   "free", rid=req.rid, reason=reason)
        if _capacity.enabled():
            self.stats.capacity_tracker().note_done(1)
        if _tenant.enabled():
            _tenant.account(
                req.tenant,
                cancellations=1 if reason == "cancelled" else 0,
                resident_kv_bytes=-(len(slot.blocks) * self._block_bytes),
                latency_ms=(time.monotonic() - req.t_enq) * 1e3)
        if req.tl is not None:
            lat = self.stats.latency()
            if reason == "cancelled":
                lat.cancelled.inc()
                lat.cancelled_tokens.inc(slot.n_generated)
            # close the decode phase (zero-width for a stream finished
            # at its first token) and fold the timeline in: the three
            # phases sum to this request's end-to-end wall
            req.tl.stamp("decode")
            lat.phases.observe(req.tl, rid=req.rid, finish=reason,
                               tokens=slot.n_generated)
        if _audit.enabled() and reason != "cancelled":
            # per-stream token-id rolling hash into the audit ring,
            # keyed by the prompt's content hash so replicas that
            # decoded the SAME prompt are comparable fleet-wide.
            # Cancelled streams truncate at client timing, never at
            # model output — they are not comparable and stay out
            h = _audit.fnv1a64(b"")
            for t in req.handle._tokens:
                h = _audit.fold_token(h, t)
            _audit.note_stream(self.name, "",
                               _audit.request_hash(req.prompt), h)
        req.handle._finish(reason)

    def _release(self, req: DecodeRequest, slot_idx, error) -> None:
        parked_before = (self.prefix.parked_blocks
                         if self.prefix is not None else 0)
        released = 0
        with self._lock:
            for i, s in enumerate(self._slots):
                if s is not None and s.req is req:
                    self.cache.allocator.release(s.blocks)
                    released += len(s.blocks)
                    self._tables[i, :] = 0
                    self._slots[i] = None
                    self.stats.leaves.inc()
            self.stats.blocks_free.set(self.cache.allocator.free_blocks)
            self.stats.active.set(sum(x is not None for x in self._slots))
            self._update_pool_gauges()
        if released:
            self._note_blocks_released(released, parked_before, "free",
                                       rid=req.rid, reason="error")
            if _tenant.enabled():
                _tenant.account(req.tenant, resident_kv_bytes=-(
                    released * self._block_bytes))
        req.handle._fail(error)

    def _fail_all(self, error) -> None:
        parked_before = (self.prefix.parked_blocks
                         if self.prefix is not None else 0)
        released = 0
        with self._lock:
            slots, self._slots = (list(self._slots),
                                  [None] * self.max_slots)
            for s in slots:
                if s is not None:
                    self.cache.allocator.release(s.blocks)
                    released += len(s.blocks)
                    self.stats.leaves.inc()
            self._tables[:] = 0
            self._update_pool_gauges()
        if released:
            self._note_blocks_released(released, parked_before, "free",
                                       reason="fail_all")
        for s in slots:
            if s is not None:
                if _tenant.enabled():
                    _tenant.account(s.req.tenant, resident_kv_bytes=-(
                        len(s.blocks) * self._block_bytes))
                s.req.handle._fail(error)

    # -- memory anatomy ----------------------------------------------------
    def _mem_pool_snapshot(self) -> dict:
        """The MemoryLedger callback: this engine's KV pool bytes by
        state.  Lock-light (counter reads race admission by at most one
        block — the ledger is a snapshot, not a barrier)."""
        alloc = self.cache.allocator
        parked = (self.prefix.parked_blocks
                  if self.prefix is not None else 0)
        bb = self._block_bytes
        resident = sum(s is not None for s in self._slots)
        out = {"reserved": self.cache.nbytes,
               "used": alloc.referenced_blocks * bb,
               "parked": parked * bb,
               "block_bytes": bb,
               "blocks": {"size": self.cache.num_blocks,
                          "free": alloc.free_blocks,
                          "referenced": alloc.referenced_blocks,
                          "parked": parked},
               "resident_streams": resident}
        if resident:
            out["bytes_per_resident_stream"] = (
                alloc.referenced_blocks * bb // resident)
        return out

    def _mem_pool_audit(self) -> int:
        """The leak sentinel's refcount invariant: blocks neither free
        nor referenced nor parked nor the trash block — must be 0."""
        parked = (self.prefix.parked_blocks
                  if self.prefix is not None else 0)
        return self.cache.allocator.leaked(parked)

    def _note_blocks_released(self, n_blocks: int, parked_before: int,
                              kind: str, **extra) -> None:
        """File block-release events: blocks the prefix cache kept
        (refcount hit zero while advertised) park, the rest free."""
        if self._mem_pool is None or n_blocks <= 0:
            return
        parked_now = (self.prefix.parked_blocks
                      if self.prefix is not None else 0)
        d = min(max(parked_now - parked_before, 0), n_blocks)
        bb = self._block_bytes
        if d:
            _memory.note_event("park", self._mem_pool, d * bb)
        if n_blocks - d:
            _memory.note_event(kind, self._mem_pool,
                               (n_blocks - d) * bb, **extra)

    def _admit_headroom_ok(self, need_blocks: int) -> bool:
        """Overcommit admission's measured-bytes consult: admit only
        while the ledger's byte view of the pool agrees there is room
        (reserved − used; parked bytes are reclaimable so they count
        as headroom).  Attribution that disagrees with the allocator
        would be a bug, so this is a cross-check, not a second
        allocator — and it only exists when the ledger does."""
        p = _memory.get(self._mem_pool)
        if p is None:
            return True
        s = p.snapshot()
        return s["reserved"] - s["used"] >= need_blocks * self._block_bytes

    def _recover_oom(self, error) -> bool:
        """OOM forensics + recovery: a RESOURCE_EXHAUSTED escaping the
        step dispatch dumps a named post-mortem (full ledger, top
        holders, event tail) and — when the refcounted lifecycle is on
        and a stream is live — sheds the NEWEST stream through the
        existing preemption path (counted), so the engine keeps
        serving instead of failing every slot.  Returns False (caller
        falls through to _fail_all) when unarmed or not an OOM."""
        if self._mem_pool is None or not _memory.is_oom(error):
            return False
        _memory.oom_forensics(error, "decode_step")
        if not self._refc or not any(s is not None for s in self._slots):
            return False
        self._preempt_newest()
        _obs_stats.scope(f"decode.{self.name}").counter(
            "oom_recovered", "RESOURCE_EXHAUSTED step dispatches "
            "survived by preempting the newest stream").inc()
        return True

    # -- observability -----------------------------------------------------
    def decodez(self) -> dict:
        """The /decodez payload: slots, cache, queue, recent rates."""
        with self._lock:
            slots = [
                None if s is None else {
                    "rid": s.req.rid, "prompt_len": int(s.req.prompt.size),
                    "generated": s.n_generated,
                    "context_len": int(s.pos_next),
                    "max_new_tokens": s.req.sampling.max_new_tokens}
                for s in self._slots]
            pending = len(self._pending)
        out = {
            "model": self.name,
            "config": self.model.config.to_dict(),
            "cache": self.cache.snapshot(),
            "max_blocks_per_seq": self.max_blocks_per_seq,
            "prefill_buckets": list(self.prefill_ladder.sizes),
            "max_slots": self.max_slots,
            "slots": slots,
            "queue_depth": pending,
            "tokens": self.stats.tokens.value,
            "steps": self.stats.steps.value,
            "prefills": self.stats.prefills.value,
            "joins": self.stats.joins.value,
            "leaves": self.stats.leaves.value,
            "shed": self.stats.shed.value,
        }
        if self._refc:
            # the refcounted block lifecycle (flag-latched; absent
            # flags-off so the payload shape stays byte-identical)
            alloc = self.cache.allocator
            parked = (self.prefix.parked_blocks
                      if self.prefix is not None else 0)
            ps = self._pstats
            out["block_pool"] = {
                "size": self.cache.num_blocks,
                "free": alloc.free_blocks,
                "referenced": alloc.referenced_blocks,
                "cached": parked,
                "leaked": alloc.leaked(parked),
                "cow_forks": ps.cow_forks.value,
                "overcommit": self._overcommit_on,
            }
            if self.prefix is not None:
                lk, ht = ps.prefix_lookups.value, ps.prefix_hits.value
                out["prefix_cache"] = {
                    "entries": len(self.prefix),
                    "cached_blocks": parked,
                    "lookups": lk,
                    "hits": ht,
                    "hit_rate": round(ht / max(lk, 1), 4),
                    "saved_prefill_tokens": ps.saved_prefill_tokens.value,
                    "inserts": ps.prefix_inserts.value,
                    "evictions": ps.prefix_evictions.value,
                    "collisions": self.prefix.collisions,
                }
            if self._overcommit_on:
                out["preemption"] = {
                    "preempts": ps.preempts.value,
                    "resumes": ps.preempt_resumes.value,
                    "reprefill_tokens": ps.reprefill_tokens.value,
                }
        snap = self.stats.step_ms.snapshot()
        if snap.get("count"):
            out["step_p50_ms"] = self.stats.step_ms.percentile(0.50)
            out["step_p99_ms"] = self.stats.step_ms.percentile(0.99)
        tsnap = self.stats.token_ms.snapshot()
        if tsnap.get("count"):
            out["token_p50_ms"] = self.stats.token_ms.percentile(0.50)
            out["token_p99_ms"] = self.stats.token_ms.percentile(0.99)
        lat = self.stats.lat
        if lat is not None:
            # the FLAGS_phase_attribution plane: TTFT/TBT tails,
            # goodput accounting, per-phase attribution
            if lat.ttft_ms.count:
                out["ttft_p50_ms"] = lat.ttft_ms.percentile(0.50)
                out["ttft_p99_ms"] = lat.ttft_ms.percentile(0.99)
            if lat.tbt_ms.count:
                out["tbt_p50_ms"] = lat.tbt_ms.percentile(0.50)
                out["tbt_p99_ms"] = lat.tbt_ms.percentile(0.99)
            out["goodput"] = lat.goodput()
            out["phases"] = lat.phases.snapshot()
        cap = self.stats.capacity()
        if cap is not None:
            out["capacity"] = cap.snapshot()
        return out

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout: float = 60.0) -> bool:
        """Wait until every accepted request has finished."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._pending or any(s is not None for s in self._slots):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._lock.wait(timeout=min(left, 0.2))
        return True

    def close(self, timeout: float = 30.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._lock.notify_all()
        self._thread.join(timeout=timeout)
        if self._mem_pool is not None:
            _memory.unregister(self._mem_pool)
        _debug_server.unregister_decodez(self.name)
        _capacity.unregister(f"decode.{self.name}")
