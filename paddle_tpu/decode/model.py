"""Decoder-only transformer LM adapter for the decode plane.

The decode engine needs a model expressed as three pure-JAX functions
sharing one parameter schema — a full causal forward (the re-prefill
baseline and the parity anchor), a prompt prefill that WRITES the paged
cache, and a one-token decode step that READS it through the paged
attention kernel:

- :meth:`TransformerLM.full_logits` — ``tokens [B, T] → logits
  [B, T, V]``, plain causal attention over the whole prefix.
- :meth:`TransformerLM.prefill` — padded prompt ``[1, Tb]`` (Tb on the
  prefill bucket ladder) → last-position logits + first sampled token,
  with every real position's K/V scattered into the request's cache
  blocks (padded positions scatter into the reserved trash block 0).
- :meth:`TransformerLM.decode_step` — the continuous-batching hot
  dispatch: ``[S]`` last tokens at ``[S]`` positions, K/V appended to
  the cache, attention via
  :func:`paddle_tpu.kernels.attention.decode_attention`, next token
  sampled ON DEVICE (greedy / top-k / temperature — only the sampled
  ``[S]`` int32 vector needs a host readback per step).

The layer math (post-LN residuals, sinusoidal positions, sqrt(D) embed
scale) deliberately mirrors ``models/transformer.py``'s decoder stack
so "the tiny transformer" means the same architecture family; the
incremental path and the full forward share the SAME per-layer
functions, which is what makes the paged-cache greedy parity an
algebraic identity (same math, different association) rather than a
coincidence.

Persistence: :func:`save_lm` / :func:`load_lm` write a model dir
(``decode_config.json`` + ``params.npz``) that ``tools/serve.py
--decode`` serves directly.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..kernels.attention import decode_attention, paged_attention_xla
from ..kernels.quant import (QMAX, SCALE_EPS, kv_dequantize, kv_head_amax,
                             kv_quantize)

_LN_EPS = 1e-5
# static top-k ceiling compiled into the sampling epilogue: per-slot k
# varies at runtime UNDER it without a recompile (a fixed shape is the
# whole decode-plane contract)
TOPK_MAX = 64


@dataclasses.dataclass(frozen=True)
class LMConfig:
    """Geometry of a decoder-only TransformerLM."""

    vocab: int
    d_model: int = 64
    n_head: int = 4
    d_ffn: int = 128
    n_layer: int = 2
    max_seq_len: int = 128
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "LMConfig":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)
                      if f.name in d})


def _pos_table(max_len: int, d_model: int) -> np.ndarray:
    """Sinusoidal positions (models/transformer.py `_pos_encoding_table`)."""
    pos = np.arange(max_len)[:, None].astype("float64")
    dim = np.arange(d_model // 2)[None, :].astype("float64")
    angle = pos / np.power(10000.0, 2 * dim / d_model)
    table = np.zeros((max_len, d_model))
    table[:, 0::2] = np.sin(angle)
    table[:, 1::2] = np.cos(angle)
    return table.astype("float32")


def _param_names(cfg: LMConfig) -> List[str]:
    names = ["emb"]
    for i in range(cfg.n_layer):
        names += [f"l{i}.wq", f"l{i}.wk", f"l{i}.wv", f"l{i}.wo",
                  f"l{i}.ln1.g", f"l{i}.ln1.b",
                  f"l{i}.fc1", f"l{i}.fc2",
                  f"l{i}.ln2.g", f"l{i}.ln2.b"]
    names.append("out_proj")
    return names


class TransformerLM:
    """One decoder-only LM: config + the three jit-ready functions.

    Params are a plain name→array dict (``init_params`` /
    ``save_lm``/``load_lm``); the engine device-puts them once and
    passes them as ``const`` through ``Executor.run_callable``."""

    def __init__(self, config: LMConfig):
        self.config = config
        self._pos = jnp.asarray(_pos_table(config.max_seq_len,
                                           config.d_model))

    # -- parameters --------------------------------------------------------
    def init_params(self, seed: int = 0) -> Dict[str, np.ndarray]:
        cfg = self.config
        rng = np.random.RandomState(seed)
        D, F, V = cfg.d_model, cfg.d_ffn, cfg.vocab

        def mat(m, n, scale=None):
            s = scale if scale is not None else (1.0 / np.sqrt(m))
            return (rng.randn(m, n) * s).astype("float32")

        p = {"emb": mat(V, D, scale=D ** -0.5), "out_proj": mat(D, V)}
        for i in range(cfg.n_layer):
            p[f"l{i}.wq"] = mat(D, D)
            p[f"l{i}.wk"] = mat(D, D)
            p[f"l{i}.wv"] = mat(D, D)
            p[f"l{i}.wo"] = mat(D, D)
            p[f"l{i}.ln1.g"] = np.ones((D,), "float32")
            p[f"l{i}.ln1.b"] = np.zeros((D,), "float32")
            p[f"l{i}.fc1"] = mat(D, F)
            p[f"l{i}.fc2"] = mat(F, D)
            p[f"l{i}.ln2.g"] = np.ones((D,), "float32")
            p[f"l{i}.ln2.b"] = np.zeros((D,), "float32")
        return p

    def param_list(self, params: Dict) -> List:
        """The ``const`` list in the fixed order the builders close
        over (missing names fail loudly here, not inside a trace)."""
        return [jnp.asarray(params[n]) for n in _param_names(self.config)]

    def _unpack(self, plist) -> Dict[str, jnp.ndarray]:
        return dict(zip(_param_names(self.config), plist))

    # -- shared layer math -------------------------------------------------
    @staticmethod
    def _ln(x, g, b):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + _LN_EPS) * g + b

    def _qkv(self, p, i, h):
        """h [..., D] → q, k, v [..., H, Dh]."""
        cfg = self.config
        hd = cfg.head_dim

        def split(x):
            return x.reshape(x.shape[:-1] + (cfg.n_head, hd))
        return (split(h @ p[f"l{i}.wq"]), split(h @ p[f"l{i}.wk"]),
                split(h @ p[f"l{i}.wv"]))

    def _post_attn(self, p, i, h, ctx):
        """Residual + FFN half of one layer; ctx is the attention
        output merged back to [..., D]."""
        cfg = self.config
        ctx = ctx.reshape(ctx.shape[:-2] + (cfg.d_model,))
        h = self._ln(h + ctx @ p[f"l{i}.wo"],
                     p[f"l{i}.ln1.g"], p[f"l{i}.ln1.b"])
        f = jax.nn.relu(h @ p[f"l{i}.fc1"]) @ p[f"l{i}.fc2"]
        return self._ln(h + f, p[f"l{i}.ln2.g"], p[f"l{i}.ln2.b"])

    # -- full forward (baseline / parity anchor) ---------------------------
    def full_logits(self, plist, tokens, lengths=None):
        """tokens [B, T] int32 → logits [B, T, V]; positions ≥ length
        masked out of attention when ``lengths`` [B] is given."""
        p = self._unpack(plist)
        cfg = self.config
        B, T = tokens.shape
        sc = float(1.0 / np.sqrt(cfg.head_dim))
        h = p["emb"][tokens] * (cfg.d_model ** 0.5) + self._pos[:T]
        qi = jnp.arange(T)
        causal = qi[:, None] >= qi[None, :]
        mask = causal[None]
        if lengths is not None:
            mask = jnp.logical_and(
                mask, qi[None, None, :] < lengths[:, None, None])
        for i in range(cfg.n_layer):
            q, k, v = self._qkv(p, i, h)          # [B, T, H, Dh]
            s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) * sc
            s = jnp.where(mask[:, None], s, -1e30)
            w = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", w,
                             v.astype(jnp.float32)).astype(h.dtype)
            h = self._post_attn(p, i, h, ctx)
        return h @ p["out_proj"]

    # -- cache writes ------------------------------------------------------
    @staticmethod
    def _scatter_kv(cache, layer, blocks, offsets, rows):
        """rows [N, H, Dh] into cache[layer] at (block, offset) pairs."""
        return cache.at[layer, blocks, offsets].set(rows)

    @staticmethod
    def _scatter_kv_q(cache, scales, layer, blocks, offsets, rows, slot,
                      valid, block_table):
        """Quantized bulk scatter (prefill / suffix prefill): rows
        [T, H, Dh] land as int8 codes with one fresh abs-max scale per
        (destination block, head).

        ``slot`` [T] is each row's index into ``block_table`` (clamped
        for pad lanes), ``valid`` [T] masks real prompt lanes.  The
        per-block scale is the max over the VALID rows bound for that
        table slot; untouched slots (the already-resident prefix of a
        suffix prefill, and pad slots) keep their existing scale —
        prefill only ever writes FRESH blocks (suffix starts are
        block-aligned: prefix-cache hits and preemption resume both
        hand back whole blocks), so no stored code needs rescaling
        here."""
        T = rows.shape[0]
        MB = block_table.shape[0]
        ha = kv_head_amax(rows) * valid[:, None].astype(jnp.float32)
        onehot = jnp.logical_and(
            slot[:, None] == jnp.arange(MB, dtype=jnp.int32)[None, :],
            valid[:, None])                              # [T, MB]
        blk_amax = jnp.max(
            jnp.where(onehot[:, :, None], ha[:, None, :], 0.0),
            axis=0)                                      # [MB, H]
        touched = jnp.any(onehot, axis=0)                # [MB]
        old = scales[layer][block_table]                 # [MB, H]
        new = jnp.where(touched[:, None],
                        jnp.maximum(blk_amax, SCALE_EPS), old)
        scales = scales.at[layer, block_table].set(new)
        q = kv_quantize(rows, new[slot])                 # [T, H, Dh] int8
        cache = cache.at[layer, blocks, offsets].set(q)
        return cache, scales

    @staticmethod
    def _append_kv_q(cache, scales, layer, blocks, offsets, rows):
        """Quantized single-row append (decode step): rows [S, H, Dh],
        one per slot, each into its OWN block (writable blocks are
        refcount-1 exclusive; shared blocks were COW-forked by the
        engine before this dispatch — inactive slots all target trash
        block 0, whose content and scale are never read unmasked).

        When a new row grows a (block, head)'s abs-max the block's
        stored codes requantize to the new scale in VMEM-register math
        (``round(q * old/new)`` — at most half a code of drift per
        growth, and the scale only ever grows over a block's
        residency, so drift is bounded by the growth count, not the
        token count)."""
        S = rows.shape[0]
        ha = kv_head_amax(rows)                          # [S, H]
        old = scales[layer, blocks]                      # [S, H]
        new = jnp.maximum(old, ha)                       # [S, H]
        blk = cache[layer, blocks]                       # [S, bs, H, Dh]
        ratio = jnp.where(new > 0.0,
                          old / jnp.maximum(new, SCALE_EPS), 1.0)
        blk = jnp.clip(jnp.round(blk.astype(jnp.float32)
                                 * ratio[:, None, :, None]),
                       -QMAX, QMAX).astype(jnp.int8)
        q = kv_quantize(rows, new)                       # [S, H, Dh]
        blk = blk.at[jnp.arange(S), offsets].set(q)
        cache = cache.at[layer, blocks].set(blk)
        scales = scales.at[layer, blocks].set(new)
        return cache, scales

    # -- prefill -----------------------------------------------------------
    def prefill(self, plist, kc, vc, tokens, length, block_table,
                seed, temperature, top_k, ks=None, vs=None):
        """tokens [1, Tb] (bucket-padded), length [] int32, block_table
        [MB] int32 → (kc', vc', next_token [] int32, logits [V]) — or,
        with the int8 scale pools ``ks``/``vs`` threaded (quantized
        cache), (kc', vc', ks', vs', next_token, logits).

        One full causal forward over the padded prompt; every real
        position's K/V lands in the request's blocks, pad positions
        land in trash block 0 (their attention contribution is masked
        by ``length`` either way).  Prefill attention always runs on
        the fresh f32 K/V computed THIS dispatch — quantization only
        affects what the cache stores, so the first token is exact
        either way.  The FIRST generated token samples here, so a
        joining request streams its first token without waiting for a
        decode step."""
        cfg = self.config
        p = self._unpack(plist)
        Tb = tokens.shape[1]
        bs = kc.shape[2]
        MB = block_table.shape[0]
        sc = float(1.0 / np.sqrt(cfg.head_dim))
        pos_idx = jnp.arange(Tb, dtype=jnp.int32)
        valid = pos_idx < length
        slot = jnp.minimum(pos_idx // bs, MB - 1)
        blocks = jnp.where(valid, block_table[slot], 0)
        offsets = pos_idx % bs
        qi = jnp.arange(Tb)
        mask = jnp.logical_and(qi[:, None] >= qi[None, :],
                               qi[None, :] < length)[None]
        h = p["emb"][tokens] * (cfg.d_model ** 0.5) + self._pos[:Tb]
        for i in range(cfg.n_layer):
            q, k, v = self._qkv(p, i, h)          # [1, Tb, H, Dh]
            if ks is None:
                kc = self._scatter_kv(kc, i, blocks, offsets, k[0])
                vc = self._scatter_kv(vc, i, blocks, offsets, v[0])
            else:
                kc, ks = self._scatter_kv_q(kc, ks, i, blocks, offsets,
                                            k[0], slot, valid,
                                            block_table)
                vc, vs = self._scatter_kv_q(vc, vs, i, blocks, offsets,
                                            v[0], slot, valid,
                                            block_table)
            s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) * sc
            s = jnp.where(mask[:, None], s, -1e30)
            w = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", w,
                             v.astype(jnp.float32)).astype(h.dtype)
            h = self._post_attn(p, i, h, ctx)
        last = h[0, jnp.maximum(length - 1, 0)]
        logits = last @ p["out_proj"]
        tok = _sample(logits[None], seed[None],
                      jnp.zeros((1,), jnp.int32), temperature[None],
                      top_k[None])[0]
        if ks is None:
            return kc, vc, tok, logits
        return kc, vc, ks, vs, tok, logits

    # -- suffix prefill (prefix-cache hits / preemption resume) ------------
    def prefill_suffix(self, plist, kc, vc, tokens, start, length,
                       block_table, seed, temperature, top_k,
                       ks=None, vs=None):
        """tokens [1, Sb] (bucket-padded suffix), start [] int32 (how
        many leading positions are already resident in the cache —
        block-aligned prefix-cache hits), length [] int32 (total real
        sequence length; the suffix is positions start..length-1),
        block_table [MB] int32 → (kc', vc', next_token [] int32,
        logits [V]); with the int8 scale pools ``ks``/``vs`` threaded,
        (kc', vc', ks', vs', next_token, logits) and the gathered
        context (cached prefix INCLUDED) is dequantized per block
        before the dense masked attention.

        The prompt's cached prefix is NOT recomputed: suffix K/V is
        scattered into the request's blocks first, then — because
        every suffix lane shares the SAME block table — the whole
        context is gathered ONCE per layer ([MB, bs] → [MB*bs] rows)
        and attention is a dense masked matmul of the Sb suffix
        queries against it (a lane at absolute position ``pos`` sees
        context rows 0..pos: the cached prefix plus the suffix rows
        written this dispatch, in the same layer).  That keeps the
        gather O(context) instead of the per-lane paged path's
        O(lanes x context).  Pad lanes scatter into trash block 0 and
        attend (masked) to position 0 only; their output is
        discarded.  Unwritten table slots are trash block 0 too — as
        flattened rows their positions exceed every real ``pos``, so
        the mask drops them.  Samples the first generated token like
        :meth:`prefill` (token index 0)."""
        cfg = self.config
        p = self._unpack(plist)
        Sb = tokens.shape[1]
        bs = kc.shape[2]
        MB = block_table.shape[0]
        sc = float(1.0 / np.sqrt(cfg.head_dim))
        lane = jnp.arange(Sb, dtype=jnp.int32)
        n = length - start                      # real suffix length
        valid = lane < n
        pos = start + lane
        safe_pos = jnp.minimum(jnp.where(valid, pos, 0),
                               cfg.max_seq_len - 1)
        slot = jnp.minimum(safe_pos // bs, MB - 1)
        blocks = jnp.where(valid, block_table[slot], 0)
        offsets = safe_pos % bs
        tpos = jnp.arange(MB * bs, dtype=jnp.int32)
        mask = tpos[None, :] <= safe_pos[:, None]   # [Sb, MB*bs]
        h = (p["emb"][tokens[0]] * (cfg.d_model ** 0.5)
             + self._pos[safe_pos])
        for i in range(cfg.n_layer):
            q, k, v = self._qkv(p, i, h)          # [Sb, H, Dh]
            if ks is None:
                kc = self._scatter_kv(kc, i, blocks, offsets, k)
                vc = self._scatter_kv(vc, i, blocks, offsets, v)
                ck = kc[i][block_table].reshape(MB * bs, cfg.n_head,
                                                cfg.head_dim)
                cv = vc[i][block_table].reshape(MB * bs, cfg.n_head,
                                                cfg.head_dim)
            else:
                kc, ks = self._scatter_kv_q(kc, ks, i, blocks, offsets,
                                            k, slot, valid, block_table)
                vc, vs = self._scatter_kv_q(vc, vs, i, blocks, offsets,
                                            v, slot, valid, block_table)
                ck = kv_dequantize(
                    kc[i][block_table],
                    ks[i][block_table][:, None, :]).reshape(
                        MB * bs, cfg.n_head, cfg.head_dim)
                cv = kv_dequantize(
                    vc[i][block_table],
                    vs[i][block_table][:, None, :]).reshape(
                        MB * bs, cfg.n_head, cfg.head_dim)
            s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                           ck.astype(jnp.float32)) * sc
            s = jnp.where(mask[None], s, -1e30)
            w = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("hqk,khd->qhd", w, cv.astype(jnp.float32))
            h = self._post_attn(p, i, h, ctx.astype(h.dtype))
        last = h[jnp.maximum(n - 1, 0)]
        logits = last @ p["out_proj"]
        tok = _sample(logits[None], seed[None],
                      jnp.zeros((1,), jnp.int32), temperature[None],
                      top_k[None])[0]
        if ks is None:
            return kc, vc, tok, logits
        return kc, vc, ks, vs, tok, logits

    # -- decode step (the continuous-batching hot dispatch) ----------------
    def decode_step(self, plist, kc, vc, tokens, positions, block_tables,
                    seeds, steps, temperature, top_k, attn_impl=None,
                    ks=None, vs=None):
        """tokens [S] int32 (each slot's last token), positions [S]
        int32 (where that token sits), block_tables [S, MB] int32,
        seeds [S] uint32 + steps [S] int32 (per-request sampling
        identity — see :func:`_sample`) → (kc', vc', next_tokens [S],
        logits [S, V]); with the int8 scale pools ``ks``/``vs``
        threaded, (kc', vc', ks', vs', next_tokens, logits) and the
        paged attention dequantizes per-block-per-head in the kernel.

        Writes each slot's K/V at (position // bs, position % bs) via
        its block table, then attends over positions 0..position
        through the paged kernel.  Inactive slots feed position 0 with
        an all-zero (trash) block table: they compute masked garbage
        into block 0 and their sampled token is ignored by the engine —
        fixed shapes, no branches."""
        cfg = self.config
        p = self._unpack(plist)
        bs = kc.shape[2]
        cl = positions + 1
        blocks = block_tables[jnp.arange(tokens.shape[0]),
                              positions // bs]
        offsets = positions % bs
        h = p["emb"][tokens] * (cfg.d_model ** 0.5) + self._pos[positions]
        for i in range(cfg.n_layer):
            q, k, v = self._qkv(p, i, h)          # [S, H, Dh]
            if ks is None:
                kc = self._scatter_kv(kc, i, blocks, offsets, k)
                vc = self._scatter_kv(vc, i, blocks, offsets, v)
                ctx = decode_attention(q, kc[i], vc[i], block_tables,
                                       cl, impl=attn_impl)
            else:
                kc, ks = self._append_kv_q(kc, ks, i, blocks, offsets, k)
                vc, vs = self._append_kv_q(vc, vs, i, blocks, offsets, v)
                ctx = decode_attention(q, kc[i], vc[i], block_tables,
                                       cl, impl=attn_impl,
                                       k_scale=ks[i], v_scale=vs[i])
            h = self._post_attn(p, i, h, ctx.astype(h.dtype))
        logits = h @ p["out_proj"]
        toks = _sample(logits, seeds, steps, temperature, top_k)
        if ks is None:
            return kc, vc, toks, logits
        return kc, vc, ks, vs, toks, logits


def _hash_uniform(seeds, steps, kk):
    """Counter-hash uniforms in (0, 1): one murmur-style mix per
    (request seed, token index, candidate lane) — the attention
    dropout hash's recipe, keyed PER REQUEST.  A seeded stream is
    replayable bit-for-bit regardless of which slot it lands on or
    what else shares the decode batch (an engine-global PRNG key
    could not promise that)."""
    S = seeds.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.uint32, (S, kk), 1)
    x = (seeds.astype(jnp.uint32)[:, None] * jnp.uint32(0x9E3779B1)
         ^ steps.astype(jnp.uint32)[:, None] * jnp.uint32(0x85EBCA77)
         ^ lane * jnp.uint32(0xC2B2AE3D))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = (jax.lax.bitcast_convert_type(x >> 8, jnp.int32)
         .astype(jnp.float32) * jnp.float32(1.0 / (1 << 24)))
    return jnp.clip(u, 1e-7, 1.0 - 1e-7)


def _sample(logits, seeds, steps, temperature, top_k):
    """On-device sampling epilogue: logits [S, V], seeds [S] uint32
    (per REQUEST), steps [S] int32 (each request's token index),
    temperature [S] f32 (<= 0 ⇒ greedy), top_k [S] int32 (0 ⇒ full
    vocab) → tokens [S] int32.  Per-slot knobs vary at runtime under
    the static ``TOPK_MAX`` ceiling; sampling is Gumbel-max over the
    top slice with :func:`_hash_uniform` bits, so a request's sampled
    stream depends only on (its seed, its token indices) — replayable
    across slot placements and batch compositions."""
    S, V = logits.shape
    kk = min(TOPK_MAX, V)
    vals, idx = jax.lax.top_k(logits.astype(jnp.float32), kk)  # [S, kk]
    lane = jnp.arange(kk, dtype=jnp.int32)[None, :]
    want = jnp.where(top_k > 0, jnp.minimum(top_k, kk), kk)[:, None]
    vals = jnp.where(lane < want, vals, -jnp.inf)
    g = -jnp.log(-jnp.log(_hash_uniform(seeds, steps, kk)))
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    choice = jnp.argmax(vals / temp + g, axis=-1)
    greedy = idx[:, 0]                     # top_k output is sorted
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


# ---------------------------------------------------------------------------
# model-dir persistence (tools/serve.py --decode serves these)
# ---------------------------------------------------------------------------

_CONFIG_FILE = "decode_config.json"
_PARAMS_FILE = "params.npz"


def save_lm(dirname: str, config: LMConfig, params: Dict) -> None:
    """Write a decode-servable model dir (config JSON + params npz);
    atomic per file (tmp + replace) like io.py's save discipline."""
    os.makedirs(dirname, exist_ok=True)
    cpath = os.path.join(dirname, _CONFIG_FILE)
    with open(cpath + ".tmp", "w") as f:
        json.dump(config.to_dict(), f, indent=2)
    os.replace(cpath + ".tmp", cpath)
    ppath = os.path.join(dirname, _PARAMS_FILE)
    np.savez(ppath + ".tmp.npz",
             **{k: np.asarray(v) for k, v in params.items()})
    os.replace(ppath + ".tmp.npz", ppath)


def load_lm(dirname: str):
    """(TransformerLM, params dict) from a :func:`save_lm` dir."""
    with open(os.path.join(dirname, _CONFIG_FILE)) as f:
        cfg = LMConfig.from_dict(json.load(f))
    with np.load(os.path.join(dirname, _PARAMS_FILE)) as z:
        params = {k: z[k].copy() for k in z.files}
    missing = [n for n in _param_names(cfg) if n not in params]
    if missing:
        raise ValueError(f"model dir {dirname!r} is missing params: "
                         f"{missing[:4]}{'...' if len(missing) > 4 else ''}")
    return TransformerLM(cfg), params


__all__ = ["LMConfig", "TransformerLM", "save_lm", "load_lm",
           "paged_attention_xla", "TOPK_MAX"]
