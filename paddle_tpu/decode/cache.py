"""Paged KV cache: fixed-size device blocks + a host-side allocator.

The device half (:class:`PagedKVCache`) is two preallocated arrays
``[num_layers, num_blocks, block_tokens, n_head, head_dim]`` (keys and
values) that ride :meth:`Executor.run_callable` as donated state —
every prefill/decode dispatch consumes the old buffers and returns the
updated ones, so the cache is resident in device memory for the
engine's whole life and no dispatch ever copies it to host.

The host half (:class:`BlockAllocator`) is a free list over block ids.
Block 0 is RESERVED as the trash block: padded prompt positions and
inactive decode slots write their (garbage) K/V there, which keeps
every dispatch a fixed-shape scatter with no branching — the price of
one wasted block buys shape-stable admission/eviction (the whole point
of paging: a request joining or leaving moves block-table entries,
never compiled shapes).

Sizing: a request admitted with prompt length P and output budget M
reserves ``ceil((P + M) / block_tokens)`` blocks up front — admission
is the only point that can fail for lack of memory; a running stream
can never hit cache OOM mid-generation.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ..core import flags as _flags


def blocks_for(tokens: int, block_tokens: int) -> int:
    """Blocks needed to hold ``tokens`` tokens."""
    return max(1, -(-int(tokens) // int(block_tokens)))


class BlockAllocator:
    """Free-list allocator over cache block ids 1..num_blocks-1
    (block 0 is the reserved trash block — module doc)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 usable + trash), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(1, self.num_blocks))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids, or None (caller queues) when short — never a
        partial grant."""
        if n > len(self._free):
            return None
        out, self._free = self._free[:n], self._free[n:]
        return out

    def release(self, blocks: List[int]) -> None:
        for b in blocks:
            if not 1 <= b < self.num_blocks:
                raise ValueError(f"bad block id {b}")
        self._free.extend(blocks)


class PagedKVCache:
    """The device arrays (module doc).  ``state()`` hands the [k, v]
    list to ``Executor.run_callable``; ``update()`` swaps in the
    returned (donated-in-place) handles."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_tokens: Optional[int] = None,
                 dtype="float32"):
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(
            _flags.get_flags("decode_block_tokens")
            if block_tokens is None else block_tokens)
        if self.block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got "
                             f"{self.block_tokens}")
        shape = (self.num_layers, self.num_blocks, self.block_tokens,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.allocator = BlockAllocator(self.num_blocks)

    @property
    def nbytes(self) -> int:
        return int(self.k.size) * self.k.dtype.itemsize * 2

    def state(self) -> list:
        return [self.k, self.v]

    def update(self, new_state: list) -> None:
        self.k, self.v = new_state

    def max_context(self, max_blocks_per_seq: int) -> int:
        return max_blocks_per_seq * self.block_tokens

    def snapshot(self) -> dict:
        return {
            "num_blocks": self.num_blocks,
            "block_tokens": self.block_tokens,
            "free_blocks": self.allocator.free_blocks,
            "bytes": self.nbytes,
        }
