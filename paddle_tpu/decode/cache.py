"""Paged KV cache: fixed-size device blocks + a host-side allocator.

The device half (:class:`PagedKVCache`) is two preallocated arrays
``[num_layers, num_blocks, block_tokens, n_head, head_dim]`` (keys and
values) that ride :meth:`Executor.run_callable` as donated state —
every prefill/decode dispatch consumes the old buffers and returns the
updated ones, so the cache is resident in device memory for the
engine's whole life and no dispatch ever copies it to host.

The host half (:class:`BlockAllocator`) is a refcounted free list over
block ids.  Block 0 is RESERVED as the trash block: padded prompt
positions and inactive decode slots write their (garbage) K/V there,
which keeps every dispatch a fixed-shape scatter with no branching —
the price of one wasted block buys shape-stable admission/eviction
(the whole point of paging: a request joining or leaving moves
block-table entries, never compiled shapes).

Every allocated block carries a refcount.  With the legacy reservation
policy each block has exactly one owner, so ``alloc``/``release`` behave
(and order the free list) exactly as the original single-owner free
list did.  Prefix sharing and beam forking raise refcounts above one:
a block referenced by several streams is immutable to all of them —
writers must fork it (copy-on-write) first.  A zero-refcount block
either returns to the free list or, when a :class:`PrefixCache` claims
it, is *parked* in the cache's LRU so a later prompt with the same
content can revive it without re-prefilling.

Sizing: under the legacy policy a request admitted with prompt length
P and output budget M reserves ``ceil((P + M) / block_tokens)`` blocks
up front — admission is the only point that can fail for lack of
memory.  Under ``FLAGS_decode_overcommit`` admission reserves only
``ceil((P + 1) / block_tokens)`` and grows one block per step; a
failed growth triggers preemption (engine doc).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..core import flags as _flags


def blocks_for(tokens: int, block_tokens: int) -> int:
    """Blocks needed to hold ``tokens`` tokens."""
    return max(1, -(-int(tokens) // int(block_tokens)))


_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def _fnv1a64(data: bytes, h: int = _FNV_OFFSET) -> int:
    for byte in data:
        h = ((h ^ byte) * _FNV_PRIME) & _MASK64
    return h


def _fold_token(h: int, token: int) -> int:
    return _fnv1a64(int(token).to_bytes(4, "little", signed=True), h)


class BlockAllocator:
    """Refcounted free-list allocator over cache block ids
    1..num_blocks-1 (block 0 is the reserved trash block — module doc).

    ``alloc`` hands out blocks at refcount 1; ``incref`` adds sharers;
    ``decref``/``release`` drop references.  A block whose refcount
    reaches zero goes back on the free list *in drop order* — with
    single-owner usage this reproduces the original free-list ordering
    byte for byte.  If a :class:`PrefixCache` is attached, zero-ref
    blocks it has registered are parked in its LRU instead of freed.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 usable + trash), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(1, self.num_blocks))
        self._ref: Dict[int, int] = {}
        self._prefix_cache: Optional["PrefixCache"] = None

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def referenced_blocks(self) -> int:
        """Blocks with refcount >= 1 (held by at least one stream)."""
        return len(self._ref)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def leaked(self, parked: int = 0) -> int:
        """Pool invariant: usable blocks not free, not referenced and
        not parked in a prefix cache.  Must be zero at all times."""
        return (self.num_blocks - 1 - len(self._free)
                - len(self._ref) - int(parked))

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids at refcount 1, or None (caller queues /
        reclaims / preempts) when short — never a partial grant."""
        if n > len(self._free):
            return None
        out, self._free = self._free[:n], self._free[n:]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, block: int) -> None:
        if block not in self._ref:
            raise ValueError(f"incref of unreferenced block {block}")
        self._ref[block] += 1

    def decref(self, block: int) -> None:
        """Drop one reference; at zero the block is parked in the
        attached prefix cache (if it registered the block) or freed."""
        n = self._ref.get(block, 0)
        if n <= 0:
            raise ValueError(f"decref of unreferenced block {block}")
        if n > 1:
            self._ref[block] = n - 1
            return
        del self._ref[block]
        if self._prefix_cache is not None and self._prefix_cache._park(block):
            return
        self._free.append(block)

    def release(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if not 1 <= b < self.num_blocks:
                raise ValueError(f"bad block id {b}")
        for b in blocks:
            self.decref(b)


class PrefixCache:
    """Content-addressed registry of full, immutable prompt blocks.

    A block is cacheable once prefill has written all ``block_tokens``
    of its positions from the prompt — from then on its K/V content is
    a pure function of (model identity, token ids up to the block
    boundary), captured by a rolling FNV-1a chain hash.  Admission
    walks the new prompt's block-aligned prefix against the registry
    and adopts hits (incref / revive), so a shared system prompt
    prefills once.

    Entries whose block is still referenced by live streams cost
    nothing; when the last reference drops the allocator *parks* the
    block here (LRU order) instead of freeing it.  ``reclaim`` evicts
    parked blocks back to the free list under pool pressure — a cached
    block is only ever a loan from the free pool.

    Hash hits are verified against the stored token ids before reuse:
    a 64-bit collision can alias two prefixes, and serving another
    stream's K/V would silently corrupt output, so a colliding entry
    is treated as a miss (and counted).
    """

    def __init__(self, allocator: BlockAllocator, block_tokens: int,
                 model_key: str = ""):
        self.allocator = allocator
        self.block_tokens = int(block_tokens)
        self._seed = _fnv1a64(str(model_key).encode("utf-8"))
        # key -> (block id, token ids covered by this block)
        self._entries: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        self._block_key: Dict[int, int] = {}
        # zero-refcount cached blocks, oldest-parked first
        self._lru: "OrderedDict[int, int]" = OrderedDict()
        self.collisions = 0
        allocator._prefix_cache = self

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def parked_blocks(self) -> int:
        return len(self._lru)

    def chain_keys(self, tokens: Sequence[int]) -> List[int]:
        """Rolling hash keyed at each full block boundary of
        ``tokens``: key[i] covers tokens[: (i + 1) * block_tokens]."""
        bs = self.block_tokens
        keys: List[int] = []
        h = self._seed
        for i in range(len(tokens) // bs):
            for t in tokens[i * bs:(i + 1) * bs]:
                h = _fold_token(h, int(t))
            keys.append(h)
        return keys

    def match(self, tokens: Sequence[int], max_blocks: int
              ) -> List[Tuple[int, int]]:
        """Longest cached block-aligned prefix of ``tokens``, capped at
        ``max_blocks`` blocks.  Returns [(key, block)] per hit; stops
        at the first miss (a later block is only valid on top of all
        earlier ones).  Token ids are verified on every hash hit."""
        hits: List[Tuple[int, int]] = []
        bs = self.block_tokens
        toks = [int(t) for t in tokens]
        for i, key in enumerate(self.chain_keys(toks)):
            if len(hits) >= max_blocks:
                break
            ent = self._entries.get(key)
            if ent is None:
                break
            block, covered = ent
            if tuple(toks[:(i + 1) * bs]) != covered:
                self.collisions += 1
                break
            hits.append((key, block))
        return hits

    def acquire(self, key: int) -> int:
        """Take a reference on a matched entry's block (revives it from
        the LRU if parked)."""
        block, _ = self._entries[key]
        if block in self._lru:
            del self._lru[block]
            self.allocator._ref[block] = 1
        else:
            self.allocator.incref(block)
        return block

    def insert(self, key: int, tokens: Sequence[int], block: int) -> bool:
        """Register a freshly prefilled full block under ``key``.  The
        block stays owned by its stream (no extra ref); it parks here
        when the last stream drops it.  First writer wins — an existing
        live entry is kept."""
        if key in self._entries:
            return False
        if block in self._block_key:
            return False
        self._entries[key] = (block, tuple(int(t) for t in tokens))
        self._block_key[block] = key
        return True

    def holds(self, block: int) -> bool:
        """True if writing into ``block`` must fork it (its content is
        advertised to future admissions)."""
        return block in self._block_key

    def _park(self, block: int) -> bool:
        """Allocator callback: keep this zero-ref block cached (LRU)
        instead of freeing it.  False if the block is not registered."""
        if block not in self._block_key:
            return False
        self._lru[block] = block
        self._lru.move_to_end(block)
        return True

    def _drop_entry(self, block: int) -> None:
        key = self._block_key.pop(block)
        del self._entries[key]

    def reclaim(self, n_blocks: int) -> int:
        """Evict up to ``n_blocks`` parked blocks (oldest first) back
        to the free list.  Returns how many were freed."""
        freed = 0
        while freed < n_blocks and self._lru:
            block, _ = self._lru.popitem(last=False)
            self._drop_entry(block)
            self.allocator._free.append(block)
            freed += 1
        return freed

    def snapshot(self) -> dict:
        return {
            "entries": len(self._entries),
            "parked_blocks": len(self._lru),
            "collisions": self.collisions,
        }


class PagedKVCache:
    """The device arrays (module doc).  ``state()`` hands the [k, v]
    list to ``Executor.run_callable``; ``update()`` swaps in the
    returned (donated-in-place) handles.

    ``dtype="int8"`` (``FLAGS_decode_kv_dtype``) stores blocks
    quantized: k/v pools become int8 and two parallel f32 scale pools
    ``[num_layers, num_blocks, n_head]`` carry one abs-max scale per
    (block, head) — the qdq convention of ``kernels/quant.py``
    (``x ~= q * s / 127``).  ``state()`` then threads
    ``[k, v, k_scale, v_scale]`` so every dispatch moves the scale
    rows with the blocks (COW block copies copy the scale row through
    the same dim-1 block axis).  Everything host-side — the allocator,
    prefix cache, block tables — moves block IDS only and is unchanged.
    The f32 default keeps ``state()``, ``nbytes`` and the block layout
    byte-identical to the unquantized build."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_tokens: Optional[int] = None,
                 dtype="float32"):
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(
            _flags.get_flags("decode_block_tokens")
            if block_tokens is None else block_tokens)
        if self.block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got "
                             f"{self.block_tokens}")
        self.dtype = str(dtype)
        self.quantized = self.dtype == "int8"
        shape = (self.num_layers, self.num_blocks, self.block_tokens,
                 self.num_heads, self.head_dim)
        if self.quantized:
            self.k = jnp.zeros(shape, jnp.int8)
            self.v = jnp.zeros(shape, jnp.int8)
            sshape = (self.num_layers, self.num_blocks, self.num_heads)
            self.k_scale = jnp.zeros(sshape, jnp.float32)
            self.v_scale = jnp.zeros(sshape, jnp.float32)
        else:
            self.k = jnp.zeros(shape, dtype)
            self.v = jnp.zeros(shape, dtype)
            self.k_scale = None
            self.v_scale = None
        self.allocator = BlockAllocator(self.num_blocks)

    @property
    def nbytes(self) -> int:
        """ACTUAL pool bytes: dtype-aware block storage plus the scale
        pools when quantized — what the MemoryLedger pool and the
        per-tenant resident_kv_bytes attribute (a quantized cache must
        not report fp32-sized blocks)."""
        n = int(self.k.size) * self.k.dtype.itemsize * 2
        if self.k_scale is not None:
            n += int(self.k_scale.size) * self.k_scale.dtype.itemsize * 2
        return n

    def state(self) -> list:
        if self.quantized:
            return [self.k, self.v, self.k_scale, self.v_scale]
        return [self.k, self.v]

    def update(self, new_state: list) -> None:
        if self.quantized:
            self.k, self.v, self.k_scale, self.v_scale = new_state
        else:
            self.k, self.v = new_state

    def max_context(self, max_blocks_per_seq: int) -> int:
        return max_blocks_per_seq * self.block_tokens

    def snapshot(self) -> dict:
        snap = {
            "num_blocks": self.num_blocks,
            "block_tokens": self.block_tokens,
            "free_blocks": self.allocator.free_blocks,
            "bytes": self.nbytes,
        }
        if self.quantized:
            # new keys only under the flag: the f32 snapshot surface
            # stays byte-identical
            snap["dtype"] = self.dtype
            snap["scale_bytes"] = int(
                self.k_scale.size) * self.k_scale.dtype.itemsize * 2
        return snap
