"""Beam search over the paged KV cache via copy-on-write block forking.

:class:`~paddle_tpu.contrib.decoder.IncrementalBeamDecoder` carries the
beam-search selection state (``pre_ids``/``pre_scores``/per-step
parents) across dispatches but leaves the MODEL state to the caller:
after every step the carried state must be gathered by the returned
parent pointers.  For a transformer that state is the whole KV cache —
and the whole-sequence decoder's answer (``L.gather`` on dense state
tensors) would copy ``O(beam x context)`` K/V per step.

:class:`PagedBeamDecoder` makes the gather a BLOCK-TABLE operation on
the refcounted allocator instead:

- the prompt prefills ONCE; every beam lane starts as a reference to
  the same prompt blocks (refcount = beam width);
- the parent gather after each selection re-points lane tables at the
  parent's blocks (incref the adopted, decref the abandoned) — zero
  device copies;
- a lane only pays a device block-copy when it WRITES into a block
  another lane still references (copy-on-write): exactly the frontier
  block where hypotheses diverge, at most one block per lane per step
  and usually amortized to much less.

``share_prefix=False`` keeps every lane's blocks private with eager
device copies at fork points — the program-level-copy baseline.  Both
modes read and write bit-identical K/V (a device block copy is exact),
so selections, final ids and scores are bit-equal; the COW mode just
skips the copies that were never observed — the equivalence the tests
pin.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .cache import PagedKVCache, blocks_for
from .model import TransformerLM
from ..core import flags as _flags
from ..core.executor import Executor


class PagedBeamDecoder:
    """Beam-search session: one model + private paged cache + an
    :class:`IncrementalBeamDecoder` for selection/backtrack.

    ``decode(prompt, max_steps)`` returns the contrib decoder's
    ``BeamDecodeResult`` (ids [beam, T], scores, cand_len, src_len).
    """

    def __init__(self, model: TransformerLM, params: dict,
                 beam_size: int, end_id: int,
                 topk_size: Optional[int] = None,
                 name: str = "beam",
                 block_tokens: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 executor: Optional[Executor] = None,
                 share_prefix: bool = True,
                 attn_impl: Optional[str] = None):
        from ..contrib.decoder import IncrementalBeamDecoder

        self.model = model
        self.name = name
        cfg = model.config
        self.beam_size = int(beam_size)
        self.end_id = int(end_id)
        self.topk_size = int(topk_size if topk_size is not None
                             else max(self.beam_size, 2))
        self.share_prefix = bool(share_prefix)
        self._attn_impl = attn_impl
        bs = int(_flags.get_flags("decode_block_tokens")
                 if block_tokens is None else block_tokens)
        self.max_blocks_per_seq = blocks_for(cfg.max_seq_len, bs)
        if num_blocks is None:
            # unshared lanes transiently hold old + adopted copies
            # during the parent gather — double the worst case
            factor = 1 if self.share_prefix else 2
            num_blocks = 1 + factor * self.beam_size * self.max_blocks_per_seq
        self.cache = PagedKVCache(cfg.n_layer, cfg.n_head, cfg.head_dim,
                                  num_blocks, bs, dtype="float32")
        self._exe = executor if executor is not None \
            else Executor(training=False)
        self._plist = model.param_list(params)
        self._ibd = IncrementalBeamDecoder(self.beam_size, self.end_id,
                                           self.topk_size)
        self._lanes: List[List[int]] = []
        # plain session counters (no registry series: a beam session is
        # a library object, not a serving plane)
        self.cow_forks = 0
        self.block_copies = 0

    # -- pool helpers ------------------------------------------------------
    def _alloc1(self) -> int:
        got = self.cache.allocator.alloc(1)
        if got is None:
            raise RuntimeError(
                f"beam session {self.name!r}: block pool exhausted "
                f"({self.cache.num_blocks} blocks, beam {self.beam_size})")
        return got[0]

    def _copy_block(self, src: int, dst: int) -> None:
        def build():
            def fn(feed, state, const):
                s, d = feed
                k, v = state
                k = k.at[:, d].set(k[:, s])
                v = v.at[:, d].set(v[:, s])
                return [], [k, v]
            return fn

        _, new_state = self._exe.run_callable(
            f"decode/{self.name}/blkcopy", build,
            [np.int32(src), np.int32(dst)],
            state=self.cache.state(), const=[])
        self.cache.update(new_state)
        self.block_copies += 1

    def _private_copy(self, src_blocks: List[int]) -> List[int]:
        out = []
        for b in src_blocks:
            nb = self._alloc1()
            self._copy_block(b, nb)
            out.append(nb)
        return out

    def _free_lanes(self) -> None:
        for lane in self._lanes:
            self.cache.allocator.release(lane)
        self._lanes = []

    def leaked(self) -> int:
        return self.cache.allocator.leaked()

    # -- the session -------------------------------------------------------
    def _table(self) -> np.ndarray:
        t = np.zeros((self.beam_size, self.max_blocks_per_seq), np.int32)
        for l, lane in enumerate(self._lanes):
            t[l, :len(lane)] = lane
        return t

    def _ensure_writable(self, pos: int) -> None:
        """Growth + copy-on-write for every lane's write-target block
        at sequence position ``pos`` (the step about to dispatch
        scatters each lane's K/V there)."""
        bs = self.cache.block_tokens
        alloc = self.cache.allocator
        j = pos // bs
        for lane in self._lanes:
            while j >= len(lane):
                lane.append(self._alloc1())
            b = lane[j]
            if alloc.refcount(b) > 1:
                nb = self._alloc1()
                self._copy_block(b, nb)
                lane[j] = nb
                alloc.decref(b)
                self.cow_forks += 1

    def _adopt_parents(self, parent: np.ndarray) -> None:
        """The beam gather as a block-table operation: each lane's
        table becomes its parent's.  incref every adopted block FIRST,
        then drop the old references — correct under any parent
        permutation (self-adoption, swaps, one parent taken by all)."""
        old = self._lanes
        alloc = self.cache.allocator
        if self.share_prefix:
            new = []
            for l in range(self.beam_size):
                src = old[int(parent[l])]
                for b in src:
                    alloc.incref(b)
                new.append(list(src))
            for lane in old:
                for b in lane:
                    alloc.decref(b)
        else:
            # program-level-copy baseline: every lane materializes a
            # private copy of its parent's blocks, every step
            new = [self._private_copy(old[int(parent[l])])
                   for l in range(self.beam_size)]
            for lane in old:
                alloc.release(lane)
        self._lanes = new

    def _candidates(self, logits: np.ndarray):
        """[bw, V] logits -> ([bw, topk] ids int64, [bw, topk] softmax
        probs) — the fc(softmax) + topk half of the whole-sequence
        decoder's loop body, on host (deterministic stable argsort)."""
        x = logits.astype(np.float32)
        x = x - x.max(axis=-1, keepdims=True)
        p = np.exp(x)
        p /= p.sum(axis=-1, keepdims=True)
        idx = np.argsort(-p, axis=-1, kind="stable")[:, :self.topk_size]
        return idx.astype(np.int64), np.take_along_axis(p, idx, axis=-1)

    def decode(self, prompt, max_steps: int):
        """Beam-decode ``max_steps`` tokens after ``prompt``.  Returns
        the backtracked ``BeamDecodeResult``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        P = int(prompt.size)
        bw = self.beam_size
        cfg = self.model.config
        if P < 1:
            raise ValueError("empty prompt")
        if P + max_steps > min(cfg.max_seq_len,
                               self.cache.max_context(
                                   self.max_blocks_per_seq)):
            raise ValueError(f"prompt {P} + steps {max_steps} exceeds "
                             f"the session context bound")
        self._free_lanes()
        model, impl = self.model, self._attn_impl

        # prefill ONCE; lane 0 owns the prompt blocks
        base = self.cache.allocator.alloc(blocks_for(P, self.cache.block_tokens))
        if base is None:
            raise RuntimeError("beam session: pool too small for prompt")
        self._lanes = [base]
        table = np.zeros((self.max_blocks_per_seq,), np.int32)
        table[:len(base)] = base
        tokens = np.zeros((1, P), np.int32)
        tokens[0] = prompt

        def build_prefill():
            def fn(feed, state, const):
                kc, vc, tok, logits = model.prefill(
                    const, state[0], state[1], *feed)
                return [logits], [kc, vc]
            return fn

        (logits0,), new_state = self._exe.run_callable(
            f"decode/{self.name}/beam_prefill/{P}", build_prefill,
            [tokens, np.int32(P), table, np.uint32(0),
             np.float32(0.0), np.int32(0)],
            state=self.cache.state(), const=self._plist)
        self.cache.update(new_state)
        logits0 = np.asarray(logits0)

        # fan lane 0 out to the full beam: COW references, or private
        # copies in the unshared baseline
        if self.share_prefix:
            for _ in range(1, bw):
                for b in base:
                    self.cache.allocator.incref(b)
                self._lanes.append(list(base))
        else:
            for _ in range(1, bw):
                self._lanes.append(self._private_copy(base))

        self._ibd.start()
        cand_ids, cand_probs = self._candidates(
            np.broadcast_to(logits0, (bw, logits0.shape[-1])))
        sel_ids, parent = self._ibd.step(cand_ids, cand_probs)
        self._adopt_parents(parent)

        def build_step():
            def fn(feed, state, const):
                kc, vc, toks, logits = model.decode_step(
                    const, state[0], state[1], *feed, attn_impl=impl)
                return [logits], [kc, vc]
            return fn

        zeros_u = np.zeros((bw,), np.uint32)
        zeros_i = np.zeros((bw,), np.int32)
        zeros_f = np.zeros((bw,), np.float32)
        for s in range(2, max_steps + 1):
            pos = P + s - 2          # where the last selected token's
            self._ensure_writable(pos)   # K/V lands this dispatch
            last = sel_ids[:, 0].astype(np.int32)
            (logits,), new_state = self._exe.run_callable(
                f"decode/{self.name}/beam_step", build_step,
                [last, np.full((bw,), pos, np.int32), self._table(),
                 zeros_u, zeros_i, zeros_f, zeros_i],
                state=self.cache.state(), const=self._plist)
            self.cache.update(new_state)
            cand_ids, cand_probs = self._candidates(np.asarray(logits))
            sel_ids, parent = self._ibd.step(cand_ids, cand_probs)
            self._adopt_parents(parent)
        result = self._ibd.finalize()
        self._free_lanes()
        return result

    def close(self) -> None:
        self._free_lanes()


__all__ = ["PagedBeamDecoder"]
