"""Decode server: the generative plane's streaming RPC front door.

One more :class:`~paddle_tpu.distributed.transport.RPCServer` service
(like the pserver/master/registry/serving endpoints), with one new
message type:

- ``DECODE`` (msg 23): ``name`` = model name, payload = JSON request
  ``{"prompt": [ids], "max_new_tokens":, "temperature":, "top_k":,
  "seed":, "eos_id":, "chunk_tokens":}``.  The reply is a STREAM — the
  transport sends one frame per chunk as the engine generates (the
  multi-frame handler contract ``transport.STREAM``), each payload one
  tag byte + body:

  * ``T`` + ``serde.dumps_batch`` of ``[("tokens", int32[k])]`` — a
    chunk of ``chunk_tokens`` generated tokens (default 1: true
    token-by-token streaming), riding the PR-3 zero-copy batched serde;
  * ``F`` + JSON ``{"n_tokens":, "finish": "eos"|"length"}`` — end of
    stream;
  * ``O`` / ``L`` + JSON — typed :class:`Overloaded` /
    :class:`RequestTooLong` detail (single-frame reply, like the
    serving plane's INFER tags).

- ``DECODE_ADMIN`` (msg 26): JSON command — ``{"cmd": "status"}``
  returns the per-engine ``/decodez`` payloads.

Replica groups: ``registry_ep`` set ⇒ one TTL lease per served model
under ``decode/<model>/<replica_id>`` with role ``DECODE`` and the live
tokens/s riding the lease data — the PR-8 registry announce path, so
:class:`~paddle_tpu.decode.client.DecodeClient` discovers replicas and
health-gates exactly like the one-shot serving client.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional

import numpy as np

from .engine import DecodeEngine, SamplingParams
from ..distributed import registry as _registry
from ..distributed import serde, transport
from ..observability import audit as _audit
from ..observability import canary as _canary
from ..observability import flight as _flight
from ..observability import memory as _memory
from ..serving.batcher import Draining, Overloaded, RequestTooLong

# one msg-type namespace across every service: transport 1-14,
# master 15-20, serving 21/22, observability 24/25 — decode takes 23/26
DECODE = 23
DECODE_ADMIN = 26

transport.MSG_NAMES.update({DECODE: "decode",
                            DECODE_ADMIN: "decode_admin"})

_TAG_TOKENS = b"T"
_TAG_FIN = b"F"
_TAG_OVERLOAD = b"O"
_TAG_TOO_LONG = b"L"
_TAG_DRAINING = b"D"


def replica_key(model: str, replica_id: str) -> str:
    """The registry lease key a decode replica announces under."""
    return f"decode/{model}/{replica_id}"


def parse_replica_key(logical: str):
    """``(model, replica_id)`` from a decode lease key, else None."""
    parts = logical.split("/", 2)
    if len(parts) == 3 and parts[0] == "decode":
        return parts[1], parts[2]
    return None


class DecodeService:
    """``handle()`` contract of transport.RPCServer services; DECODE
    replies stream (``transport.STREAM``)."""

    def __init__(self, engines: Dict[str, DecodeEngine]):
        self.engines = dict(engines)
        # graceful drain: once set, new DECODE submits get a typed
        # Draining reply (the leases are already deregistered); the
        # streams already running keep generating to their FIN
        self.draining = False
        self.endpoint = ""

    def handle(self, msg_type, trainer_id, name, payload):
        if msg_type == DECODE:
            if self.draining:
                e = Draining(name, self.endpoint)
                return transport.OK, [
                    _TAG_DRAINING + json.dumps(e.to_dict()).encode("utf-8")]
            body = json.loads(bytes(payload).decode("utf-8"))
            eng = self.engines.get(name)
            if eng is None:
                return transport.ERR, \
                    f"decode: unknown model {name!r}".encode()
            sampling = SamplingParams.from_dict(body)
            chunk = max(1, int(body.get("chunk_tokens", 1)))
            # wire-optional tenant id: present only when the client set
            # one (old peers ignore unknown JSON keys — interop both
            # ways, absent ⇒ byte-identical request bodies)
            tenant = body.get("tenant")
            if not isinstance(tenant, str) or not tenant:
                tenant = None
            try:
                handle = eng.submit(body.get("prompt") or [], sampling,
                                    tenant=tenant)
            except Overloaded as e:
                return transport.OK, [
                    _TAG_OVERLOAD + json.dumps(e.to_dict()).encode("utf-8")]
            except RequestTooLong as e:
                return transport.OK, [
                    _TAG_TOO_LONG + json.dumps(e.to_dict()).encode("utf-8")]
            return transport.STREAM, self._stream(handle, chunk)
        if msg_type == DECODE_ADMIN:
            body = json.loads(bytes(payload).decode("utf-8"))
            if body.get("cmd") == "status":
                return transport.OK, json.dumps(
                    {m: e.decodez() for m, e in sorted(self.engines.items())},
                    default=repr).encode("utf-8")
            return transport.ERR, \
                f"decode_admin: unknown cmd {body.get('cmd')!r}".encode()
        return transport.ERR, f"decode: unknown msg {msg_type}".encode()

    @staticmethod
    def _stream(handle, chunk_tokens: int):
        """Frame generator: T-chunks as tokens arrive, then FIN.

        Two failure disciplines:
        - every token wait is BOUNDED by FLAGS_rpc_deadline — a wedged
          engine surfaces as a transport ERR frame, never a connection
          thread parked forever (the serving plane's INFER contract);
        - a client disconnect abandons this generator (the transport's
          STREAM path closes it), and the ``finally`` cancels the
          request — the engine frees the slot + cache blocks instead
          of generating into the void."""
        from ..core import flags as _flags
        deadline = float(_flags.get_flags("rpc_deadline"))
        buf = []
        try:
            while True:
                tok = handle.next_token(timeout=deadline)
                if tok is None:
                    break
                buf.append(tok)
                if len(buf) >= chunk_tokens:
                    yield [_TAG_TOKENS] + serde.dumps_batch_vec(
                        [("tokens", np.asarray(buf, np.int32))])
                    buf = []
            if buf:
                yield [_TAG_TOKENS] + serde.dumps_batch_vec(
                    [("tokens", np.asarray(buf, np.int32))])
            final = handle.result(timeout=0.0)
            yield [_TAG_FIN + json.dumps(
                {"n_tokens": final["n_tokens"],
                 "finish": final["finish"]}).encode("utf-8")]
        finally:
            handle.cancel()   # no-op when the stream finished normally


class DecodeServer:
    """One decode-serving process: RPC endpoint + engines + announces.

    ``engines``: model name → prebuilt :class:`DecodeEngine` (the
    server owns them and closes them on :meth:`stop` unless
    ``own_engines=False``)."""

    def __init__(self, endpoint: str = "127.0.0.1:0",
                 engines: Optional[Dict[str, DecodeEngine]] = None,
                 registry_ep: Optional[str] = None,
                 replica_id: Optional[str] = None,
                 lease_ttl: float = _registry.DEFAULT_TTL,
                 own_engines: bool = True):
        self.engines: Dict[str, DecodeEngine] = dict(engines or {})
        self._own_engines = own_engines
        self.service = DecodeService(self.engines)
        self._server = transport.RPCServer(endpoint, self.service)
        self.registry_ep = registry_ep
        self.lease_ttl = lease_ttl
        self.replica_id = replica_id or f"{self.endpoint}"
        self._hb_lock = threading.Lock()
        self._heartbeats: Dict[str, _registry.Heartbeat] = {}
        self._started = False

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def endpoint(self) -> str:
        host = self._server.endpoint.rsplit(":", 1)[0]
        return f"{host}:{self.port}"

    def add_engine(self, name: str, engine: DecodeEngine) -> None:
        self.engines[name] = engine
        self.service.engines[name] = engine
        self._sync_announcements()
        self._sync_canary_targets()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._server.start()
        self._started = True
        self.service.endpoint = self.endpoint
        # correctness plane: the golden prober self-arms in any decode
        # process (no-op with FLAGS_canary_probe off)
        _canary.maybe_start_from_flags()
        self._sync_announcements()
        self._sync_canary_targets()

    def stop(self, drain: bool = False, drain_timeout: float = 60.0
             ) -> None:
        """Shut the replica down.  ``drain=True`` is the graceful
        sequence (the serving plane's discipline, stream-shaped):
        deregister the leases FIRST so clients discover away from this
        replica before the socket dies, answer straggler submits with a
        typed :class:`Draining` reply, let every in-flight stream
        generate to its FIN within ``drain_timeout``, then close."""
        self._started = False
        with self._hb_lock:
            hbs, self._heartbeats = dict(self._heartbeats), {}
        for hb in hbs.values():
            hb.stop(bye=True)
        if drain:
            self.service.draining = True
            deadline = time.monotonic() + drain_timeout
            for name, eng in sorted(self.engines.items()):
                left = max(0.1, deadline - time.monotonic())
                if not eng.drain(timeout=left):
                    _flight.note("decode_drain_timeout", model=name,
                                 endpoint=self.endpoint)
        for model in self.engines:
            _canary.unregister_target(replica_key(model, self.replica_id))
        # drain: mid-reply connections (a stream's trailing FIN frame)
        # get a bounded grace before the transport severs them
        self._server.stop(graceful_s=2.0 if drain else 0.0)
        if self._own_engines:
            for eng in self.engines.values():
                eng.close()

    def install_sigterm_drain(self, drain_timeout: float = 60.0) -> None:
        """Arm SIGTERM as the graceful-drain trigger (what a supervisor
        shrink or an orchestrator rolling restart sends).  The handler
        runs :meth:`stop(drain=True)` on a daemon thread — signal
        handlers must return fast — and only AFTER the drain completes
        re-delivers SIGTERM under the PREVIOUS disposition, so the
        flight recorder's dump-then-die handler (or plain default
        termination) still runs, but post-drain instead of cutting the
        streams it was about to dump.  The previous disposition is
        restored immediately in the handler, so a SECOND SIGTERM during
        the drain escalates to the old immediate behavior.  Main
        thread only (signal module contract)."""
        import os as _os
        import signal as _signal

        prev = _signal.getsignal(_signal.SIGTERM)

        def _on_term(signum, frame):
            _flight.note("decode_sigterm_drain", endpoint=self.endpoint)
            # restore FIRST (handlers may only be set from the main
            # thread — the drain thread can't do it later)
            _signal.signal(_signal.SIGTERM, prev)

            def _drain_then_exit():
                try:
                    self.stop(drain=True, drain_timeout=drain_timeout)
                finally:
                    # hand the signal to its original disposition:
                    # flight dump + death, or default termination
                    _os.kill(_os.getpid(), _signal.SIGTERM)

            threading.Thread(target=_drain_then_exit, daemon=True,
                             name="decode-drain").start()

        _signal.signal(_signal.SIGTERM, _on_term)

    # -- registry announce -------------------------------------------------
    def _model_health(self, model: str):
        def probe() -> dict:
            eng = self.engines.get(model)
            return {"step": eng.stats.tokens.value if eng else 0}
        return probe

    def _model_data(self, model: str):
        def data() -> dict:
            out = {"model": model, "endpoint": self.endpoint}
            eng = self.engines.get(model)
            if eng is not None:
                z = eng.decodez()
                out["tokens"] = z["tokens"]
                out["queue_depth"] = z["queue_depth"]
                out["slots_active"] = sum(
                    s is not None for s in z["slots"])
                # token-level tail SLOs ride the lease payload so the
                # fleet sees each replica's TTFT/TBT p99 without
                # scraping it (present iff FLAGS_phase_attribution)
                for k in ("ttft_p99_ms", "tbt_p99_ms"):
                    if k in z:
                        out[k] = z[k]
                # capacity headroom rides the same lease payload
                # (present iff FLAGS_capacity_attribution with
                # completed work): the fleet reads saturation, not
                # just liveness
                cap = eng.stats.capacity()
                if cap is not None:
                    hr = cap.headroom()
                    if hr is not None:
                        out.update(hr)
            # correctness plane rides the same lease (canary streaks
            # present iff FLAGS_canary_probe; per-stream token-hash
            # digests present iff FLAGS_divergence_check) — the
            # supervisor's sentinel groups them across replicas
            can = _canary.lease_rider(replica_key(model, self.replica_id))
            if can is not None:
                out["canary"] = can
            dig = _audit.recent_digests()
            if dig is not None and model in dig:
                out["digests"] = {model: dig[model]}
            # memory anatomy rides the same lease (present iff
            # FLAGS_memory_attribution and pools registered): measured
            # KV-pool byte headroom for the ElasticController
            mem = _memory.lease_rider()
            if mem is not None:
                out.update(mem)
            return out
        return data

    # -- golden canary targets ---------------------------------------------
    def _canary_submit(self, model: str):
        """A probe submit fn through the real decode submit path
        (engine admission -> prefill -> continuous-batch steps).
        Golden feeds: ``prompt`` (int ids) plus an optional
        ``max_new_tokens`` scalar; the reply is the greedy token
        stream as ``[("tokens", int32[n])]`` so the prober's generic
        pair comparison applies (exact match — token ids carry no
        rtol)."""
        def submit(feeds: dict, tenant: Optional[str]):
            eng = self.engines.get(model)
            if eng is None:
                raise RuntimeError(f"canary probe: no engine {model!r}")
            prompt = np.asarray(feeds["prompt"], np.int32).reshape(-1)
            mnt = 8
            if "max_new_tokens" in feeds:
                mnt = int(np.asarray(
                    feeds["max_new_tokens"]).reshape(-1)[0])
            handle = eng.submit(prompt,
                                SamplingParams(max_new_tokens=mnt),
                                tenant=tenant)
            from ..core import flags as _flags
            final = handle.result(
                timeout=float(_flags.get_flags("rpc_deadline")))
            return [("tokens", np.asarray(final["tokens"], np.int32))]
        return submit

    def _sync_canary_targets(self) -> None:
        """Mirror :meth:`_sync_announcements` for the prober's target
        registry (works registry-less too) — a no-op unless armed."""
        if not _canary.enabled() or not self._started:
            return
        for model in self.engines:
            _canary.register_target(
                replica_key(model, self.replica_id), model,
                self._canary_submit(model))

    def _sync_announcements(self) -> None:
        """One registry heartbeat per served model (the serving plane's
        announce discipline with role DECODE)."""
        if not self.registry_ep or not self._started:
            return
        names = set(self.engines)
        with self._hb_lock:
            for model in sorted(names - set(self._heartbeats)):
                hb = _registry.Heartbeat(
                    self.registry_ep, replica_key(model, self.replica_id),
                    self.endpoint, ttl=self.lease_ttl, role="DECODE",
                    health_fn=self._model_health(model),
                    data_fn=self._model_data(model))
                hb.start()
                self._heartbeats[model] = hb
            for model in sorted(set(self._heartbeats) - names):
                self._heartbeats.pop(model).stop(bye=True)
