"""Autoregressive decode plane: stateful generative serving.

The PR-8 serving plane (:mod:`paddle_tpu.serving`) does one-shot
fixed-shape inference; generative traffic — the transformer / LSTM
token-by-token story (survey §2.9 inference subsystem + the level-2
``beam_search_decode`` machinery the reference ships in
``contrib/decoder.py``) — needs per-request state that survives across
dispatches.  Without a KV cache every generated token re-prefills the
whole prefix, so latency scales quadratically in output length.  This
package is that state plane, built on the repo's own primitives:

- **Paged KV cache** (:mod:`cache`): per-request key/value state lives
  in device memory as fixed-size blocks (``FLAGS_decode_block_tokens``)
  drawn from a preallocated pool; a request holds a block TABLE, so
  admission/eviction moves table entries and never changes a compiled
  shape.  The cache arrays ride
  :meth:`~paddle_tpu.core.executor.Executor.run_callable` as donated
  cache-resident state — they update in place in HBM and never
  round-trip to host.
- **Token-level continuous batching** (:mod:`engine`): requests join
  and leave a running decode batch at token granularity — the serving
  batcher's bucket-ladder discipline applied to the TIME axis.
  Prefill dispatches are SPLIT from the decode step (their own
  prompt-length bucket ladder, ``FLAGS_decode_prefill_buckets``), so a
  long new prompt never stalls in-flight streams.
- **Pallas decode-attention kernel**
  (:func:`paddle_tpu.kernels.attention.decode_attention`): one query
  token per slot against its gathered block list via scalar-prefetch
  block tables, with a counted XLA-gather fallback and interpret-mode
  CPU coverage (the ``kernels/sparse.py`` contract).
- **On-device sampling** (:mod:`model`): greedy / top-k / temperature
  inside the decode dispatch; incremental beam search rides
  :class:`paddle_tpu.contrib.decoder.IncrementalBeamDecoder` (the
  reference beam machinery, one ``beam_search`` step per decode step),
  and :class:`~paddle_tpu.decode.beam.PagedBeamDecoder` runs its beams
  as copy-on-write references into the paged cache (the parent gather
  becomes a block-table operation, not a state copy).
- **Refcounted block lifecycle** (``FLAGS_decode_prefix_cache`` /
  ``FLAGS_decode_overcommit``, both latched per engine): blocks carry
  refcounts; full prompt blocks are content-addressed in a
  :class:`~paddle_tpu.decode.cache.PrefixCache` so shared system
  prompts prefill once (later requests prefill only their suffix);
  admission may overcommit the pool, with decode-step growth and
  newest-stream preemption + token-exact re-prefill resume under
  pressure.  Both flags off: byte-identical legacy behavior.
- **Streaming serving** (:mod:`server` / :mod:`client`): tokens stream
  to clients over a new framed ``DECODE`` msg type on the existing
  zero-copy transport (multi-frame replies — the transport's STREAM
  handler contract), with per-model replica announce/health riding the
  PR-8 registry path and ``decode.*`` counters + ``/decodez`` on the
  observability plane.

Nothing here is imported by the core framework: a process that never
builds an engine gets no new arrays, threads, or sockets.
"""
from __future__ import annotations

from .cache import (BlockAllocator, PagedKVCache,  # noqa: F401
                    PrefixCache)
from .model import (LMConfig, TransformerLM, load_lm,  # noqa: F401
                    save_lm)
from .engine import (DecodeEngine, DecodeHandle,  # noqa: F401
                     DecodeRequest, SamplingParams)
from .beam import PagedBeamDecoder  # noqa: F401
from .server import DecodeServer, DecodeService  # noqa: F401
from .client import DecodeClient  # noqa: F401
from ..contrib.decoder import IncrementalBeamDecoder  # noqa: F401
from ..serving.batcher import (Draining, Overloaded,  # noqa: F401
                               RequestTooLong)

__all__ = [
    "BlockAllocator", "PagedKVCache", "PrefixCache",
    "LMConfig", "TransformerLM", "save_lm", "load_lm",
    "DecodeEngine", "DecodeHandle", "DecodeRequest", "SamplingParams",
    "PagedBeamDecoder",
    "DecodeServer", "DecodeService", "DecodeClient",
    "IncrementalBeamDecoder", "Draining", "Overloaded",
    "RequestTooLong",
]
