"""Thin streaming decode client: replica discovery + token iterator.

The generative counterpart of
:class:`~paddle_tpu.serving.client.ServingClient`.  A DECODE request's
reply is a FRAME STREAM (one frame per token chunk — see
:mod:`paddle_tpu.decode.server` for the tag grammar), so each
generation opens its OWN connection off the shared RPC pool: a stream
occupies its connection until the FIN frame, and striped reuse would
interleave two streams' frames.

Failover policy: a connection failure BEFORE the first token rotates
to the next replica (nothing was generated — safe to resend); after
the first token it surfaces — the stream is stateful and a blind
resend would bill the prompt twice.  A typed ``Overloaded`` rotates;
``RequestTooLong`` raises immediately (every replica enforces the same
bound).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import server as _server
from ..distributed import registry as _dist_registry
from ..distributed import serde, transport
from ..serving.batcher import Draining, Overloaded, RequestTooLong


class DecodeClient:
    def __init__(self, endpoints: Optional[Sequence[str]] = None,
                 registry_ep: Optional[str] = None, trainer_id: int = 0,
                 refresh_s: float = 2.0,
                 connect_timeout: float = 10.0):
        if not endpoints and not registry_ep:
            raise ValueError("DecodeClient needs endpoints or registry_ep")
        self._static = list(endpoints or [])
        self.registry_ep = registry_ep
        self.refresh_s = refresh_s
        self.connect_timeout = connect_timeout
        # discovery + admin ride the shared striped pool; streams don't
        self._rpc = transport.RPCClient(trainer_id)
        self._lock = threading.Lock()
        self._rr: Dict[str, int] = {}
        self._cache: Dict[str, Tuple[float, List[str]]] = {}

    # -- discovery (the ServingClient pattern over decode/ leases) ---------
    def replicas(self, model: str) -> List[str]:
        if not self.registry_ep:
            return list(self._static)
        with self._lock:
            ent = self._cache.get(model)
            if ent is not None and time.monotonic() < ent[0]:
                return list(ent[1])
        snap = _dist_registry.fetch_snapshot(self._rpc, self.registry_ep)
        try:
            health = _dist_registry.fetch_health(self._rpc,
                                                 self.registry_ep)
        except Exception:
            health = {}
        eps = []
        for logical, lease in sorted((snap.get("leases") or {}).items()):
            parsed = _server.parse_replica_key(logical)
            if parsed is None or parsed[0] != model:
                continue
            if (health.get(logical) or {}).get("state") == "DEAD":
                continue
            eps.append(lease["endpoint"])
        with self._lock:
            self._cache[model] = (time.monotonic() + self.refresh_s, eps)
        return eps

    # -- generation --------------------------------------------------------
    def generate_stream(self, model: str, prompt, max_new_tokens: int = 32,
                        temperature: float = 0.0, top_k: int = 0,
                        seed: int = 0, eos_id: Optional[int] = None,
                        chunk_tokens: int = 1,
                        tenant: Optional[str] = None):
        """Yield generated token ids as they stream; the generator's
        return value (``StopIteration.value``) is the FIN dict.
        ``tenant`` adds a wire-optional id for per-tenant metering —
        the key is included ONLY when set, so requests without one are
        byte-identical to tenant-unaware builds (old servers ignore
        the unknown key)."""
        body = {
            "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature), "top_k": int(top_k),
            "seed": int(seed), "eos_id": eos_id,
            "chunk_tokens": int(chunk_tokens)}
        if tenant:
            body["tenant"] = str(tenant)
        req = json.dumps(body).encode("utf-8")
        eps = self.replicas(model)
        if not eps:
            raise RuntimeError(f"no live decode replicas for {model!r}")
        with self._lock:
            start = self._rr.get(model, 0)
            self._rr[model] = start + 1
        last_exc: Optional[Exception] = None
        for i in range(len(eps)):
            ep = eps[(start + i) % len(eps)]
            stream = self._open_stream(ep, model, req)
            try:
                # force the first frame NOW: connection failures and
                # typed Overloaded can still rotate replicas (nothing
                # was generated); after this, the stream is stateful
                first = next(stream, None)
            except (ConnectionError, OSError) as e:
                last_exc = e
                continue
            except Overloaded as e:
                last_exc = e   # another replica may have slot headroom
                continue
            except Draining as e:
                last_exc = e   # graceful shutdown straggler: rotate
                continue
            return self._relay(first, stream)
        raise last_exc if last_exc is not None else RuntimeError(
            f"no decode replica answered for {model!r}")

    @staticmethod
    def _relay(first, stream):
        def gen():
            item = first
            while item is not None:
                if isinstance(item, dict):   # FIN
                    return item
                for t in item:
                    yield int(t)
                item = next(stream, None)
            return {}
        return gen()

    def generate(self, model: str, prompt, timeout: float = 120.0,
                 **kw) -> dict:
        """Blocking aggregate: ``{"tokens": [...], "finish":, ...}``."""
        toks: List[int] = []
        final = {}
        gen = self.generate_stream(model, prompt, **kw)
        deadline = time.monotonic() + timeout
        while True:
            try:
                toks.append(next(gen))
            except StopIteration as stop:
                final = stop.value or {}
                break
            if time.monotonic() > deadline:
                gen.close()
                raise TimeoutError(
                    f"decode of {model!r} exceeded {timeout}s")
        out = {"tokens": toks}
        out.update(final)
        return out

    def _open_stream(self, endpoint: str, model: str, payload: bytes):
        """Dedicated-connection frame reader: yields int32 token arrays
        (T frames) then the FIN dict; raises typed Overloaded /
        RequestTooLong / RuntimeError (ERR frame).  The connection
        closes with the generator (FIN, error, or caller .close())."""
        def frames():
            host, port = endpoint.rsplit(":", 1)
            io = transport._connect_io(host, int(port),
                                       self.connect_timeout)
            try:
                bufs = transport._pack_body_vec(
                    _server.DECODE, 0, model, [payload])
                transport._send_frame_any(io, bufs)
                while True:
                    body = io.recv_frame()
                    if body is None:
                        raise ConnectionError(
                            f"decode replica {endpoint} closed mid-stream")
                    rtype, _, _, rpayload = transport._unpack_body(body)
                    if rtype == transport.ERR:
                        raise RuntimeError(
                            "decode stream failed: "
                            + bytes(rpayload).decode("utf-8", "replace"))
                    tag = bytes(rpayload[:1])
                    rest = rpayload[1:]
                    if tag == _server._TAG_TOKENS:
                        pairs = serde.loads_batch(rest, copy=True)
                        yield np.asarray(pairs[0][1], np.int32)
                    elif tag == _server._TAG_FIN:
                        yield json.loads(bytes(rest).decode("utf-8"))
                        return
                    elif tag == _server._TAG_OVERLOAD:
                        raise Overloaded.from_dict(
                            json.loads(bytes(rest).decode("utf-8")))
                    elif tag == _server._TAG_TOO_LONG:
                        raise RequestTooLong.from_dict(
                            json.loads(bytes(rest).decode("utf-8")))
                    elif tag == _server._TAG_DRAINING:
                        raise Draining.from_dict(
                            json.loads(bytes(rest).decode("utf-8")))
                    else:
                        raise RuntimeError(
                            f"decode stream: unknown tag {tag!r}")
            finally:
                try:
                    io.close()
                except Exception:
                    pass

        return frames()

    # -- admin -------------------------------------------------------------
    def status(self, endpoint: str) -> dict:
        out = self._rpc._raw_request(
            endpoint, _server.DECODE_ADMIN, "status",
            json.dumps({"cmd": "status"}).encode("utf-8"))
        return json.loads(bytes(out).decode("utf-8"))
