"""Self-healing fleet supervisor: detect → decide → act → recover.

The missing actuator of the elastic story (ROADMAP item 4 residual):
:class:`~paddle_tpu.checkpoint.elastic.ElasticController` *decides*
("grow"/"shrink"/"hold" from registry health gauges) and the checkpoint
plane makes acting *safe* (topology-independent sharded checkpoints,
two-phase commit, N→M rehydration) — but until now a human restarted
dead processes.  :class:`Supervisor` closes the loop: it owns worker
lifecycle end to end from a declarative :class:`FleetSpec`.

Per worker, a small state machine::

    STARTING ──(lease seen / proc up)──> LIVE ──(shrink)──> DRAINING
       │                                  │                     │
       └──(action deadline)──┐            │ (proc exit != 0,    │(reaped)
                             v            v  or lease DEAD)     v
                 DEAD ──(budget left)──> REPLACING ──> STARTING ...
                   └──(budget blown)──> role HOLD  (crashloop)

Recovery disciplines:

- **stateless roles** (serving replicas, sleepers): a death respawns
  that one worker, after exponential backoff, budget permitting.
- **rollback roles** (``FleetSpec.rollback_roles`` — the sync-mode
  pserver fleet + its trainers): pserver state is only consistent
  *fleet-wide*, so one death rolls the WHOLE group back: every group
  member is killed, the stateful members respawn and hydrate their own
  sections from the newest COMPLETE sharded-checkpoint step (the PR-11
  N→M path — a replacement binds a FRESH ephemeral port and re-claims
  its logical key at the registry, so promotion-aware clients retarget),
  and dependents (trainers) respawn with ``{resume_step}`` pointing at
  the cut — deterministic data replay resumes at loss parity with zero
  human steps.
- **crash loops**: deaths are counted per role inside a sliding window;
  more than ``restart_budget`` respawns in ``restart_window_s`` puts
  the role (and the fleet status) in HOLD — a loud degrade
  (``supervisor.crashloop`` gauge + flight note) instead of a restart
  storm.  ``resume_role`` lifts it.
- **bounded actions**: a spawned worker that never turns LIVE within
  ``action_deadline_s`` is killed and counted (``action_timeouts``);
  the control loop itself never blocks on a wedged spawn — every
  action is a state transition checked per tick.

Elastic resize rides the same machinery: ``resize(role, n)`` (or the
``/fleetz?resize=role:n`` admin page, or a standing ``target`` in the
spec driven through ``ElasticController.decide`` with flap-damping
hysteresis) grows/shrinks stateless roles by spawn/drain, and resizes
rollback roles via cut-then-rollback: trigger a fleet checkpoint cut
(``notify_checkpoint``), poll the two-phase commit, then roll the group
back at the new size — the live N→M resize, automated.

Observability: ``supervisor.*`` counters/gauges, a ``/fleetz`` debug
page (per-worker state machine + history), flight-recorder notes on
every death/replacement/rollback/hold, and ``tools/fleet.py`` as the
operator CLI (launch/status/resize/drain from a spec file).
"""
from __future__ import annotations

import json
import os
import subprocess
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..observability import audit as _audit
from ..observability import debug_server as _debug_server
from ..observability import flight as _flight
from ..observability import stats as _obs_stats

__all__ = ["free_ports", "RoleSpec", "FleetSpec", "Supervisor"]

# worker states (the /fleetz state machine)
STARTING = "STARTING"
LIVE = "LIVE"
DRAINING = "DRAINING"
DEAD = "DEAD"
REPLACING = "REPLACING"
COMPLETED = "COMPLETED"
HELD = "HELD"


def free_ports(n: int) -> List[int]:
    """Allocate ``n`` distinct free localhost ports (bind-to-0, then
    release).  THE ephemeral-port helper — tests (``dist_model``, the
    chaos runner) and the supervisor all share this one implementation
    so nothing rolls its own colliding allocator.  Note the supervisor
    itself only uses these as stable LOGICAL endpoint ids: supervised
    workers bind ``host:0`` and announce their real port through the
    registry, so two fleets can never race for a released port."""
    import socket

    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _substitute(value: str, subs: Dict[str, str]) -> str:
    """Token substitution over the known placeholder set only (a stray
    ``{`` in a flag value must not explode like str.format would)."""
    for k, v in subs.items():
        value = value.replace("{" + k + "}", str(v))
    return value


class RoleSpec:
    """One role of a fleet: how many workers, how to launch one, and
    the robustness budget that governs restarting it.

    ``argv``/``env`` values may carry placeholders, substituted per
    spawn: ``{index}`` (worker index in the role), ``{spawn}`` (0-based
    incarnation counter), ``{name}`` (worker name ``<role>-<index>``),
    ``{registry}`` (the fleet registry endpoint), ``{checkpoint_root}``,
    ``{resume_step}`` (newest COMPLETE checkpoint step at spawn time, 0
    when none), ``{logical}`` (this worker's logical endpoint id), and
    ``{<role>_logicals}`` (comma list of any role's logical ids).

    ``env_once`` maps a worker index to env entries applied ONLY to
    that worker's FIRST spawn — the chaos suite arms its fault
    injection there, so a replacement comes up clean instead of
    re-arming the kill that created it.

    ``logical="auto"`` allocates one stable logical endpoint id per
    worker (``127.0.0.1:<free port>`` — an identity, not a binding;
    pass ``PADDLE_BIND_ENDPOINT=127.0.0.1:0`` style env so the worker
    binds ephemerally and announces).  ``health_role`` names the fleet
    health-plane role string (``PSERVER``/``TRAINER``/...) this role's
    workers heartbeat as — the key the DEAD-lease watch and the
    ElasticController decisions match on.
    """

    def __init__(self, count: int, argv: Sequence[str],
                 env: Optional[Dict[str, str]] = None,
                 env_once: Optional[Dict[int, Dict[str, str]]] = None,
                 logical: Optional[object] = None,
                 health_role: str = "",
                 after: Sequence[str] = (),
                 after_live: bool = True,
                 restart_budget: int = 3,
                 restart_window_s: float = 120.0,
                 backoff_s: float = 0.25,
                 backoff_max_s: float = 10.0,
                 action_deadline_s: float = 60.0,
                 grace_s: float = 5.0,
                 done_ok: bool = False,
                 target: Optional[int] = None):
        self.count = int(count)
        self.argv = list(argv)
        self.env = dict(env or {})
        self.env_once = {int(k): dict(v)
                         for k, v in (env_once or {}).items()}
        self.logical = logical
        self.health_role = health_role
        self.after = list(after)
        # True: dependents wait for deps to be LIVE (lease-gated) —
        # the safe default.  False: deps need only be SPAWNED, so a
        # rollback overlaps the dependents' process/import/compile
        # startup with the deps' (the transport's registry polling
        # absorbs the ordering) — the supervisor's pipelined-recovery
        # MTTR advantage over a serial choreographed restart.
        self.after_live = bool(after_live)
        self.restart_budget = int(restart_budget)
        self.restart_window_s = float(restart_window_s)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.action_deadline_s = float(action_deadline_s)
        self.grace_s = float(grace_s)
        self.done_ok = bool(done_ok)
        self.target = None if target is None else int(target)
        if self.count < 0 or self.restart_budget < 0:
            raise ValueError("count and restart_budget must be >= 0")

    @classmethod
    def from_dict(cls, d: dict) -> "RoleSpec":
        known = {"count", "argv", "env", "env_once", "logical",
                 "health_role", "after", "after_live", "restart_budget",
                 "restart_window_s", "backoff_s", "backoff_max_s",
                 "action_deadline_s", "grace_s", "done_ok", "target"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown RoleSpec fields: {sorted(unknown)}")
        return cls(**d)

    def to_dict(self) -> dict:
        return {"count": self.count, "argv": list(self.argv),
                "env": dict(self.env),
                "env_once": {k: dict(v) for k, v in self.env_once.items()},
                "logical": self.logical, "health_role": self.health_role,
                "after": list(self.after), "after_live": self.after_live,
                "restart_budget": self.restart_budget,
                "restart_window_s": self.restart_window_s,
                "backoff_s": self.backoff_s,
                "backoff_max_s": self.backoff_max_s,
                "action_deadline_s": self.action_deadline_s,
                "grace_s": self.grace_s, "done_ok": self.done_ok,
                "target": self.target}


class FleetSpec:
    """A whole fleet, declaratively: roles × counts × env, the registry
    (``"auto"`` = the supervisor runs one in-process), the sharded
    checkpoint root recovery hydrates from, which roles form the
    rollback group, and the elastic knobs (``hysteresis`` = consecutive
    same-direction ElasticController observations required before a
    grow/shrink acts — the flap damper; ``checkpoint_every_s`` = the
    supervisor's own periodic fleet-cut ticker, 0 = workers/spec own
    the cadence)."""

    def __init__(self, roles: Dict[str, RoleSpec],
                 registry: str = "auto",
                 checkpoint_root: Optional[str] = None,
                 rollback_roles: Sequence[str] = (),
                 cut_role: Optional[str] = None,
                 checkpoint_every_s: float = 0.0,
                 hysteresis: int = 2,
                 quarantine_on_canary_fail: bool = False,
                 name: str = "fleet"):
        self.roles = {r: (s if isinstance(s, RoleSpec)
                          else RoleSpec.from_dict(s))
                      for r, s in roles.items()}
        self.registry = registry
        self.checkpoint_root = checkpoint_root
        self.rollback_roles = list(rollback_roles)
        self.checkpoint_every_s = float(checkpoint_every_s)
        self.hysteresis = max(1, int(hysteresis))
        # correctness quarantine (observability/canary.py): when True, a
        # worker whose heartbeat reports a confirmed canary-fail streak
        # is DRAINED — never killed — so in-flight requests finish while
        # the lying replica leaves the serving set
        self.quarantine_on_canary_fail = bool(quarantine_on_canary_fail)
        self.name = name
        for r in self.rollback_roles:
            if r not in self.roles:
                raise ValueError(f"rollback role {r!r} not in roles")
        for r, s in self.roles.items():
            for dep in s.after:
                if dep not in self.roles:
                    raise ValueError(
                        f"role {r!r} depends on unknown role {dep!r}")
        # the role whose logical endpoints receive checkpoint_notify
        # fleet cuts: default = the first rollback role with logicals
        if cut_role is None:
            for r in self.rollback_roles:
                if self.roles[r].logical is not None:
                    cut_role = r
                    break
        self.cut_role = cut_role

    @classmethod
    def from_dict(cls, d: dict) -> "FleetSpec":
        d = dict(d)
        roles = {r: RoleSpec.from_dict(s) if not isinstance(s, RoleSpec)
                 else s for r, s in d.pop("roles").items()}
        return cls(roles=roles, **d)

    @classmethod
    def from_file(cls, path: str) -> "FleetSpec":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> dict:
        return {"name": self.name, "registry": self.registry,
                "checkpoint_root": self.checkpoint_root,
                "rollback_roles": list(self.rollback_roles),
                "cut_role": self.cut_role,
                "checkpoint_every_s": self.checkpoint_every_s,
                "hysteresis": self.hysteresis,
                "quarantine_on_canary_fail": self.quarantine_on_canary_fail,
                "roles": {r: s.to_dict() for r, s in self.roles.items()}}


class _Worker:
    """One supervised worker slot (a stable identity across respawns)."""

    _HISTORY = 16

    def __init__(self, role: str, index: int, logical: Optional[str]):
        self.role = role
        self.index = index
        self.name = f"{role}-{index}"
        self.logical = logical
        self.state = REPLACING          # pending its first spawn
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None
        self.spawns = 0
        self.last_rc: Optional[int] = None
        self.not_before = 0.0           # backoff gate for the next spawn
        self.deadline = 0.0             # STARTING -> LIVE bound
        self.drain_t0 = 0.0
        self.physical: Optional[str] = None   # last lease endpoint seen
        self.avoid_physical: Optional[str] = None  # dead incarnation's
        self.consecutive_deaths = 0
        self.expected_exit = False      # we killed it (drain/rollback)
        self.since = time.time()
        self.history: List[dict] = []

    def transition(self, state: str, **info) -> None:
        self.state = state
        self.since = time.time()
        self.history.append({"ts": round(self.since, 3), "state": state,
                             **info})
        del self.history[:-self._HISTORY]

    def to_dict(self) -> dict:
        return {"name": self.name, "role": self.role, "index": self.index,
                "state": self.state, "pid": self.pid,
                "spawns": self.spawns, "last_rc": self.last_rc,
                "logical": self.logical, "physical": self.physical,
                "since": round(self.since, 3),
                "history": list(self.history)}


class _SupMetrics:
    def __init__(self):
        sc = _obs_stats.scope("supervisor")
        self.spawns = sc.counter("spawns", "worker processes launched")
        self.deaths = sc.counter(
            "deaths", "unexpected worker exits (nonzero rc, signal, or "
            "DEAD lease) the supervisor acted on")
        self.collateral = sc.counter(
            "collateral_deaths", "group members reaped as part of a "
            "rollback (not counted against any budget)")
        self.replacements = sc.counter(
            "replacements", "individual workers respawned after a death")
        self.rollbacks = sc.counter(
            "rollbacks", "whole-group rollback recoveries to the newest "
            "COMPLETE checkpoint step")
        self.action_timeouts = sc.counter(
            "action_timeouts", "spawns killed for missing the "
            "STARTING->LIVE action deadline")
        self.wedged_kills = sc.counter(
            "wedged_kills", "processes killed because their health "
            "lease went DEAD while the process was still alive")
        self.drains = sc.counter(
            "drains", "workers gracefully drained (shrink/stop)")
        self.cuts = sc.counter(
            "cuts", "fleet checkpoint cuts the supervisor triggered")
        self.crashloop = sc.gauge(
            "crashloop", "1 while any role is HOLDing after blowing its "
            "restart budget (the anti-restart-storm fence)")
        self.holds = sc.gauge("holds", "roles currently in HOLD")
        self.live = sc.gauge("workers_live", "workers currently LIVE")
        self.slo_breaches = sc.counter(
            "slo_breaches", "sustained worker SLO-breach transitions "
            "the supervisor observed via the heartbeat slo dimension "
            "(observability/slo.py); observed and flight-noted, never "
            "an automatic resize — decisions stay HOLD-safe")
        self.slo_breach_workers = sc.gauge(
            "slo_breach_workers", "workers currently in confirmed "
            "(hysteresis-damped) SLO breach")
        self.canary_fails = sc.counter(
            "canary_fails", "sustained canary-fail transitions observed "
            "via the heartbeat canary dimension (observability/"
            "canary.py) after hysteresis damping")
        self.canary_quarantines = sc.counter(
            "canary_quarantines", "workers DRAINED (never killed) under "
            "spec.quarantine_on_canary_fail after a confirmed "
            "canary-fail streak")
        self.canary_fail_workers = sc.gauge(
            "canary_fail_workers", "workers currently in confirmed "
            "(hysteresis-damped) canary fail")
        self.divergence_named = sc.counter(
            "divergence_named", "divergent replicas the cross-replica "
            "sentinel named from lease-data digests "
            "(observability/audit.py)")


class Supervisor:
    """Owns a fleet per :class:`FleetSpec` (module doc).  Thread-safe
    public surface; one daemon control-loop thread does every check and
    every action as non-blocking state transitions."""

    def __init__(self, spec: FleetSpec, controller=None,
                 poll_s: float = 0.2, registry_poll_s: float = 0.5,
                 workdir: Optional[str] = None):
        self.spec = spec
        self.poll_s = float(poll_s)
        self.registry_poll_s = float(registry_poll_s)
        self.workdir = workdir
        self.lock = threading.RLock()
        self.metrics = _SupMetrics()
        self._own_registry = None
        self.registry_ep: Optional[str] = None
        self.controller = controller     # built at start() when None
        self.workers: Dict[str, _Worker] = {}
        self._role_workers: Dict[str, List[_Worker]] = {}
        self._deaths: Dict[str, List[float]] = {}   # role -> death times
        self._holds: Dict[str, str] = {}            # role -> reason
        self._rollback_active = False
        self._resize_cut: Optional[dict] = None     # pending cut-resize
        self._logicals: Dict[str, List[str]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next_reg_poll = 0.0
        self._next_cut = 0.0
        self._health: Dict[str, dict] = {}
        self._leases: Dict[str, str] = {}
        # capacity headroom harvested from lease DATA payloads
        # (FLAGS_capacity_attribution at the replicas): {lease key:
        # {headroom_frac, binding_phase, ...}} — empty when no replica
        # publishes it, so flags-off /fleetz is unchanged
        self._headroom: Dict[str, dict] = {}
        # measured memory headroom harvested from the same lease DATA
        # payloads (FLAGS_memory_attribution at the replicas): {lease
        # key: {memory_headroom_frac, memory_bytes, ...}}
        self._mem_headroom: Dict[str, dict] = {}
        # SLO-breach observation (heartbeat slo dimension): per-worker
        # consecutive-poll streaks, and the confirmed-breach set after
        # spec.hysteresis agreeing observations
        self._slo_streak: Dict[str, int] = {}
        self._slo_confirmed: Dict[str, list] = {}
        # canary-fail observation (heartbeat canary dimension), same
        # damping discipline; confirmed entries drive the optional
        # quarantine_on_canary_fail DRAIN policy
        self._canary_streak: Dict[str, int] = {}
        self._canary_confirmed: Dict[str, list] = {}
        # the cross-replica divergence verdict over lease-data digest
        # riders (FLAGS_divergence_check at the replicas); {} when no
        # replica publishes digests, so flags-off /fleetz is unchanged
        self._divergence: dict = {}
        self._divergence_seen: set = set()
        self._started = False
        self._client = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Supervisor":
        from . import registry as _registry_mod
        from . import transport as _transport
        with self.lock:
            if self._started:
                return self
            self._started = True
            if self.spec.registry == "auto":
                self._own_registry = _registry_mod.RegistryServer(
                    "127.0.0.1:0")
                self._own_registry.start()
                self.registry_ep = f"127.0.0.1:{self._own_registry.port}"
            else:
                self.registry_ep = self.spec.registry
            self._client = _transport.RPCClient(0)
            # cut notifies address LOGICAL endpoints: resolve them
            # through THIS fleet's registry (workers bind ephemerally)
            self._client.set_registry(self.registry_ep)
            if self.controller is None:
                from ..checkpoint.elastic import ElasticController
                self.controller = ElasticController(
                    self.registry_ep, poll_ttl=self.registry_poll_s,
                    hysteresis=self.spec.hysteresis)
            for role, rs in self.spec.roles.items():
                logicals = self._alloc_logicals(role, rs)
                self._logicals[role] = logicals
                ws = []
                for i in range(rs.count):
                    w = _Worker(role, i,
                                logicals[i] if i < len(logicals) else None)
                    self.workers[w.name] = w
                    ws.append(w)
                self._role_workers[role] = ws
            if self.spec.checkpoint_every_s > 0:
                self._next_cut = time.monotonic() + \
                    self.spec.checkpoint_every_s
        _debug_server.register_fleetz(self.spec.name, self.status,
                                      self._admin)
        _flight.note("supervisor_start", fleet=self.spec.name,
                     registry=self.registry_ep,
                     roles={r: s.count for r, s in self.spec.roles.items()})
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"supervisor-{self.spec.name}")
        self._thread.start()
        return self

    def _alloc_logicals(self, role: str, rs: RoleSpec) -> List[str]:
        if rs.logical is None:
            return []
        if rs.logical == "auto":
            return [f"127.0.0.1:{p}" for p in free_ports(rs.count)]
        return [str(x) for x in rs.logical]

    def stop(self, grace_s: Optional[float] = None) -> None:
        """Drain every worker (SIGTERM → grace → SIGKILL) and shut the
        control loop + owned registry down."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        with self.lock:
            workers = list(self.workers.values())
        for w in workers:
            self._terminate(w, hard=False)
        deadline = time.monotonic() + (grace_s if grace_s is not None
                                       else 5.0)
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.wait(timeout=max(0.0,
                                            deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    self._terminate(w, hard=True)
                    w.proc.wait(timeout=10.0)
        _debug_server.unregister_fleetz(self.spec.name)
        if self._own_registry is not None:
            self._own_registry.stop()
        _flight.note("supervisor_stop", fleet=self.spec.name)

    def wait(self, timeout: float = 600.0,
             poll: float = 0.2) -> str:
        """Block until the fleet reaches a terminal condition: every
        ``done_ok`` role fully COMPLETED ("done"), any role HOLDing
        ("hold"), or timeout ("timeout")."""
        deadline = time.monotonic() + timeout
        done_roles = [r for r, s in self.spec.roles.items() if s.done_ok]
        while time.monotonic() < deadline:
            with self.lock:
                if self._holds:
                    return "hold"
                if done_roles and all(
                        all(w.state == COMPLETED
                            for w in self._role_workers[r])
                        for r in done_roles):
                    return "done"
            time.sleep(poll)
        return "timeout"

    # -- public actions ----------------------------------------------------
    def resize(self, role: str, count: int) -> dict:
        """Retarget ``role`` to ``count`` workers.  Stateless roles
        grow/shrink directly; rollback roles go through cut-then-
        rollback (a fleet checkpoint cut commits first, then the group
        restarts at the new size and hydrates from it — the automated
        N→M resize)."""
        count = int(count)
        with self.lock:
            if role not in self.spec.roles:
                raise KeyError(f"unknown role {role!r}")
            rs = self.spec.roles[role]
            old = rs.count
            if count == old:
                return {"role": role, "count": old, "action": "hold"}
            if role in self.spec.rollback_roles:
                self._begin_cut_resize(role, count)
                return {"role": role, "count": count, "from": old,
                        "action": "cut_then_rollback"}
            if count > old:
                self._grow_locked(role, count)
                return {"role": role, "count": count, "from": old,
                        "action": "grow"}
            self._shrink_locked(role, count)
            return {"role": role, "count": count, "from": old,
                    "action": "shrink"}

    def drain_worker(self, name: str) -> dict:
        with self.lock:
            w = self.workers.get(name)
            if w is None:
                raise KeyError(f"unknown worker {name!r}")
            self._drain_locked(w)
            return {"drained": name}

    def resume_role(self, role: Optional[str] = None) -> dict:
        """Lift a HOLD (operator acknowledged the crash loop): clears
        the death window and re-enables respawns."""
        with self.lock:
            roles = [role] if role else list(self._holds)
            for r in roles:
                self._holds.pop(r, None)
                self._deaths.pop(r, None)
                for w in self._role_workers.get(r, ()):
                    w.consecutive_deaths = 0
                    if w.state == HELD:
                        w.transition(REPLACING, why="resumed")
                        w.not_before = 0.0
            self.metrics.holds.set(len(self._holds))
            self.metrics.crashloop.set(1 if self._holds else 0)
        _flight.note("supervisor_resume", roles=roles)
        return {"resumed": roles}

    def checkpoint_cut(self, wait_s: float = 0.0) -> dict:
        """Trigger a fleet checkpoint cut via ``notify_checkpoint`` on
        the cut role's logical endpoints (each pserver snapshots its own
        sections; the store commits two-phase when every piece lands).
        ``wait_s > 0`` polls for a NEW complete step that long."""
        from .. import checkpoint as _ckpt
        from . import ps_ops as _ps_ops
        root = self.spec.checkpoint_root
        role = self.spec.cut_role
        if not root or not role:
            raise RuntimeError(
                "checkpoint_cut needs spec.checkpoint_root and a cut "
                "role with logical endpoints")
        eps = list(self._logicals.get(role, ()))
        before = _ckpt.latest_complete_step(root)
        # the supervisor's own registry-resolving client (NOT the
        # process-global one): logical endpoints must resolve to the
        # workers' announced ephemeral ports
        _ps_ops.broadcast_checkpoint_notify(self._client, eps, root,
                                            connect_timeout=5.0)
        self.metrics.cuts.inc()
        out = {"endpoints": eps, "before": before}
        if wait_s > 0:
            deadline = time.monotonic() + wait_s
            while time.monotonic() < deadline:
                now_step = _ckpt.latest_complete_step(root)
                if now_step is not None and now_step != before:
                    out["committed"] = now_step
                    return out
                time.sleep(0.1)
            out["committed"] = None
        return out

    # -- status / admin ----------------------------------------------------
    def status(self) -> dict:
        from .. import checkpoint as _ckpt
        with self.lock:
            workers = [w.to_dict() for w in self.workers.values()]
            holds = dict(self._holds)
            roles = {}
            now = time.monotonic()
            headroom = dict(self._headroom)
            mem_headroom = dict(self._mem_headroom)
            canary_streaks = dict(self._canary_streak)
            for r, rs in self.spec.roles.items():
                window = [t for t in self._deaths.get(r, ())
                          if now - t <= rs.restart_window_s]
                roles[r] = {"count": rs.count, "target": rs.target,
                            "restart_budget": rs.restart_budget,
                            "deaths_in_window": len(window),
                            "hold": holds.get(r)}
                # lease-data capacity next to liveness: the tightest
                # replica's headroom, matched by the announce-key
                # prefix of the role's health plane (serving/decode)
                prefix = {"SERVING": "serving/",
                          "DECODE": "decode/"}.get(
                    (rs.health_role or "").upper())
                if prefix:
                    fracs = [v["headroom_frac"]
                             for k, v in headroom.items()
                             if k.startswith(prefix)]
                    if fracs:
                        roles[r]["headroom_frac"] = min(fracs)
                    # measured memory next to modeled capacity: the
                    # tightest replica's byte headroom, plus a leak
                    # flag when any replica's refcount audit failed
                    mfracs = [v["memory_headroom_frac"]
                              for k, v in mem_headroom.items()
                              if k.startswith(prefix)
                              and "memory_headroom_frac" in v]
                    if mfracs:
                        roles[r]["memory_headroom_frac"] = min(mfracs)
                    if any(v.get("memory_leak")
                           for k, v in mem_headroom.items()
                           if k.startswith(prefix)):
                        roles[r]["memory_leak"] = True
                    # the worst live canary-fail streak among this
                    # role's announce keys (absent when all pass, so
                    # flags-off status is unchanged)
                    streaks = [s for k, s in canary_streaks.items()
                               if k.startswith(prefix)]
                    if streaks:
                        roles[r]["canary_fail_streak"] = max(streaks)
        with self.lock:
            slo = {w: list(r) for w, r in self._slo_confirmed.items()}
            canary = {w: list(t)
                      for w, t in self._canary_confirmed.items()}
            divergence = dict(self._divergence)
        out = {"fleet": self.spec.name,
               "state": "HOLD" if holds else "RUNNING",
               "registry": self.registry_ep,
               "rollback_roles": list(self.spec.rollback_roles),
               "roles": roles, "workers": workers,
               "slo_breaches": slo, "canary_fails": canary}
        if divergence.get("divergent") or divergence.get("suspect"):
            out["divergence"] = divergence
        if headroom:
            out["headroom"] = headroom
        if mem_headroom:
            out["memory_headroom"] = mem_headroom
        root = self.spec.checkpoint_root
        if root:
            out["checkpoint"] = {
                "root": root,
                "latest_complete_step": _ckpt.latest_complete_step(root)}
        return out

    def _admin(self, cmd: dict) -> dict:
        """The /fleetz mutation surface (tools/fleet.py drives this)."""
        if "resize" in cmd:
            role, _, n = str(cmd["resize"]).partition(":")
            return self.resize(role, int(n))
        if "drain" in cmd:
            return self.drain_worker(str(cmd["drain"]))
        if "resume" in cmd:
            arg = str(cmd["resume"])
            return self.resume_role(None if arg in ("", "1", "all")
                                    else arg)
        if "cut" in cmd:
            return self.checkpoint_cut(
                wait_s=float(cmd.get("wait", 0) or 0))
        raise ValueError(f"fleetz admin: unknown command {cmd!r}")

    # -- spawn machinery ---------------------------------------------------
    def _subs_for(self, w: _Worker) -> Dict[str, str]:
        from .. import checkpoint as _ckpt
        resume = 0
        root = self.spec.checkpoint_root
        if root:
            resume = _ckpt.latest_complete_step(root) or 0
        subs = {"index": w.index, "spawn": w.spawns, "name": w.name,
                "role": w.role, "registry": self.registry_ep or "",
                "checkpoint_root": root or "",
                "resume_step": resume, "logical": w.logical or "",
                "workdir": self.workdir or os.getcwd()}
        for role, logicals in self._logicals.items():
            subs[f"{role}_logicals"] = ",".join(logicals)
        return subs

    def _spawn(self, w: _Worker) -> None:
        """One launch (call with lock held).  Never raises into the
        control loop: a spawn error is a counted death."""
        rs = self.spec.roles[w.role]
        subs = self._subs_for(w)
        argv = [_substitute(a, subs) for a in rs.argv]
        env = dict(os.environ)
        env.update({k: _substitute(v, subs) for k, v in rs.env.items()})
        if w.spawns == 0:
            for k, v in rs.env_once.get(w.index, {}).items():
                env[k] = _substitute(v, subs)
        try:
            w.proc = subprocess.Popen(
                argv, env=env, cwd=self.workdir,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                start_new_session=True)
        except OSError as e:
            w.proc = None
            w.transition(DEAD, why=f"spawn failed: {e!r}"[:200])
            self._on_death(w, f"spawn error: {e!r}"[:200])
            return
        w.pid = w.proc.pid
        w.spawns += 1
        w.expected_exit = False
        w.deadline = time.monotonic() + rs.action_deadline_s
        w.transition(STARTING, pid=w.pid, spawn=w.spawns)
        self.metrics.spawns.inc()
        _flight.note("supervisor_spawn", worker=w.name, pid=w.pid,
                     spawn=w.spawns)

    def _terminate(self, w: _Worker, hard: bool) -> None:
        if w.proc is None or w.proc.poll() is not None:
            return
        try:
            w.proc.kill() if hard else w.proc.terminate()
        except OSError:  # pragma: no cover - already reaped
            pass

    def _deps_live(self, role: str) -> bool:
        ok = ((LIVE, COMPLETED) if self.spec.roles[role].after_live
              else (STARTING, LIVE, COMPLETED))
        for dep in self.spec.roles[role].after:
            for w in self._role_workers.get(dep, ()):
                if w.state not in ok:
                    return False
        return True

    # -- the control loop --------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # the loop must survive everything
                _flight.note("supervisor_tick_error",
                             error=repr(e)[:200])
            self._stop.wait(self.poll_s)

    def _tick(self) -> None:
        now = time.monotonic()
        if self.registry_ep and now >= self._next_reg_poll:
            self._next_reg_poll = now + self.registry_poll_s
            self._poll_registry()
        # elastic decisions do registry RPCs (the controller's
        # fleet_view fetch): gather them OUTSIDE the lock so a slow
        # registry can never stall the /fleetz status+admin surface;
        # the actions re-check state under the lock (idempotent)
        decisions = self._elastic_decide()
        with self.lock:
            self._reap_exits()
            self._check_health_dead()
            self._check_deadlines(now)
            self._advance_drains(now)
            self._maybe_finish_rollback()
            self._pending_spawns(now)
            self._elastic_act(decisions)
            self._resize_cut_tick(now)
            cut_due = self._cut_due(now)
            self.metrics.live.set(sum(w.state == LIVE
                                      for w in self.workers.values()))
        if cut_due:
            # the notify fan-out is a per-endpoint bounded RPC round —
            # OUTSIDE the lock like every other network call, so an
            # unreachable pserver (the exact scenario recovery exists
            # for) can never freeze death reaping or /fleetz
            try:
                self.checkpoint_cut()
            except Exception as e:
                _flight.note("supervisor_cut_failed",
                             error=repr(e)[:200])

    def _poll_registry(self) -> None:
        """Refresh the lease + health view (outside the lock: one
        bounded RPC round)."""
        from . import registry as _registry_mod
        try:
            snap = _registry_mod.fetch_snapshot(
                self._client, self.registry_ep, connect_timeout=2.0)
            health = _registry_mod.fetch_health(
                self._client, self.registry_ep, connect_timeout=2.0)
        except Exception:
            return              # registry blip: keep the last view
        leases = {k: v["endpoint"]
                  for k, v in (snap.get("leases") or {}).items()}
        headroom = {}
        mem_headroom = {}
        digests = {}
        for key, data in (snap.get("data") or {}).items():
            if not isinstance(data, dict):
                continue
            if "headroom_frac" in data:
                headroom[key] = {k: data[k] for k in
                                 ("headroom_frac", "binding_phase",
                                  "predicted_max_qps") if k in data}
            if "memory_headroom_frac" in data or "memory_bytes" in data:
                mem_headroom[key] = {k: data[k] for k in
                                     ("memory_headroom_frac",
                                      "memory_bytes",
                                      "memory_parked_bytes",
                                      "memory_leak") if k in data}
            if isinstance(data.get("digests"), dict):
                digests[key] = data["digests"]
        # the sentinel proper: group digest riders ACROSS replicas and
        # name a divergent minority (pure function, outside the lock)
        verdict = _audit.name_divergent(digests) if digests else {}
        with self.lock:
            self._leases = leases
            self._headroom = headroom
            self._mem_headroom = mem_headroom
            self._health = health
            self._observe_slo_locked(health)
            # detect (canary streak) is noted before name (divergence
            # verdict) within a poll; the confirm+quarantine fires a
            # hysteresis-damped poll later — so the flight record reads
            # detect → name → drain in order
            self._observe_canary_locked(health)
            self._observe_divergence_locked(verdict)
            for w in self.workers.values():
                if w.logical and w.logical in leases:
                    w.physical = leases[w.logical]

    def _observe_slo_locked(self, health: Dict[str, dict]) -> None:
        """Fold one FRESH health view's slo dimensions into the damped
        breach observation (call with the lock held).  A worker whose
        heartbeat reports ``slo: breach`` for ``spec.hysteresis``
        consecutive polls becomes a CONFIRMED breach: counted, flight-
        noted, gauged and shown on /fleetz — but never an automatic
        resize (a breached-yet-alive fleet is an operator decision;
        killing the only replica that IS serving would make the SLO
        worse).  One non-breach poll resets the streak (the watchdog's
        own sustain window already filtered transients)."""
        need = self.spec.hysteresis
        for worker, info in health.items():
            slo = info.get("slo")
            if slo == "breach":
                streak = self._slo_streak.get(worker, 0) + 1
                self._slo_streak[worker] = streak
                if streak >= need and worker not in self._slo_confirmed:
                    rules = list(info.get("slo_rules") or [])
                    self._slo_confirmed[worker] = rules
                    self.metrics.slo_breaches.inc()
                    _flight.note("supervisor_slo_breach", worker=worker,
                                 rules=rules, streak=streak)
            else:
                self._slo_streak.pop(worker, None)
                if worker in self._slo_confirmed:
                    self._slo_confirmed.pop(worker)
                    _flight.note("supervisor_slo_clear", worker=worker)
        # workers that vanished from the view (deregistered/reaped)
        for worker in list(self._slo_confirmed):
            if worker not in health:
                self._slo_confirmed.pop(worker)
                self._slo_streak.pop(worker, None)
        self.metrics.slo_breach_workers.set(len(self._slo_confirmed))

    def _observe_canary_locked(self, health: Dict[str, dict]) -> None:
        """Fold one FRESH health view's canary dimensions into the
        damped observation (call with the lock held).  Same discipline
        as :meth:`_observe_slo_locked` — ``spec.hysteresis`` agreeing
        polls confirm — but with one extra tooth: under
        ``spec.quarantine_on_canary_fail`` a confirmed replica is
        DRAINED (the PR-13 typed drain: SIGTERM → deregister → finish
        in-flight → reap), never killed.  A canary fail means the
        replica answers WRONG, so leaving it in the serving set is
        worse than losing its capacity; draining quarantines it with
        zero dropped requests.

        Attribution: the canary dimension is process-global, so a
        process serving several announce keys stamps ``fail`` on every
        one of its heartbeats.  When the failing target's OWN announce
        key is present in this same health view, blame lands there and
        its innocent siblings are treated as passing — only a target
        name that maps to no visible key falls back to blaming the
        reporting key."""
        need = self.spec.hysteresis
        for worker, info in health.items():
            failing = info.get("canary") == "fail"
            targets = list(info.get("canary_targets") or [])
            if failing and targets and worker not in targets \
                    and any(t in health for t in targets):
                failing = False
            if failing:
                streak = self._canary_streak.get(worker, 0) + 1
                self._canary_streak[worker] = streak
                if streak == 1:
                    _flight.note("supervisor_canary_detect",
                                 worker=worker, targets=targets)
                if streak >= need and worker not in self._canary_confirmed:
                    self._canary_confirmed[worker] = targets
                    self.metrics.canary_fails.inc()
                    _flight.note("supervisor_canary_fail", worker=worker,
                                 targets=targets, streak=streak)
                    if self.spec.quarantine_on_canary_fail:
                        self._quarantine_locked(worker)
            else:
                self._canary_streak.pop(worker, None)
                if worker in self._canary_confirmed:
                    self._canary_confirmed.pop(worker)
                    _flight.note("supervisor_canary_clear", worker=worker)
        for worker in list(self._canary_confirmed):
            if worker not in health:
                self._canary_confirmed.pop(worker)
                self._canary_streak.pop(worker, None)
        self.metrics.canary_fail_workers.set(len(self._canary_confirmed))

    def _quarantine_locked(self, key: str) -> None:
        """Map a confirmed-failing heartbeat key to its supervised
        worker and drain it.  Serving/decode replicas heartbeat under
        announce keys (``serving/<model>/<replica>``) whose lease
        endpoint matches the worker's announced physical endpoint;
        plain workers heartbeat under their logical id directly.  An
        unmapped key (an unsupervised replica sharing the registry) is
        flight-noted, never guessed at."""
        ep = self._leases.get(key)
        for w in self.workers.values():
            if w.state not in (LIVE, STARTING):
                continue
            if w.logical == key or (ep is not None
                                    and ep in (w.physical, w.logical)):
                self.metrics.canary_quarantines.inc()
                _flight.note("supervisor_canary_quarantine",
                             worker=w.name, key=key)
                self._drain_locked(w)
                return
        _flight.note("supervisor_canary_quarantine_unmapped", key=key)

    def _observe_divergence_locked(self, verdict: dict) -> None:
        """Record the newest sentinel verdict; count + flight-note each
        NEWLY named (replica, group, digest) finding exactly once (the
        same divergence re-observed every poll is one event, not a
        counter storm)."""
        self._divergence = verdict
        for f in verdict.get("divergent") or ():
            fp = (f.get("replica"), f.get("model"), f.get("version"),
                  f.get("request_hash"), f.get("digest"))
            if fp in self._divergence_seen:
                continue
            self._divergence_seen.add(fp)
            self.metrics.divergence_named.inc()
            _flight.note("supervisor_divergence_named", **f)

    def _winding_down(self) -> bool:
        """True when every done_ok worker has finished (state COMPLETED
        or a 0 exit not yet reaped) — the window in which the REST of
        the fleet exiting cleanly is the normal end of the job, not a
        silent capacity loss.  A fleet with no done_ok role never winds
        down: its workers are services, and a clean exit is still an
        unexpected exit."""
        saw_done_role = False
        for role, rs in self.spec.roles.items():
            if not rs.done_ok:
                continue
            saw_done_role = True
            for w in self._role_workers.get(role, ()):
                if w.state == COMPLETED:
                    continue
                if w.proc is not None and w.proc.poll() == 0:
                    continue     # exited clean, reaped later this tick
                return False
        return saw_done_role

    def _reap_exits(self) -> None:
        winding_down = None      # computed lazily, once per tick
        for w in self.workers.values():
            if w.proc is None or w.state in (DEAD, REPLACING, COMPLETED,
                                             HELD):
                continue
            rc = w.proc.poll()
            if rc is None:
                continue
            w.last_rc = rc
            if w.state == DRAINING:
                w.transition(DEAD, rc=rc, why="drained")
                continue
            if w.expected_exit:
                # a supervisor-initiated kill outside a drain (rollback
                # members are already REPLACING and counted at the kill
                # site; this is the residual expected-exit path)
                w.transition(DEAD, rc=rc, why="expected")
                continue
            if rc == 0:
                if self.spec.roles[w.role].done_ok:
                    w.transition(COMPLETED, rc=0)
                    continue
                # a service worker exiting CLEAN is still an unexpected
                # exit — unless the fleet is winding down (pservers
                # return 0 once every trainer said COMPLETE): silently
                # reading it as COMPLETED would hide lost capacity
                if winding_down is None:
                    winding_down = self._winding_down()
                if winding_down:
                    w.transition(COMPLETED, rc=0)
                    continue
            w.transition(DEAD, rc=rc)
            self._on_death(w, f"exit rc={rc}")

    def _check_health_dead(self) -> None:
        """A worker whose lease went DEAD while its process still runs
        is wedged (GC death spiral, deadlock, partitioned): kill it so
        the normal death path replaces it."""
        if not self._health:
            return
        for w in self.workers.values():
            if w.state != LIVE or not w.logical or w.proc is None \
                    or w.proc.poll() is not None:
                continue
            ent = self._health.get(w.logical)
            if ent and ent.get("state") == "DEAD":
                self.metrics.wedged_kills.inc()
                _flight.note("supervisor_wedged_kill", worker=w.name,
                             logical=w.logical)
                self._terminate(w, hard=True)
                # reaped as a normal death next tick

    def _check_deadlines(self, now: float) -> None:
        for w in self.workers.values():
            if w.state != STARTING:
                continue
            if self._is_live(w):
                w.consecutive_deaths = 0   # proved itself: reset backoff
                w.transition(LIVE)
                continue
            if now >= w.deadline:
                self.metrics.action_timeouts.inc()
                _flight.note("supervisor_action_timeout", worker=w.name,
                             spawn=w.spawns)
                self._terminate(w, hard=True)
                if w.proc is not None:
                    try:   # SIGKILL reaps near-instantly: no zombie
                        w.last_rc = w.proc.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:  # pragma: no cover
                        pass
                w.transition(DEAD, why="action deadline")
                self._on_death(w, "spawn missed its action deadline")

    def _is_live(self, w: _Worker) -> bool:
        if w.proc is None or w.proc.poll() is not None:
            return False
        if not w.logical:
            return True           # no lease contract: running == live
        phys = self._leases.get(w.logical)
        if phys is None:
            return False
        # a lingering lease from the dead incarnation must not count
        return w.avoid_physical is None or phys != w.avoid_physical

    def _advance_drains(self, now: float) -> None:
        for w in self.workers.values():
            if w.state == DRAINING and w.proc is not None \
                    and w.proc.poll() is None \
                    and now - w.drain_t0 > self.spec.roles[w.role].grace_s:
                self._terminate(w, hard=True)

    # -- death handling ----------------------------------------------------
    def _on_death(self, w: _Worker, why: str) -> None:
        rs = self.spec.roles[w.role]
        self.metrics.deaths.inc()
        w.avoid_physical = w.physical
        _flight.note("supervisor_death", worker=w.name, why=why,
                     spawns=w.spawns)
        now = time.monotonic()
        window = self._deaths.setdefault(w.role, [])
        window.append(now)
        window[:] = [t for t in window if now - t <= rs.restart_window_s]
        if len(window) > rs.restart_budget:
            self._hold_role(w.role,
                            f"{len(window)} deaths in "
                            f"{rs.restart_window_s:.0f}s window "
                            f"(budget {rs.restart_budget}); last: {why}")
            w.transition(HELD, why="restart budget exhausted")
            return
        w.consecutive_deaths += 1
        backoff = min(rs.backoff_max_s,
                      rs.backoff_s * (2 ** (w.consecutive_deaths - 1)))
        if w.role in self.spec.rollback_roles:
            self._begin_rollback(w, backoff)
        else:
            self.metrics.replacements.inc()
            w.not_before = now + backoff
            w.transition(REPLACING, backoff_s=round(backoff, 3))

    def _hold_role(self, role: str, reason: str) -> None:
        """Crash-loop fence: stop respawning, say so loudly, keep the
        rest of the fleet serving.  The operator resumes explicitly."""
        if role in self._holds:
            return
        self._holds[role] = reason
        self.metrics.holds.set(len(self._holds))
        self.metrics.crashloop.set(1)
        _flight.note("supervisor_crashloop", role=role, reason=reason)
        print(f"[supervisor {self.spec.name}] role {role!r} is HOLDING: "
              f"{reason}", flush=True)
        # a held rollback role holds the whole rollback group (its
        # state can no longer be kept consistent by restarts)
        if role in self.spec.rollback_roles:
            for r in self.spec.rollback_roles:
                self._holds.setdefault(r, f"rollback group held by {role}")
                for w in self._role_workers.get(r, ()):
                    if w.state in (REPLACING, STARTING):
                        self._terminate(w, hard=True)
                        w.transition(HELD, why=f"group held by {role}")
            self.metrics.holds.set(len(self._holds))

    # -- rollback recovery (the stateful-group path) -----------------------
    def _begin_rollback(self, initiator: _Worker, backoff: float) -> None:
        """Roll the whole rollback group back to the newest COMPLETE
        checkpoint step: kill every member, then respawn in dependency
        order (stateful members hydrate their own sections from the
        cut; dependents get ``{resume_step}``).  Deaths we cause here
        are collateral, not budget events."""
        self.metrics.rollbacks.inc()
        self._rollback_active = True
        from .. import checkpoint as _ckpt
        step = None
        if self.spec.checkpoint_root:
            step = _ckpt.latest_complete_step(self.spec.checkpoint_root)
        _flight.note("supervisor_rollback", initiator=initiator.name,
                     resume_step=step)
        now = time.monotonic()
        for role in self.spec.rollback_roles:
            for w in self._role_workers.get(role, ()):
                if w.state in (COMPLETED, HELD):
                    continue    # a finished/held worker never restarts
                if w.proc is not None and w.proc.poll() is None:
                    # still running (collateral, or a live resize
                    # anchor): its in-memory state is being rolled back
                    # anyway, so a hard kill is correct AND fast.
                    # Counted HERE — the worker transitions straight to
                    # REPLACING, so the reap loop never sees this exit
                    w.expected_exit = True
                    self._terminate(w, hard=True)
                    self.metrics.collateral.inc()
                # the dead incarnation's unexpired lease must not mark
                # its replacement LIVE: remember the stale physical
                w.avoid_physical = w.physical
                w.transition(REPLACING, why="rollback")
                w.not_before = now + backoff

    def _maybe_finish_rollback(self) -> None:
        if not self._rollback_active:
            return
        for role in self.spec.rollback_roles:
            for w in self._role_workers.get(role, ()):
                if w.state not in (LIVE, COMPLETED, HELD):
                    return
        self._rollback_active = False
        _flight.note("supervisor_rollback_done")

    def _pending_spawns(self, now: float) -> None:
        # dependency order: a role spawns only when its deps are LIVE
        for role in self.spec.roles:
            if role in self._holds:
                continue
            if not self._deps_live(role):
                continue
            for w in self._role_workers.get(role, ()):
                if w.state == REPLACING and now >= w.not_before:
                    self._spawn(w)

    # -- elastic decisions -------------------------------------------------
    def _elastic_decide(self) -> List[tuple]:
        """Standing targets flow through ElasticController.decide (with
        its flap-damping hysteresis).  Runs OUTSIDE the supervisor lock
        — decide() may fetch the registry health view.  Returns
        ``[(role, decision), ...]`` for :meth:`_elastic_act`."""
        if self.controller is None:
            return []
        out = []
        for role, rs in self.spec.roles.items():
            if rs.target is None or role in self._holds:
                continue
            try:
                d = self.controller.decide(rs.health_role or role,
                                           rs.target)
            except Exception:
                continue          # registry blip: no decision this tick
            if d["action"] != "hold":
                out.append((role, d))
        return out

    def _elastic_act(self, decisions: List[tuple]) -> None:
        """Apply damped decisions (call with the lock held).  Actions
        clamp to ``rs.target`` — never ``count ± delta`` — so the same
        decision re-observed while lagging leases catch up (a respawn
        takes seconds; a drained lease lingers a TTL) is an idempotent
        no-op instead of a runaway grow storm / drain-to-zero."""
        for role, d in decisions:
            rs = self.spec.roles.get(role)
            if rs is None or rs.target is None or role in self._holds \
                    or self._resize_cut is not None:
                continue
            if role in self.spec.rollback_roles:
                if rs.count != rs.target:
                    self._note_decision(role, d)
                    self._begin_cut_resize(role, rs.target)
            elif d["action"] == "grow" and rs.count < rs.target:
                self._note_decision(role, d)
                self._grow_locked(role, rs.target)
            elif d["action"] == "shrink" and rs.count > rs.target:
                self._note_decision(role, d)
                self._shrink_locked(role, rs.target)

    @staticmethod
    def _note_decision(role: str, d: dict) -> None:
        _flight.note("supervisor_elastic_decision", role=role,
                     **{k: d[k] for k in ("action", "delta", "target")})

    def _grow_locked(self, role: str, count: int) -> None:
        rs = self.spec.roles[role]
        ws = self._role_workers[role]
        logicals = self._logicals[role]
        if rs.logical is not None and len(logicals) < count:
            # ONE batch allocation: free_ports holds all sockets open
            # together, which is what makes the ids distinct — minting
            # them one-by-one could hand the same released port back
            logicals.extend(f"127.0.0.1:{p}"
                            for p in free_ports(count - len(logicals)))
        for i in range(len(ws), count):
            w = _Worker(role, i, logicals[i] if i < len(logicals) else None)
            self.workers[w.name] = w
            ws.append(w)
        # re-grow over previously drained slots: a DEAD worker inside
        # the new count comes back (fresh spawn, backoff cleared)
        for w in ws[:count]:
            if w.state == DEAD:
                w.avoid_physical = w.physical
                w.consecutive_deaths = 0
                w.not_before = 0.0
                w.transition(REPLACING, why="regrown")
        rs.count = count
        _flight.note("supervisor_grow", role=role, count=count)

    def _shrink_locked(self, role: str, count: int) -> None:
        rs = self.spec.roles[role]
        ws = self._role_workers[role]
        for w in ws[count:]:
            if w.state in (LIVE, STARTING):
                self._drain_locked(w)
            elif w.state == REPLACING:
                w.transition(DEAD, why="shrunk before respawn")
        rs.count = count
        _flight.note("supervisor_shrink", role=role, count=count)

    def _drain_locked(self, w: _Worker) -> None:
        """Graceful retire: SIGTERM (serving/decode workers deregister
        + finish in-flight, trainers/pservers flight-dump), bounded by
        the role's ``grace_s``, then SIGKILL."""
        if w.state not in (LIVE, STARTING):
            return
        self.metrics.drains.inc()
        w.expected_exit = True
        w.drain_t0 = time.monotonic()
        w.transition(DRAINING)
        _flight.note("supervisor_drain", worker=w.name)
        self._terminate(w, hard=False)

    # -- cut-then-rollback resize -----------------------------------------
    def _begin_cut_resize(self, role: str, count: int) -> None:
        """N→M resize of a stateful group: cut first (so the new layout
        hydrates fresh state), then roll the group back at the new
        size.  Non-blocking: this only STAGES the resize — the notify
        fan-out fires on the next tick outside the supervisor lock
        (``_cut_due``), and the commit poll happens per tick under the
        role's action deadline."""
        from .. import checkpoint as _ckpt
        root = self.spec.checkpoint_root
        rs = self.spec.roles[role]
        before = _ckpt.latest_complete_step(root) if root else None
        self._resize_cut = {
            "role": role, "count": count, "before": before,
            "notify": True,
            "deadline": time.monotonic() + rs.action_deadline_s}
        _flight.note("supervisor_resize_begin", role=role, count=count)

    def _resize_cut_tick(self, now: float) -> None:
        if self._resize_cut is None:
            return
        from .. import checkpoint as _ckpt
        rc = self._resize_cut
        root = self.spec.checkpoint_root
        step = _ckpt.latest_complete_step(root) if root else None
        if step is not None and step != rc["before"]:
            self._resize_cut = None
            role, count = rc["role"], rc["count"]
            rs = self.spec.roles[role]
            # resize the slot table THEN rollback: the respawn sees the
            # new logicals list and each member hydrates its resharded
            # sections from the cut
            if count > rs.count:
                self._grow_locked(role, count)
            elif count < rs.count:
                ws = self._role_workers[role]
                for w in ws[count:]:
                    w.expected_exit = True
                    self._terminate(w, hard=True)
                    w.transition(DEAD, why="resized away")
                del ws[count:]
                for w in list(self.workers.values()):
                    if w.role == role and w.index >= count:
                        del self.workers[w.name]
                del self._logicals[role][count:]
                rs.count = count
            anchor = self._role_workers[role][0]
            self._begin_rollback(anchor, backoff=0.0)
            _flight.note("supervisor_resize_rollback", role=role,
                         count=count, cut_step=step)
        elif now >= rc["deadline"]:
            self._resize_cut = None
            self.metrics.action_timeouts.inc()
            _flight.note("supervisor_resize_cut_timeout",
                         role=rc["role"])

    def _cut_due(self, now: float) -> bool:
        """Decide (under the lock) whether a checkpoint-notify round
        should fire this tick — a staged resize's one-shot cut, or the
        periodic ticker.  The RPCs themselves run in _tick OUTSIDE the
        lock."""
        rc = self._resize_cut
        if rc is not None and rc.pop("notify", None):
            return True          # the resize's one-shot cut trigger
        if self.spec.checkpoint_every_s <= 0 or now < self._next_cut:
            return False
        self._next_cut = now + self.spec.checkpoint_every_s
        if self._rollback_active or rc is not None:
            return False
        cut_ws = self._role_workers.get(self.spec.cut_role or "", ())
        return bool(cut_ws) and all(w.state == LIVE for w in cut_ws)
