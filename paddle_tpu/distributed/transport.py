"""Framed-TCP variable transport: RPC client/server for pserver mode.

TPU-native replacement for the reference's gRPC transport
(``paddle/fluid/operators/distributed/grpc_client.h:175-206``,
``grpc_server.cc:82,117``, ``rpc_server.cc`` request barriers).  Runs over
DCN between TPU-VM hosts; intra-pod dense traffic rides XLA collectives
instead (parallel/), so this path only carries pserver/sparse variables.

Wire format (little-endian), one frame per request and per response:

    u32  body_len
    body = u8 msg_type | i32 trainer_id | u16 name_len | name | payload

Connections are persistent; each client socket is a serial
request/response channel (guarded by a lock), and the client fans out to
many endpoints concurrently via a shared thread pool — the analogue of the
reference's async completion queues + ``Wait`` (``grpc_client.h:180-213``).
Server handlers may block (sync-mode barriers), so the server is
thread-per-connection like the reference's handler thread pools.
"""
from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from . import serde

# message types (request)
SEND_VAR = 1
GET_VAR = 2
BATCH_BARRIER = 3
FETCH_BARRIER = 4
COMPLETE = 5
PREFETCH = 6
CHECKPOINT_NOTIFY = 7
# message types (response)
OK = 0
ERR = 255

_HDR = struct.Struct("<BiH")  # msg_type, trainer_id, name_len


def _send_frame(sock: socket.socket, msg_type: int, trainer_id: int,
                name: str, payload: bytes = b"") -> None:
    nm = name.encode("utf-8")
    body = _HDR.pack(msg_type, trainer_id, len(nm)) + nm + payload
    sock.sendall(struct.pack("<I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            return None
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket):
    raw = _recv_exact(sock, 4)
    if raw is None:
        return None
    (blen,) = struct.unpack("<I", raw)
    body = _recv_exact(sock, blen)
    if body is None:
        return None
    msg_type, trainer_id, name_len = _HDR.unpack_from(body, 0)
    off = _HDR.size
    name = body[off:off + name_len].decode("utf-8")
    payload = body[off + name_len:]
    return msg_type, trainer_id, name, payload


class RPCServer:
    """Serves variable requests against a pluggable service object.

    ``service.handle(msg_type, trainer_id, name, payload)`` returns
    ``(resp_type, resp_payload)`` and may block (barriers).  Reference:
    ``AsyncGRPCServer`` + ``RequestHandler`` (``grpc_server.cc:82``,
    ``request_handler_impl.cc``).
    """

    def __init__(self, endpoint: str, service):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self.service = service
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        frame = _recv_frame(self.request)
                    except OSError:
                        return
                    if frame is None:
                        return
                    msg_type, tid, name, payload = frame
                    try:
                        rtype, rpayload = outer.service.handle(
                            msg_type, tid, name, payload)
                    except Exception as e:  # propagate as ERR frame
                        rtype, rpayload = ERR, repr(e).encode("utf-8")
                    try:
                        _send_frame(self.request, rtype, tid, name, rpayload)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, int(port)), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name=f"rpc-server-{endpoint}")

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class _Conn:
    def __init__(self, endpoint: str, connect_timeout: float):
        host, port = endpoint.rsplit(":", 1)
        self.lock = threading.Lock()
        deadline = time.time() + connect_timeout
        last = None
        while True:
            try:
                self.sock = socket.create_connection((host, int(port)), timeout=30.0)
                self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self.sock.settimeout(None)
                return
            except OSError as e:  # pserver may not be up yet (_wait_ps_ready)
                last = e
                if time.time() > deadline:
                    raise ConnectionError(
                        f"cannot reach pserver at {endpoint}: {last}")
                time.sleep(0.1)


class RPCClient:
    """Trainer-side client: one persistent connection per endpoint +
    a shared pool for concurrent fan-out (``GRPCClient`` analogue)."""

    _CONNECT_TIMEOUT = 120.0

    def __init__(self, trainer_id: int = 0):
        self.trainer_id = trainer_id
        self._conns: Dict[str, _Conn] = {}
        self._conns_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=16,
                                        thread_name_prefix="rpc-client")

    def _conn(self, endpoint: str) -> _Conn:
        with self._conns_lock:
            c = self._conns.get(endpoint)
            if c is None:
                c = _Conn(endpoint, self._CONNECT_TIMEOUT)
                self._conns[endpoint] = c
            return c

    def _request(self, endpoint: str, msg_type: int, name: str = "",
                 payload: bytes = b""):
        c = self._conn(endpoint)
        with c.lock:
            _send_frame(c.sock, msg_type, self.trainer_id, name, payload)
            frame = _recv_frame(c.sock)
        if frame is None:
            raise ConnectionError(f"pserver {endpoint} closed the connection")
        rtype, _, _, rpayload = frame
        if rtype == ERR:
            raise RuntimeError(
                f"pserver {endpoint} error for {name!r}: "
                f"{rpayload.decode('utf-8', 'replace')}")
        return rpayload

    # -- public API (grpc_client.h:180-206 signatures) ---------------------
    def send_var(self, endpoint: str, name: str, value) -> None:
        self._request(endpoint, SEND_VAR, name, serde.dumps_value(value))

    def get_var(self, endpoint: str, name: str):
        return serde.loads_value(self._request(endpoint, GET_VAR, name))

    def prefetch(self, endpoint: str, table_name: str, ids):
        return serde.loads_value(
            self._request(endpoint, PREFETCH, table_name, serde.dumps_value(ids)))

    def batch_barrier(self, endpoint: str) -> None:
        self._request(endpoint, BATCH_BARRIER)

    def fetch_barrier(self, endpoint: str) -> None:
        self._request(endpoint, FETCH_BARRIER)

    def checkpoint_notify(self, endpoint: str, dirname: str) -> None:
        self._request(endpoint, CHECKPOINT_NOTIFY, dirname)

    def complete(self, endpoint: str) -> None:
        self._request(endpoint, COMPLETE)

    def parallel(self, calls):
        """Run [(fn, args...), ...] concurrently; reraise first error."""
        futs = [self._pool.submit(fn, *args) for fn, *args in calls]
        return [f.result() for f in futs]


# process-wide client singleton per trainer id (connections persist across
# executor steps, like the reference's RPCClient::GetInstance)
_clients: Dict[int, RPCClient] = {}
_clients_lock = threading.Lock()


def get_client(trainer_id: int = 0) -> RPCClient:
    with _clients_lock:
        c = _clients.get(trainer_id)
        if c is None:
            c = RPCClient(trainer_id)
            _clients[trainer_id] = c
        return c
