"""Framed-TCP variable transport: RPC client/server for pserver mode.

TPU-native replacement for the reference's gRPC transport
(``paddle/fluid/operators/distributed/grpc_client.h:175-206``,
``grpc_server.cc:82,117``, ``rpc_server.cc`` request barriers).  Runs over
DCN between TPU-VM hosts; intra-pod dense traffic rides XLA collectives
instead (parallel/), so this path only carries pserver/sparse variables.

The byte transport is pluggable (FLAGS_rpc_transport):

- ``native`` (default): the C transport in ``native/paddle_tpu_native.cc``
  — connect/accept/framing/partial-IO in C with TCP_NODELAY, mirroring
  the reference's C++ gRPC byte layer under Python request handlers
  (``request_handler_impl.cc`` split).
- ``python``: stdlib sockets (always available fallback).

Wire format (little-endian): one ``u32 body_len``-prefixed frame per
request and per response, body = ``u8 msg_type | i32 trainer_id |
u16 name_len | name | payload``.

Connections are persistent; each client connection is a serial
request/response channel (guarded by a lock), and the client fans out to
many endpoints concurrently via a shared thread pool — the analogue of the
reference's async completion queues + ``Wait`` (``grpc_client.h:180-213``).
``FLAGS_rpc_conns_per_endpoint`` stripes several connections per endpoint
so concurrent requests to ONE pserver (a batched round's sub-batches, a
storm of small vars) no longer serialize on a single connection lock —
the multi-channel ``grpc_client`` role (``GetChannel`` channel pools).
Server handlers may block (sync-mode barriers), so both server backends
are thread-per-connection like the reference's handler thread pools.

Batched frames (``SEND_VARS``/``GET_VARS``) carry many ``(name, value)``
pairs per round trip, and large tensor bodies are sent scatter-gather
(``socket.sendmsg``/``sendmsg(iovec)`` in the native backend) straight
from the ndarray — see ``serde.dumps_batch_vec``.
"""
from __future__ import annotations

import ctypes
import os
import socket
import socketserver
import sys as _sys
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import serde
from ..observability import flight as _flight
from ..observability import stats as _obs_stats
from ..observability import trace as _trace
from ..observability.trace import flags_on as _telemetry_on

# message types (request)
SEND_VAR = 1
GET_VAR = 2
BATCH_BARRIER = 3
FETCH_BARRIER = 4
COMPLETE = 5
PREFETCH = 6
CHECKPOINT_NOTIFY = 7
# batched var transport: one frame carries many (name, value) pairs —
# the round-trip-per-variable cost of SEND_VAR/GET_VAR amortized to one
# RPC per pserver per round (the reference's async completion-queue
# pipelining, collapsed into explicit batch frames).  Message-type ids
# share ONE namespace across every service (registry.py holds 8-10 and
# 13, master.py 16-20, STATS_PULL 24) so telemetry labels stay
# unambiguous
SEND_VARS = 11
GET_VARS = 12
# HA pserver replication (ps_ops.PServerLoop): the primary streams every
# applied SEND_VARS batch / barrier to its backup under a monotonic
# apply-sequence number; only flows when a backup is configured
REPLICATE = 14
# fleet observability (observability/aggregate.py): answered centrally by
# _serve_io for EVERY service object, so any RPCServer — pserver, master,
# registry — can be scraped for its process-local metric snapshot
STATS_PULL = 24
# distributed tracing (observability/trace.py): pull this process's
# bounded span ring — answered centrally like STATS_PULL, so trainer 0
# (or tools/stitch_trace.py) can stitch a fleet-wide trace from any
# worker's RPC port
TRACE_PULL = 25
# message types (response)
OK = 0
ERR = 255
# streaming handler verdict (NOT a wire status — never leaves the
# server): a service returning ``(STREAM, iterator)`` has _serve_io
# send one OK frame per yielded chunk on the SAME connection, in
# order, then resume the request loop.  The receiver owns framing the
# end of the stream at the application layer (the decode plane's FIN
# tag) — the transport just moves frames.  This is what the DECODE
# msg type rides: token chunks stream over the existing zero-copy
# scatter-gather send path with no new wire format.
STREAM = 254

MSG_NAMES = {SEND_VAR: "send_var", GET_VAR: "get_var",
             SEND_VARS: "send_vars", GET_VARS: "get_vars",
             BATCH_BARRIER: "batch_barrier", FETCH_BARRIER: "fetch_barrier",
             COMPLETE: "complete", PREFETCH: "prefetch",
             CHECKPOINT_NOTIFY: "checkpoint_notify",
             REPLICATE: "replicate",
             STATS_PULL: "stats_pull", TRACE_PULL: "trace_pull"}

_HDR = struct.Struct("<BiH")  # msg_type, trainer_id, name_len

# Trace-context frame extension: the high bit of msg_type says "a
# compact trace context (trace.WIRE_CTX_SIZE bytes) sits between the
# name and the payload".  Real message types stay < 0x80 (ERR=255 is a
# response type and is excluded from the flag check), so a frame
# WITHOUT the extension is byte-identical to the pre-trace wire format
# — old peers interop untouched as long as sampling is off, which is
# the default.  Enable FLAGS_trace_sample_rate only on an upgraded
# fleet.
TRACE_CTX_FLAG = 0x80

_CONNECT_TIMEOUT = 120.0

# RPC latency buckets (ms): LAN round trips through multi-second
# sync-barrier waits and tunneled DCN links
_RPC_MS_BUCKETS = (0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                   250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


def _backend() -> str:
    from ..core import flags

    try:
        want = flags.get_flags("rpc_transport")
    except KeyError:  # pragma: no cover
        want = "native"
    if want == "native" and _native_lib() is None:
        return "python"
    return want


_native = None
_native_failed = False


def _native_lib():
    global _native, _native_failed
    if _native is None and not _native_failed:
        try:
            from ..data import native as _n
            _native = _n.load()
        except Exception:  # pragma: no cover - build env without g++
            _native_failed = True
    return _native


def _pack_body(msg_type: int, trainer_id: int, name: str,
               payload: bytes, ctx: Optional[bytes] = None) -> bytes:
    nm = name.encode("utf-8")
    if ctx:
        return (_HDR.pack(msg_type | TRACE_CTX_FLAG, trainer_id, len(nm))
                + nm + ctx + payload)
    return _HDR.pack(msg_type, trainer_id, len(nm)) + nm + payload


def _pack_body_vec(msg_type: int, trainer_id: int, name: str,
                   payload_bufs: Sequence,
                   ctx: Optional[bytes] = None) -> list:
    """Scatter-gather body: header bytes + the payload buffer list
    untouched (tensor bodies stay views; see serde.dumps_value_vec).
    Zero-length buffers are dropped so empty-payload control messages
    (barriers, COMPLETE) keep the single-buffer fast path.  ``ctx``
    (a sampled trace context) rides between name and payload under the
    TRACE_CTX_FLAG msg-type bit; None adds zero bytes."""
    nm = name.encode("utf-8")
    if ctx:
        head = (_HDR.pack(msg_type | TRACE_CTX_FLAG, trainer_id, len(nm))
                + nm + ctx)
    else:
        head = _HDR.pack(msg_type, trainer_id, len(nm)) + nm
    return [head, *[b for b in payload_bufs if len(b)]]


def _unpack_body_ext(body: bytes):
    """Returns (msg_type, trainer_id, name, payload, ctx_bytes) —
    ``payload`` is a zero-copy memoryview over ``body`` (a 64 MB inbound
    gradient frame must not pay a full slice copy before
    ``loads_batch(copy=False)`` builds its views); ``ctx_bytes`` is the
    raw trace-context extension or None.  A frame without the extension
    parses exactly as the pre-trace format."""
    raw, trainer_id, name_len = _HDR.unpack_from(body, 0)
    off = _HDR.size
    name = bytes(body[off:off + name_len]).decode("utf-8")
    off += name_len
    ctx = None
    msg_type = raw
    if raw != ERR and raw & TRACE_CTX_FLAG:
        msg_type = raw & ~TRACE_CTX_FLAG
        ctx = bytes(body[off:off + _trace.WIRE_CTX_SIZE])
        off += _trace.WIRE_CTX_SIZE
    return msg_type, trainer_id, name, memoryview(body)[off:], ctx


def _unpack_body(body: bytes):
    """4-tuple form of :func:`_unpack_body_ext` (trace context, if any,
    is parsed off and dropped)."""
    msg_type, trainer_id, name, payload, _ = _unpack_body_ext(body)
    return msg_type, trainer_id, name, payload


def _int_flag(name: str, default: int) -> int:
    from ..core import flags
    try:
        return int(flags.get_flags(name))
    except (KeyError, TypeError, ValueError):  # pragma: no cover
        return default


def _vectored_on() -> bool:
    from ..core import flags
    try:
        return bool(flags.get_flags("rpc_vectored_io"))
    except KeyError:  # pragma: no cover
        return True


def _send_frame_any(io, bufs: list) -> Tuple[int, bool]:
    """Send one frame from a buffer list; returns (nbytes, vectored).

    Single-buffer bodies and flag-off runs take the classic one-buffer
    path; everything else goes scatter-gather (``sendmsg``/``writev`` —
    no Python-level concat of tensor bytes)."""
    nbytes = serde.buffers_nbytes(bufs)
    if nbytes >= 1 << 32:
        # the u32 frame-length prefix cannot carry it; without this
        # guard the native path would TRUNCATE the length silently and
        # desynchronize the stream.  Shard the variable (slice_var_up)
        # or lower FLAGS_rpc_stripe_chunk_bytes to keep frames smaller.
        raise ValueError(
            f"RPC frame of {nbytes} bytes exceeds the u32 frame limit "
            "(4 GiB); split the batch or shard the variable")
    if len(bufs) == 1:
        io.send_frame(bufs[0] if isinstance(bufs[0], bytes)
                      else bytes(bufs[0]))
        return nbytes, False
    if _vectored_on():
        io.send_frame_vec(bufs)
        return nbytes, True
    io.send_frame(b"".join(bufs))
    return nbytes, False


# ---------------------------------------------------------------------------
# byte-frame IO backends
# ---------------------------------------------------------------------------

class _PyIO:
    """u32-framed stdlib-socket IO."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    @classmethod
    def connect(cls, host: str, port: int, timeout: float) -> "_PyIO":
        deadline = time.time() + timeout
        last = None
        while True:
            # per-attempt timeout capped by the REMAINING deadline so a
            # SYN-black-holing peer honors short failover deadlines
            attempt = max(0.2, min(30.0, deadline - time.time()))
            try:
                s = socket.create_connection((host, port), timeout=attempt)
                s.settimeout(None)
                return cls(s)
            except OSError as e:  # pserver may not be up yet
                last = e
                if time.time() > deadline:
                    raise ConnectionError(
                        f"cannot reach pserver at {host}:{port}: {last}")
                time.sleep(0.1)

    def send_frame(self, body: bytes) -> None:
        try:
            self.sock.sendall(struct.pack("<I", len(body)) + body)
        except OSError as e:
            # normalize EVERY socket failure (EPIPE, EBADF, ETIMEDOUT,
            # ...) to ConnectionError: the retry/at-most-once discipline
            # in RPCClient keys on that type
            raise ConnectionError(f"send failed: {e}") from e

    # sendmsg iovec batches stay comfortably under IOV_MAX (1024 on
    # Linux); a 256-var batch is ~513 buffers
    _IOV_BATCH = 512

    def send_frame_vec(self, buffers: Sequence) -> None:
        """Scatter-gather frame: u32 length prefix + every buffer via
        ``socket.sendmsg`` — tensor bytes go from the ndarray views to
        the kernel with no userspace concat copy."""
        views = [b if isinstance(b, (bytes, bytearray))
                 else memoryview(b).cast("B") for b in buffers]
        total = sum(len(v) for v in views)
        views.insert(0, struct.pack("<I", total))
        idx, off = 0, 0
        try:
            while idx < len(views):
                batch = [memoryview(views[idx])[off:],
                         *views[idx + 1:idx + self._IOV_BATCH]]
                sent = self.sock.sendmsg(batch)
                while idx < len(views) and sent >= len(views[idx]) - off:
                    sent -= len(views[idx]) - off
                    idx, off = idx + 1, 0
                off += sent
        except OSError as e:
            raise ConnectionError(f"vectored send failed: {e}") from e

    def recv_frame(self) -> Optional[bytes]:
        raw = self._recv_exact(4)
        if raw is None:
            return None
        (blen,) = struct.unpack("<I", raw)
        return self._recv_exact(blen)

    def _recv_exact(self, n: int) -> Optional[bytes]:
        chunks = []
        while n:
            try:
                b = self.sock.recv(min(n, 1 << 20))
            except OSError:
                return None
            if not b:
                return None
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class _NativeIO:
    """C-transport IO (framing + partial reads/writes in native code).

    Handle lifetime: exactly one thread sends/receives on an IO at a time
    (client conns serialize under _Conn.lock; the server's serving thread
    is the sole reader).  ``shutdown`` only wakes a blocked reader;
    ``close`` frees — both serialized by ``_hlock`` so a raced shutdown
    never touches a freed handle."""

    def __init__(self, handle):
        self._h = handle
        self._lib = _native_lib()
        self._hlock = threading.Lock()

    @classmethod
    def connect(cls, host: str, port: int, timeout: float) -> "_NativeIO":
        lib = _native_lib()
        h = lib.ptq_conn_connect(host.encode(), int(port), float(timeout))
        if not h:
            raise ConnectionError(f"cannot reach pserver at {host}:{port}")
        return cls(h)

    def send_frame(self, body: bytes) -> None:
        h = self._h
        if not h:
            raise ConnectionError("native transport: connection closed")
        if self._lib.ptq_conn_send_frame(h, body, len(body)) != 0:
            raise ConnectionError("native transport: send failed")

    def send_frame_vec(self, buffers: Sequence) -> None:
        """Scatter-gather frame through the C transport's sendmsg/iovec
        path (``ptq_conn_send_frame_vec``): buffer addresses are taken
        via zero-copy uint8 views; ``arrs`` pins them for the call."""
        h = self._h
        if not h:
            raise ConnectionError("native transport: connection closed")
        arrs = [np.frombuffer(b, np.uint8) for b in buffers]
        n = len(arrs)
        ptrs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
        lens = (ctypes.c_size_t * n)(*[a.nbytes for a in arrs])
        if self._lib.ptq_conn_send_frame_vec(h, ptrs, lens, n) != 0:
            raise ConnectionError("native transport: vectored send failed")

    def recv_frame(self) -> Optional[bytes]:
        h = self._h
        if not h:
            return None
        n = ctypes.c_size_t()
        p = self._lib.ptq_conn_recv_frame(h, ctypes.byref(n))
        if not p:
            return None
        try:
            return ctypes.string_at(p, n.value)
        finally:
            self._lib.ptq_buffer_free(p)

    def shutdown(self) -> None:
        with self._hlock:
            if self._h:
                self._lib.ptq_conn_shutdown(self._h)

    def close(self) -> None:
        with self._hlock:
            if self._h:
                self._lib.ptq_conn_close(self._h)
                self._h = None


def _connect_io(host: str, port: int, timeout: float):
    if _backend() == "native":
        return _NativeIO.connect(host, port, timeout)
    return _PyIO.connect(host, port, timeout)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

def _handle_request(service, msg_type: int, tid: int, name: str, payload):
    """One request against the service, with the observability messages
    (STATS_PULL/TRACE_PULL) answered centrally so EVERY service —
    pserver, master, registry — is scrapable without changes."""
    if msg_type == STATS_PULL:
        from ..observability import aggregate as _obs_aggregate
        return OK, _obs_aggregate.local_snapshot_payload()
    if msg_type == TRACE_PULL:
        from ..observability import aggregate as _obs_aggregate
        return OK, _obs_aggregate.local_trace_payload()
    return service.handle(msg_type, tid, name, payload)


def _serve_io(io, service) -> None:
    """Request loop for one connection (either backend).

    ``service.handle`` may return its payload as ``bytes`` or as a
    scatter-gather buffer list (a ``GET_VARS`` reply streams tensor
    views with no concat copy).  A frame carrying a sampled trace
    context gets a server-side span parented under the inbound context
    — the cross-process half of the Dapper stitch; the span covers the
    WHOLE handle (including any sync-barrier block, which is exactly
    the wait a stitched timeline needs to show)."""
    from . import faults as _faults
    if _faults.active() and _faults.accept_fault():
        return               # injected refuse_accept: slam the connection
    while True:
        body = io.recv_frame()
        if body is None:
            return
        # busy marker for graceful stops: from request received to
        # reply written this connection must not be severed by
        # stop(graceful_s=...) — the serving plane's drain promises the
        # accepted request's REPLY, not just its handler return
        io.busy = True
        tel = _telemetry_on()
        t0 = time.perf_counter() if tel else None
        msg_type, tid, name, payload, wctx = _unpack_body_ext(body)
        if _faults.active() and _faults.server_fault(
                MSG_NAMES.get(msg_type, str(msg_type))) is not None:
            # injected drop_conn: sever before the handler runs — to the
            # peer this is indistinguishable from the server dying with
            # the request in flight (the retry/at-most-once paths' case)
            return
        sctx = _trace.ctx_from_wire(wctx) if wctx else None
        try:
            if sctx is not None:
                with _trace.start_span(
                        "rpc.server::" + MSG_NAMES.get(msg_type,
                                                       str(msg_type)),
                        cat="rpc", parent=sctx, root=False,
                        tags={"trainer_id": tid}):
                    rtype, rpayload = _handle_request(service, msg_type,
                                                      tid, name, payload)
            else:
                rtype, rpayload = _handle_request(service, msg_type, tid,
                                                  name, payload)
        except Exception as e:
            rtype, rpayload = ERR, repr(e).encode("utf-8")
        if rtype is None:
            # handler-requested drop: close WITHOUT responding — the
            # lost-response window of a peer dying mid-request (the
            # at-most-once failure-path tests inject through this)
            return
        if rtype == STREAM:
            # multi-frame reply: one OK frame per yielded chunk (bytes
            # or scatter-gather buffer list).  A generator fault mid-
            # stream becomes a trailing ERR frame — the client sees a
            # typed error, not a silent truncation; a ConnectionError
            # means the peer went away, stop serving this conn.
            try:
                for chunk in rpayload:
                    bufs = _pack_body_vec(
                        OK, tid, name,
                        chunk if isinstance(chunk, list) else [chunk])
                    _send_frame_any(io, bufs)
                    if tel:
                        _obs_stats.scope("rpc.server").counter(
                            "stream_frames").inc()
            except ConnectionError:
                # peer vanished mid-stream: close the generator NOW so
                # its finally-cleanup (the decode plane cancels the
                # abandoned request there) runs deterministically, not
                # at some future GC
                close = getattr(rpayload, "close", None)
                if callable(close):
                    try:
                        close()
                    except Exception:
                        pass
                return
            except Exception as e:
                try:
                    _send_frame_any(io, _pack_body_vec(
                        ERR, tid, name, [repr(e).encode("utf-8")]))
                except ConnectionError:
                    return
            io.busy = False
            continue
        resp_bufs = _pack_body_vec(rtype, tid, name,
                                   rpayload if isinstance(rpayload, list)
                                   else [rpayload])
        if tel:
            sc = _obs_stats.scope("rpc.server")
            sc.counter("requests." + MSG_NAMES.get(msg_type,
                                                   str(msg_type))).inc()
            sc.counter("bytes_in").inc(len(body))
            sc.counter("bytes_out").inc(serde.buffers_nbytes(resp_bufs))
            if msg_type in (SEND_VARS, GET_VARS) and len(payload) >= 4:
                # batch frames carry their pair count up front
                sc.counter("batched_vars").inc(
                    struct.unpack_from("<I", payload)[0])
            if rtype == ERR:
                sc.counter("handler_errors").inc()
            # includes any time the handler BLOCKED on a sync-mode
            # barrier — a saturated histogram tail here is the signature
            # of one slow trainer stalling the round
            sc.histogram("handle_ms", buckets=_RPC_MS_BUCKETS).observe(
                (time.perf_counter() - t0) * 1e3)
        try:
            nbytes, vectored = _send_frame_any(io, resp_bufs)
            if tel and vectored:
                _obs_stats.scope("rpc.server").counter(
                    "vectored_bytes").inc(nbytes)
        except ConnectionError:
            return
        io.busy = False


class RPCServer:
    """Serves variable requests against a pluggable service object.

    ``service.handle(msg_type, trainer_id, name, payload)`` returns
    ``(resp_type, resp_payload)`` and may block (barriers).  Reference:
    ``AsyncGRPCServer`` + ``RequestHandler`` (``grpc_server.cc:82``,
    ``request_handler_impl.cc``).
    """

    def __init__(self, endpoint: str, service):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self.service = service
        self._impl = (_NativeServer(host, int(port), service)
                      if _backend() == "native"
                      else _PyServer(host, int(port), service))
        # Explicit readiness signal (VERDICT r4 #5): both impls have
        # BOUND AND LISTENING by now, so announce it — launchers wait on
        # the file instead of poll-connecting (the reference's
        # _wait_ps_ready sleep loop, test_dist_base.py:232, improved).
        ready_dir = os.environ.get("PADDLE_READY_DIR")
        if ready_dir:
            os.makedirs(ready_dir, exist_ok=True)
            path = os.path.join(ready_dir, f"{host}:{self._impl.port}.ready")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(self.endpoint)
            os.replace(tmp, path)  # atomic: waiters never see a partial

    @property
    def port(self) -> int:
        return self._impl.port

    def start(self) -> None:
        # every serving process is debug-scrapable when the flag asks
        # for it (no-op, no socket, at the default flag value 0), and
        # leaves a flight-recorder post-mortem when armed
        from ..observability import debug_server as _debug_server
        _debug_server.maybe_start_from_flags()
        _flight.arm_from_flags()
        self._impl.start()

    def stop(self, graceful_s: float = 0.0) -> None:
        """``graceful_s > 0``: bounded wait for connections that are
        mid-reply (request received, reply not yet written) before
        severing — the serving drain's reply guarantee.  Default 0
        keeps the immediate-stop behavior everywhere else."""
        self._impl.stop(graceful_s)


_HOST_NORM_CACHE: Dict[str, str] = {}


def _normalize_host(host: str) -> str:
    """Canonical spelling of a ready-file host: wildcard binds collapse
    to ``*``, names resolve to their address, loopback spellings agree —
    so ``0.0.0.0``/hostname vs ``127.0.0.1`` endpoint lists still match
    (ADVICE r5: a live server must never time out over a spelling)."""
    host = host.strip().lower()
    if host in ("0.0.0.0", "::", "*", ""):
        return "*"
    if host == "localhost":
        return "127.0.0.1"
    cached = _HOST_NORM_CACHE.get(host)
    if cached is None:
        try:
            cached = socket.gethostbyname(host)
        except OSError:
            cached = host
        _HOST_NORM_CACHE[host] = cached
    return cached


def _ready_file_present(ready_dir: str, endpoint: str) -> bool:
    """True when a ready-file announces ``endpoint`` — matched verbatim
    first, then by port with normalized hosts (a server that bound
    ``0.0.0.0``/a hostname announces under that spelling).

    A wildcard-only match (``0.0.0.0:PORT.ready``) names no host, so on
    a SHARED ready-dir it could belong to another machine's same-port
    server — it is only trusted after a connect probe confirms a local
    listener."""
    if os.path.exists(os.path.join(ready_dir, endpoint + ".ready")):
        return True
    host, _, port = endpoint.rpartition(":")
    want = _normalize_host(host)
    suffix = f":{port}.ready"
    try:
        entries = os.listdir(ready_dir)
    except OSError:
        return False
    wildcard = False
    for fn in entries:
        if not fn.endswith(suffix):
            continue
        got = _normalize_host(fn[:-len(suffix)])
        if got == want:
            return True  # exact host match wins over any wildcard file
        wildcard = wildcard or got == "*" or want == "*"
    return wildcard and RPCClient._probe(endpoint, 1.0)


def wait_server_ready(endpoints, timeout: float = 90.0,
                      ready_dir: Optional[str] = None,
                      log_every: float = 2.0,
                      probe_grace: Optional[float] = None,
                      registry_ep: Optional[str] = None) -> None:
    """Block until every endpoint's server is listening.

    With ``PADDLE_READY_DIR`` set (the deterministic path — every
    RPCServer in that environment announces itself with an atomic
    ready-file), this waits on the files: no connection attempts, no
    races with a server mid-bind.  Ready filenames are matched with
    normalized hosts (wildcard binds, hostnames and loopback spellings
    all agree), and after ``probe_grace`` seconds (default
    ``min(5, timeout/2)``) a still-missing file falls back to a connect
    probe — a live server whose announcement went to a different
    ready-dir (or spelling) can no longer time the caller out.  Without
    a ready-dir, probe connects from the start (the reference
    ``_wait_ps_ready`` role, test_dist_base.py:232, bounded by
    ``timeout``).

    The wait is never silent: every probe round that leaves servers
    pending increments ``rpc.wait_server.retries``, and a progress line
    goes to stderr every ``log_every`` seconds — a launcher stuck here
    for 90 s used to look identical to a hang.

    With a registry (``registry_ep`` or ``FLAGS_pserver_registry``), the
    endpoints are treated as LOGICAL keys re-resolved each round: when a
    key's resolution flips mid-wait (a backup was promoted, a
    replacement re-registered), the probe retargets the new physical
    address immediately and the grace clock restarts — instead of
    waiting out the full grace against the dead address.  Every flip is
    counted in ``rpc.wait_server.repromotes``.
    """
    t_start = time.monotonic()
    deadline = t_start + timeout
    next_log = t_start + log_every
    ready_dir = ready_dir or os.environ.get("PADDLE_READY_DIR")
    if probe_grace is None:
        probe_grace = min(5.0, timeout / 2.0)
    probe_after = t_start + probe_grace
    pending = [e.strip() for e in endpoints]
    if registry_ep is None:
        from ..core import flags as _flags
        try:
            registry_ep = _flags.get_flags("pserver_registry") or None
        except KeyError:  # pragma: no cover
            registry_ep = None
    resolved: Dict[str, str] = {}
    reg_client = None
    next_resolve = t_start
    while pending:
        if registry_ep and time.monotonic() >= next_resolve:
            next_resolve = time.monotonic() + 0.5
            from . import registry as _registry_mod
            if reg_client is None:
                reg_client = RPCClient(0)
            for ep in pending:
                if ep == registry_ep:
                    continue
                try:
                    phys = _registry_mod.resolve(reg_client, registry_ep, ep)
                except ConnectionError:
                    break         # registry itself not up yet: keep probing
                if phys is None:
                    continue
                old = resolved.get(ep)
                resolved[ep] = phys
                if old is not None and old != phys:
                    # the endpoint flipped under us (backup promoted /
                    # replacement registered): retarget and restart the
                    # grace instead of riding out the dead address
                    probe_after = time.monotonic() + probe_grace
                    if _telemetry_on():
                        _obs_stats.counter(
                            "rpc.wait_server.repromotes",
                            "wait_server_ready probe retargets after a "
                            "mid-wait promotion/re-registration").inc()
                    print(f"[wait_server_ready] {ep} re-resolved "
                          f"{old} -> {phys}; restarting probe round",
                          file=_sys.stderr, flush=True)
        still = []
        for ep in pending:
            target = resolved.get(ep, ep)
            if ready_dir:
                ok = _ready_file_present(ready_dir, target)
                if not ok and target != ep:
                    ok = _ready_file_present(ready_dir, ep)
                if not ok and time.monotonic() >= probe_after:
                    # grace expired: trust a live listener over a
                    # missing announcement file
                    ok = RPCClient._probe(target, 1.0)
                    if ok and _telemetry_on():
                        _obs_stats.counter(
                            "rpc.wait_server.probe_fallbacks",
                            "endpoints accepted via the connect-probe "
                            "fallback after the ready-file grace "
                            "period").inc()
            else:
                ok = RPCClient._probe(target, 1.0)
            if not ok:
                still.append(ep)
        pending = still
        if not pending:
            return
        if _telemetry_on():
            _obs_stats.counter(
                "rpc.wait_server.retries",
                "probe rounds that left at least one server pending in "
                "wait_server_ready").inc()
        now = time.monotonic()
        if now >= next_log:
            print(f"[wait_server_ready] {now - t_start:.1f}s: waiting for "
                  f"{len(pending)} server(s): {', '.join(pending[:4])}"
                  + (" ..." if len(pending) > 4 else ""),
                  file=_sys.stderr, flush=True)
            next_log = now + log_every
        if now > deadline:
            raise TimeoutError(
                f"servers not ready after {timeout:.0f}s: {pending} "
                + (f"(no ready-file in {ready_dir})" if ready_dir
                   else "(connect probe failed)"))
        time.sleep(0.05)


class _PyServer:
    def __init__(self, host: str, port: int, service):
        outer_service = service

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                _serve_io(_PyIO(self.request), outer_service)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name=f"rpc-server-{host}:{port}")

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def stop(self, graceful_s: float = 0.0) -> None:
        # socketserver's shutdown never severs ACCEPTED connections
        # (daemon handler threads finish their writes naturally), so
        # graceful_s needs no extra wait on this backend
        self._server.shutdown()
        self._server.server_close()


class _NativeServer:
    """Accept loop over the native listener; thread per connection."""

    def __init__(self, host: str, port: int, service):
        self._lib = _native_lib()
        self._l = self._lib.ptq_listener_create(host.encode(), port)
        if not self._l:
            raise OSError(f"cannot bind {host}:{port}")
        self._service = service
        self._conns = []
        self._threads = []
        self._closing = False
        self._port = self._lib.ptq_listener_port(self._l)
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name=f"rpc-native-{host}:{port}")

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> None:
        self._thread.start()

    def _accept_loop(self) -> None:
        while True:
            h = self._lib.ptq_listener_accept(self._l)
            if not h:
                # listener shut down (stop()): the accept loop frees it
                lstn, self._l = self._l, None
                if lstn:
                    self._lib.ptq_listener_close(lstn)
                return
            io = _NativeIO(h)
            with self._lock:
                self._conns.append(io)

            def serve(io=io):
                try:
                    _serve_io(io, self._service)
                finally:
                    with self._lock:
                        if io in self._conns:
                            self._conns.remove(io)
                        if threading.current_thread() in self._threads:
                            self._threads.remove(threading.current_thread())
                    io.close()  # the serving thread OWNS the handle

            t = threading.Thread(target=serve, daemon=True)
            with self._lock:
                self._threads.append(t)
            t.start()

    def stop(self, graceful_s: float = 0.0) -> None:
        lstn = self._l
        self._closing = True
        if lstn:
            if self._thread.is_alive():
                # wake the blocked accept; the accept loop owns the
                # listener and frees it on the way out
                self._lib.ptq_listener_shutdown(lstn)
            else:
                self._l = None
                self._lib.ptq_listener_close(lstn)
        # quiesce the ACCEPT LOOP first: a connection accepted while we
        # snapshot would escape both the shutdown and the join below
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        with self._lock:
            conns = list(self._conns)
            threads = list(self._threads)
        if graceful_s > 0:
            # graceful stop (the serving drain): a connection between
            # "request received" and "reply written" (_serve_io busy
            # marker) gets its reply OUT before we sever — shutdown()
            # on a mid-reply connection loses a reply the drain already
            # promised.  Idle connections (blocked readers) don't wait
            deadline = time.monotonic() + graceful_s
            for io in conns:
                while getattr(io, "busy", False) \
                        and time.monotonic() < deadline:
                    time.sleep(0.005)
        for io in conns:
            io.shutdown()  # wake readers; serving threads free handles
        # JOIN the woken threads (bounded): a daemon thread still inside
        # the C++ transport when the interpreter finalizes dies via
        # pthread_exit, whose forced unwind aborts through g++ frames
        # ("FATAL: exception not rethrown") — seen as flaky pserver
        # crash-on-exit under load
        for t in threads:
            t.join(timeout=5.0)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class _Conn:
    def __init__(self, endpoint: str, connect_timeout: float):
        host, port = endpoint.rsplit(":", 1)
        self.lock = threading.Lock()
        self.io = _connect_io(host, int(port), connect_timeout)


class RPCClient:
    """Trainer-side client: ``FLAGS_rpc_conns_per_endpoint`` striped
    persistent connections per endpoint + a shared pool for concurrent
    fan-out (``GRPCClient`` analogue).  Stripe selection prefers an idle
    connection, so concurrent requests to one pserver pipeline across
    stripes instead of serializing on one connection lock."""

    def __init__(self, trainer_id: int = 0):
        self.trainer_id = trainer_id
        # endpoint -> fixed-size stripe list (None = not yet connected);
        # stripe width is latched per endpoint at first use
        self._conns: Dict[str, List[Optional[_Conn]]] = {}
        self._rr: Dict[str, int] = {}
        self._was_connected: set = set()
        self._conns_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=16,
                                        thread_name_prefix="rpc-client")
        # elastic re-binding (distributed/registry.py): when a registry is
        # configured, op endpoints are LOGICAL keys resolved to the current
        # physical endpoint; re-resolved on connection failure
        from ..core import flags
        try:
            self._registry = flags.get_flags("pserver_registry") or None
        except KeyError:  # pragma: no cover
            self._registry = None
        self._resolved: Dict[str, str] = {}
        # HA barrier sequencing: one monotonic round counter per logical
        # endpoint (the dedup key the pserver uses to make barriers
        # idempotent); only touched when the transpiler emitted ha mode
        self._barrier_seq: Dict[str, int] = {}
        self._barrier_seq_lock = threading.Lock()

    def set_registry(self, endpoint: Optional[str]) -> None:
        self._registry = endpoint or None
        self._resolved.clear()

    def _resolve(self, logical: str, refresh: bool = False,
                 avoid: Optional[str] = None) -> str:
        """logical -> physical endpoint via the registry (identity when no
        registry).  ``refresh`` polls until a LIVE registration different
        from ``avoid`` (a dead endpoint) appears, up to the rpc deadline —
        covering the window between a pserver dying and its replacement
        re-registering from the shard checkpoint."""
        if self._registry is None or logical == self._registry:
            return logical
        if not refresh and logical in self._resolved:
            return self._resolved[logical]
        from . import registry as _registry_mod
        deadline = time.monotonic() + _CONNECT_TIMEOUT
        reg_err = None
        while True:
            try:
                phys = _registry_mod.resolve(self, self._registry, logical)
                reg_err = None
            except ConnectionError as e:
                # registry briefly unreachable (its own conn dropped under
                # load): indistinguishable from not-yet-registered — poll
                phys, reg_err = None, e
            if phys is not None:
                # same address as the dead server: could be its stale lease
                # (TTL not yet expired) OR a supervisor restart on the SAME
                # port — distinguish by probing the socket; a live listener
                # means the replacement is up
                if phys != avoid or self._probe(phys):
                    self._resolved[logical] = phys
                    return phys
            if time.monotonic() > deadline:
                raise ConnectionError(
                    f"no live pserver re-registered for {logical!r} "
                    f"within the deadline (registry {self._registry}"
                    + (", which is itself UNREACHABLE" if reg_err else "")
                    + ")") from reg_err
            time.sleep(0.3)

    @staticmethod
    def _probe(endpoint: str, timeout: float = 1.0) -> bool:
        try:
            host, port = endpoint.rsplit(":", 1)
            socket.create_connection((host, int(port)), timeout).close()
            return True
        except (OSError, ValueError):
            # ValueError: a LOGICAL key (no host:port shape) that has no
            # physical resolution yet — not probeable, so not ready
            return False

    def _conn(self, endpoint: str, timeout: float = _CONNECT_TIMEOUT) -> _Conn:
        with self._conns_lock:
            pool = self._conns.get(endpoint)
            if pool is None:
                pool = self._conns[endpoint] = \
                    [None] * max(1, _int_flag("rpc_conns_per_endpoint", 2))
            was = endpoint in self._was_connected
            # stripe choice: an idle live connection first (``locked()``
            # is a hint — a raced grab just means one extra queued
            # request), then an unopened slot, then round-robin
            idx = None
            for i, x in enumerate(pool):
                if x is not None and not x.lock.locked():
                    idx = i
                    break
            if idx is None:
                for i, x in enumerate(pool):
                    if x is None:
                        idx = i
                        break
            if idx is None:
                idx = self._rr.get(endpoint, 0) % len(pool)
                self._rr[endpoint] = idx + 1
            c = pool[idx]
        if c is not None:
            return c
        # Reconnect deadline policy: the LONG deadline exists for initial
        # bring-up (pservers may start after trainers).  A previously-
        # connected endpoint reconnects with a SHORT deadline only when a
        # registry exists to fail over to — static-endpoint mode keeps the
        # long deadline so an in-place pserver restart is ridden out.
        if was and self._registry is not None:
            timeout = min(timeout, 5.0)
        # connect OUTSIDE the lock: a dead endpoint's blocking connect
        # must not stall requests to healthy pservers
        c = _Conn(endpoint, timeout)
        with self._conns_lock:
            pool = self._conns.get(endpoint)
            if pool is not None and idx < len(pool):
                winner = pool[idx]
                if winner is None:
                    pool[idx] = c
                    self._was_connected.add(endpoint)
                    return c
            else:
                winner = None
        # raced another creator (or the pool was dropped): keep theirs
        try:
            c.io.close()
        except Exception:
            pass
        return winner if winner is not None else self._conn(endpoint, timeout)

    def _drop_conn(self, endpoint: str, c: "_Conn") -> None:
        with self._conns_lock:
            pool = self._conns.get(endpoint)
            if pool:
                for i, x in enumerate(pool):
                    if x is c:
                        pool[i] = None
        try:
            with c.lock:  # never free under a peer thread's send/recv
                c.io.close()
        except Exception:
            pass

    # messages safe to auto-retry after a connection error: read-only or
    # idempotent on the server.  SEND_VAR/SEND_VARS (async mode applies
    # grads on arrival) and BATCH_BARRIER (closes a round) could have been
    # applied before the response was lost — retrying would double-count,
    # so they surface the error instead (the reference's at-most-once
    # discipline for mutating RPCs).  A batch frame is all-or-nothing on
    # the wire (the server decodes it only once fully received), so
    # SEND_VARS keeps the same discipline as N SEND_VARs.
    _RETRYABLE = frozenset((GET_VAR, GET_VARS, PREFETCH, FETCH_BARRIER,
                            CHECKPOINT_NOTIFY, STATS_PULL))

    def _raw_request(self, endpoint: str, msg_type: int, name: str = "",
                     payload=b"", retry_all: bool = False,
                     connect_timeout: Optional[float] = None,
                     n_vars: int = 0):
        """``payload``: bytes, or a scatter-gather buffer list (batched
        frames — sent via sendmsg/iovec, no concat copy).

        Under a sampled trace context this opens a client span and
        injects ITS context into the frame's trace extension, so the
        server's span parents under this request (not the whole step);
        with nothing sampled the frame is byte-identical to the
        pre-trace wire."""
        tel = _telemetry_on()
        t0 = time.perf_counter() if tel else None
        sc = _obs_stats.scope("rpc.client") if tel else None
        tctx = _trace.current()
        span = (_trace.start_span(
            "rpc.client::" + MSG_NAMES.get(msg_type, str(msg_type)),
            cat="rpc", root=False,
            tags={"endpoint": endpoint, "n_vars": n_vars} if n_vars
            else {"endpoint": endpoint})
            if tctx is not None and tctx.sampled else _trace.NOOP)
        with span:
            return self._raw_request_framed(endpoint, msg_type, name,
                                            payload, retry_all,
                                            connect_timeout, n_vars,
                                            tel, t0, sc)

    def _raw_request_framed(self, endpoint, msg_type, name, payload,
                            retry_all, connect_timeout, n_vars, tel, t0, sc):
        from . import faults as _faults
        if _faults.active() and _faults.client_fault(
                MSG_NAMES.get(msg_type, str(msg_type))) is not None:
            # injected client-side drop: behave exactly like the wire
            # dying before the first byte (the retry discipline decides)
            raise ConnectionError(
                f"injected fault: connection to {endpoint} dropped")
        req_bufs = _pack_body_vec(msg_type, self.trainer_id, name,
                                  payload if isinstance(payload, list)
                                  else [payload], ctx=_trace.inject())
        body = None
        for attempt in (0, 1):
            # retry connects get a short deadline: the long one is only for
            # initial bring-up (pservers may start after trainers).  Callers
            # with their own fast-fail policy (fleet metric pulls that must
            # not hang the scrape on one dead worker) pass connect_timeout.
            c = self._conn(endpoint,
                           connect_timeout if connect_timeout is not None
                           else _CONNECT_TIMEOUT if attempt == 0 else 5.0)
            try:
                with c.lock:
                    req_len, vectored = _send_frame_any(c.io, req_bufs)
                    body = c.io.recv_frame()
                if body is None:
                    raise ConnectionError(
                        f"pserver {endpoint} closed the connection")
                break
            except ConnectionError:
                # stale cached connection (pserver restarted, or the port
                # was reassigned): reconnect once for idempotent requests
                self._drop_conn(endpoint, c)
                if tel:
                    sc.counter("conn_errors").inc()
                if attempt or not (retry_all
                                   or msg_type in self._RETRYABLE):
                    raise
                if tel:
                    sc.counter("retries").inc()
        rtype, _, _, rpayload = _unpack_body(body)
        if tel:
            sc.counter("requests." + MSG_NAMES.get(msg_type,
                                                   str(msg_type))).inc()
            sc.counter("bytes_sent").inc(req_len)
            sc.counter("bytes_recv").inc(len(body))
            if vectored:
                sc.counter("vectored_bytes").inc(req_len)
            if n_vars:
                # vars carried per batched frame: frames-per-round vs
                # batched_vars is the round-trip amortization ratio
                sc.counter("batched_vars").inc(n_vars)
            sc.histogram("latency_ms", buckets=_RPC_MS_BUCKETS).observe(
                (time.perf_counter() - t0) * 1e3)
            if rtype == ERR:
                sc.counter("server_errors").inc()
        if rtype == ERR:
            raise RuntimeError(
                f"pserver {endpoint} error for {name!r}: "
                f"{bytes(rpayload).decode('utf-8', 'replace')}")
        return rpayload

    def _request(self, endpoint: str, msg_type: int, name: str = "",
                 payload=b"", n_vars: int = 0, idempotent: bool = False,
                 connect_timeout=None):
        """``idempotent=True`` marks a normally-non-retryable message as
        safe to re-send (the HA barrier carries a round sequence number
        the server dedups on), so a failover or transient drop retries
        it instead of surfacing the error.  ``connect_timeout`` bounds
        each connect attempt (best-effort callers like checkpoint
        notify must not ride out the full crash-recovery grace on a
        dead endpoint)."""
        phys = self._resolve(endpoint)
        try:
            return self._raw_request(phys, msg_type, name, payload,
                                     n_vars=n_vars, retry_all=idempotent,
                                     connect_timeout=connect_timeout)
        except ConnectionError:
            if self._registry is None or endpoint == self._registry:
                raise
            # the pserver behind this logical endpoint is gone: wait for a
            # replacement registration and retry there.
            new_phys = self._resolve(endpoint, refresh=True, avoid=phys)
            if _telemetry_on():
                _obs_stats.scope("rpc.client").counter("failovers").inc()
            if new_phys != phys:
                # a promotion/re-registration happened: bump the global
                # epoch so OTHER cached resolutions (this client's and
                # every other client's) re-resolve before their next use
                # — correlated failures move whole hosts, not one port
                bump_promotion_epoch()
            # loud by design: operators should see every elastic failover
            # (and the flight recorder should remember it post-mortem)
            print(f"[rpc-failover] {endpoint} msg={msg_type}: "
                  f"{phys} -> {new_phys}", file=_sys.stderr, flush=True)
            # field must not be named "msg" — that is note()'s own first
            # parameter (passing it kwargs-style raised TypeError and
            # killed the failover instead of retrying)
            _flight.note("rpc_failover", endpoint=endpoint,
                         msg_type=MSG_NAMES.get(msg_type, str(msg_type)),
                         old=phys, new=new_phys)
            if idempotent:
                return self._raw_request(new_phys, msg_type, name, payload,
                                         n_vars=n_vars, retry_all=True,
                                         connect_timeout=connect_timeout)
            if new_phys == phys and msg_type not in self._RETRYABLE:
                # same address answering the probe: could be the SAME live
                # server after a transient drop — re-sending a SEND_VAR or
                # BATCH_BARRIER there could double-apply (sync rounds
                # would close early).  Keep at-most-once and surface the
                # error; only a DIFFERENT replacement address proves a new
                # server instance, where a duplicate of the lost-response
                # request lands on checkpoint-restored state (one extra
                # async grad — the reference's elastic-mode tolerance).
                raise
            # Non-idempotent messages (SEND_VAR/SEND_VARS/BATCH_BARRIER/
            # ...) get ONE attempt at the replacement: with retry_all a
            # transient drop at the new server could apply the message
            # twice there — two duplicate grads, beyond the documented
            # one-extra-async-grad tolerance.  Read-only messages still
            # retry via _raw_request's own _RETRYABLE gate.
            return self._raw_request(new_phys, msg_type, name, payload,
                                     n_vars=n_vars,
                                     connect_timeout=connect_timeout)

    # -- public API (grpc_client.h:180-206 signatures) ---------------------
    def send_var(self, endpoint: str, name: str, value) -> None:
        self._request(endpoint, SEND_VAR, name,
                      serde.dumps_value_vec(value), n_vars=1)

    def get_var(self, endpoint: str, name: str):
        return serde.loads_value(self._request(endpoint, GET_VAR, name))

    # -- batched var transport ---------------------------------------------
    def send_vars(self, endpoint: str,
                  pairs: Sequence[Tuple[str, object]]) -> None:
        """One ``SEND_VARS`` frame carrying every ``(name, value)`` pair
        (at-most-once, like N ``SEND_VAR`` s — never silently retried).
        Batches whose tensor payload exceeds
        ``FLAGS_rpc_stripe_chunk_bytes`` are split at VAR granularity
        into per-stripe sub-batches sent concurrently, so a big dense
        round uses every striped connection; per-var semantics on the
        server are unchanged (a batch of N counts as N)."""
        pairs = list(pairs)
        if not pairs:
            return
        batches = self._stripe_batches(endpoint, pairs)
        if len(batches) == 1:
            self._request(endpoint, SEND_VARS, "",
                          serde.dumps_batch_vec(pairs), n_vars=len(pairs))
            return
        # sub-batches go on DEDICATED threads, never back onto the
        # shared fan-out pool: send_vars itself usually runs ON that
        # pool (ps_ops._send fans out per endpoint), and nested
        # submit+result on one bounded pool deadlocks once every worker
        # holds an outer task.  One sub-batch rides this thread.
        errs: List[BaseException] = []
        tctx = _trace.current()
        tctx = tctx if tctx is not None and tctx.sampled else None

        def _one(sub, _ctx=None):
            try:
                with _trace.activate(_ctx):
                    self._request(endpoint, SEND_VARS, "",
                                  serde.dumps_batch_vec(sub),
                                  n_vars=len(sub))
            except BaseException as e:  # noqa: BLE001 - reraised below
                errs.append(e)

        threads = [threading.Thread(target=_one, args=(sub, tctx),
                                    daemon=True)
                   for sub in batches[1:]]
        for t in threads:
            t.start()
        _one(batches[0])
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def get_vars(self, endpoint: str, names: Sequence[str],
                 copy: bool = True) -> list:
        """One ``GET_VARS`` round trip for many variables, in request
        order.  Defaults to ``copy=True`` — writable owned arrays, same
        semantics as N ``get_var`` calls.  ``copy=False`` returns
        zero-copy read-only views over the response buffer (each view
        pins the WHOLE response — right for a consumer that uses and
        drops them within the round, like the recv host op)."""
        names = list(names)
        if not names:
            return []
        payload = serde.dumps_batch([(n, None) for n in names])
        resp = self._request(endpoint, GET_VARS, "", payload,
                             n_vars=len(names))
        pairs = serde.loads_batch(resp, copy=copy)
        if [n for n, _ in pairs] != names:
            raise RuntimeError(
                f"pserver {endpoint} GET_VARS answered out of order: "
                f"asked {names[:4]}..., got {[n for n, _ in pairs][:4]}...")
        return [v for _, v in pairs]

    def _stripe_batches(self, endpoint: str, pairs: list) -> List[list]:
        """Split a big batch into per-stripe sub-batches (greedy balance
        by tensor bytes).  Single frame when striping is off, the batch
        is small, or only one var."""
        n_stripes = max(1, _int_flag("rpc_conns_per_endpoint", 2))
        if n_stripes <= 1 or len(pairs) <= 1:
            return [pairs]
        chunk_min = _int_flag("rpc_stripe_chunk_bytes", 8 << 20)
        sizes = [serde.value_nbytes(v) for _, v in pairs]
        if chunk_min <= 0 or sum(sizes) < chunk_min:
            return [pairs]
        k = min(n_stripes, len(pairs))
        buckets: List[list] = [[] for _ in range(k)]
        fill = [0] * k
        for (pair, sz) in sorted(zip(pairs, sizes), key=lambda t: -t[1]):
            i = fill.index(min(fill))
            buckets[i].append(pair)
            fill[i] += sz
        return [b for b in buckets if b]

    def prefetch(self, endpoint: str, table_name: str, ids):
        return serde.loads_value(
            self._request(endpoint, PREFETCH, table_name, serde.dumps_value(ids)))

    def next_barrier_seq(self, endpoint: str) -> int:
        """The next HA barrier round number for ``endpoint`` (1-based,
        monotonic per logical endpoint for this client's lifetime)."""
        with self._barrier_seq_lock:
            seq = self._barrier_seq.get(endpoint, 0) + 1
            self._barrier_seq[endpoint] = seq
            return seq

    def batch_barrier(self, endpoint: str, seq: Optional[int] = None) -> None:
        """Close this trainer's round.  ``seq`` (HA mode — the transpiler
        emits it only when a backup is configured) rides in the name
        field as a per-trainer round number the pserver dedups on,
        making the barrier idempotent: a retry after a connection drop
        or a promotion can no longer close a round twice.  ``seq=None``
        keeps the PR-5 wire byte-identical."""
        if seq is None:
            self._request(endpoint, BATCH_BARRIER)
        else:
            self._request(endpoint, BATCH_BARRIER, str(int(seq)),
                          idempotent=True)

    def fetch_barrier(self, endpoint: str) -> None:
        self._request(endpoint, FETCH_BARRIER)

    def checkpoint_notify(self, endpoint: str, dirname: str,
                          connect_timeout=None) -> None:
        """Ask one pserver to checkpoint (``dirname`` may carry an
        explicit fleet-cut step, see ps_ops.ckpt_notify_name).  Rides
        the failover-aware ``_request`` path — CHECKPOINT_NOTIFY is
        retryable, so an HA promotion retargets instead of failing —
        with an optionally bounded per-attempt connect."""
        self._request(endpoint, CHECKPOINT_NOTIFY, dirname,
                      connect_timeout=connect_timeout)

    def complete(self, endpoint: str) -> None:
        """Best-effort: the last trainer's COMPLETE makes the pserver shut
        down, which can race the response/connection teardown — a dropped
        connection here means the server exited, i.e. success.  That
        includes failing to CONNECT at all: a pserver that already died
        (e.g. chaos-killed mid-snapshot) needs no COMPLETE.  Never
        retried (a duplicate COMPLETE would double-count the trainer)."""
        endpoint = self._resolve(endpoint)
        try:
            c = self._conn(endpoint)
        except ConnectionError:
            return              # already down: nothing to shut down
        try:
            with c.lock:
                c.io.send_frame(_pack_body(COMPLETE, self.trainer_id, "",
                                           b""))
                c.io.recv_frame()
        except ConnectionError:
            pass
        finally:
            self._drop_conn(endpoint, c)

    def parallel(self, calls):
        """Run [(fn, args...), ...] concurrently; reraise first error.
        A sampled trace context on the calling thread is re-homed onto
        the pool threads so per-endpoint RPC spans still stitch under
        the step root."""
        ctx = _trace.current()
        if ctx is not None and ctx.sampled:
            def _with_ctx(fn, *args):
                with _trace.activate(ctx):
                    return fn(*args)
            futs = [self._pool.submit(_with_ctx, fn, *args)
                    for fn, *args in calls]
        else:
            futs = [self._pool.submit(fn, *args) for fn, *args in calls]
        return [f.result() for f in futs]


# process-wide client singleton per trainer id (connections persist across
# executor steps, like the reference's RPCClient::GetInstance)
_clients: Dict[int, RPCClient] = {}
_clients_lock = threading.Lock()


def get_client(trainer_id: int = 0) -> RPCClient:
    with _clients_lock:
        c = _clients.get(trainer_id)
        if c is None:
            c = RPCClient(trainer_id)
            _clients[trainer_id] = c
        return c


# ---------------------------------------------------------------------------
# promotion epoch: a process-wide "the fleet topology moved" counter
# ---------------------------------------------------------------------------
# Bumped whenever a failover lands on a DIFFERENT physical address (a
# pserver replacement re-registered, or a backup was promoted).  The
# executor compares it before dispatching RPC host ops and drops every
# client's logical→physical cache on change, so endpoints that did NOT
# fail a request yet still re-resolve promptly after a promotion instead
# of timing out into their own failovers one by one.

_promotion_epoch = 0
_promotion_lock = threading.Lock()


def promotion_epoch() -> int:
    return _promotion_epoch


def bump_promotion_epoch() -> int:
    global _promotion_epoch
    with _promotion_lock:
        _promotion_epoch += 1
        return _promotion_epoch


def refresh_resolutions() -> None:
    """Drop every client's cached logical→physical resolution (they
    rebuild lazily from the registry on next use)."""
    with _clients_lock:
        clients = list(_clients.values())
    for c in clients:
        c._resolved.clear()
