"""DistributeTranspiler: single program → trainer + pserver programs.

TPU-native redesign of
``python/paddle/fluid/transpiler/distribute_transpiler.py:144,237`` (and
``slice_variable:79``).  The same contract: take the trained program
(forward + backward + optimize), shard parameters across parameter-server
endpoints, and emit

- a **trainer program**: optimize/LR ops removed; grads are split into
  row-range sections (device ops), sent to their pservers (host ops),
  fresh param sections recv'd back and concatenated (device ops);
- per-endpoint **pserver programs**: a ``listen_and_serv`` host op whose
  sub-blocks hold the re-targeted optimizer ops for the endpoint's param
  sections (plus one shared LR-schedule block);
- per-endpoint **pserver startup programs**: param sections initialized
  *bit-identically* to the local run — initializer ops are keyed by var
  name (``seed_name`` → ``LowerContext.named_prng``), so a pserver
  initializes the full parameter with the same draw and slices out its
  rows.  This replaces the reference's startup-program splicing.

Differences from the reference, by design: gradient clipping and
regularization stay on the trainer (they rewrite the grad before send);
dense merging averages over trainers (kCoeffNumDevice semantics) so a
2-trainer run on half-batches matches the 1-process run on full batches.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Optional

from ..core.program import (OP_ROLE_ATTR, OP_ROLE_VAR_ATTR, Operator, OpRole,
                            Program, Variable, default_main_program,
                            default_startup_program)


class DistributeTranspilerConfig:
    """Reference DistributeTranspilerConfig (distribute_transpiler.py:125).

    ``checkpoint_dir``/``checkpoint_every_rounds`` enable periodic pserver
    self-checkpoints with restart recovery (go/pserver/service.go:346).

    ``backup_endpoints`` (comma list aligned with ``pservers``; empty
    slots allowed) arms HA replication: each named endpoint becomes the
    PHYSICAL address of a backup replica for the same-position pserver.
    The primary's ``listen_and_serv`` streams applied batches there
    (ps_ops.PServerLoop "HA replication"), ``get_backup_program``
    builds the replica's program, trainer barriers carry round seqs
    (idempotent retries), and the registry promotes the backup on the
    primary's lease expiry.  ``lease_ttl`` (seconds; 0 = registry
    default) bounds how long a death stays unnoticed — promotion and
    health transitions are measured in these lease terms.

    ``checkpoint_sharded`` switches the pserver checkpoint path to the
    topology-independent sharded store (``paddle_tpu/checkpoint/``):
    every pserver writes only the row shards it owns plus a manifest
    extent table, saves are ASYNC (the apply loop never blocks on
    serialization), steps commit two-phase (a crash mid-save can never
    leave a loadable half-checkpoint), and a restarted OR RESIZED fleet
    re-shards the newest COMPLETE step onto its own layout — N→M
    pserver counts both directions.  Off (default) keeps the legacy
    per-endpoint ``pserver_<i>.npz`` format byte-identical."""

    slice_var_up: bool = True
    min_block_size: int = 8192
    split_method: str = "RoundRobin"  # or "HashName"
    checkpoint_dir: Optional[str] = None
    checkpoint_every_rounds: int = 0
    checkpoint_sharded: bool = False
    backup_endpoints: str = ""
    lease_ttl: float = 0.0


class _Section:
    """One row-range shard of a parameter assigned to one endpoint."""

    def __init__(self, param: str, grad: str, index: int, offset: int,
                 rows: int, total: int, is_table: bool = False):
        self.param, self.grad = param, grad
        self.index, self.offset, self.rows = index, offset, rows
        self.sliced = total > 1
        self.is_table = is_table
        self.endpoint: str = ""

    @property
    def pname(self) -> str:
        return f"{self.param}@BLOCK{self.index}" if self.sliced else self.param

    @property
    def gname(self) -> str:
        return f"{self.grad}@BLOCK{self.index}" if self.sliced else self.grad


def _split_rows(dim0: int, numel: int, max_parts: int, min_block: int) -> List[int]:
    """Row counts for slicing a [dim0, ...] var into near-even contiguous
    sections of at least ``min_block`` elements each (capability match for
    reference slice_variable:79, original row-based scheme)."""
    if dim0 <= 1 or numel < 2 * min_block or max_parts <= 1:
        return [dim0]
    parts = min(max_parts, max(1, numel // min_block), dim0)
    base, extra = divmod(dim0, parts)
    return [base + (1 if i < extra else 0) for i in range(parts)]


def _ep_groups(names: List[str], endpoints: List[str]) -> List[list]:
    """Grouped epmap for send/recv ops: ``[[endpoint, [name, ...]], ...]``
    in first-appearance endpoint order.  Emitted at transpile time so the
    batched host ops (ps_ops.py) issue ONE RPC per pserver per round
    without regrouping every step."""
    by: Dict[str, List[str]] = {}
    order: List[str] = []
    for n, ep in zip(names, endpoints):
        if ep not in by:
            by[ep] = []
            order.append(ep)
        by[ep].append(n)
    return [[ep, by[ep]] for ep in order]


def _is_optimize_op(op) -> bool:
    return ("Param" in op.inputs and "Grad" in op.inputs
            and op.attr(OP_ROLE_ATTR) == OpRole.Optimize)


def _is_lr_op(op) -> bool:
    return bool(op.attr(OP_ROLE_ATTR) == OpRole.LRSched)


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()

    # -- main entry (reference transpile:237) ------------------------------
    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  pservers: str = "127.0.0.1:6174", trainers: int = 1,
                  sync_mode: bool = True,
                  startup_program: Optional[Program] = None):
        self.trainer_id = trainer_id
        self.origin_program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.endpoints = [e.strip() for e in pservers.split(",") if e.strip()]
        self.trainers = trainers
        self.sync_mode = sync_mode
        # HA replication: position-aligned backup physical endpoints
        self.backup_map: Dict[str, str] = {}
        if self.config.backup_endpoints:
            baks = [b.strip()
                    for b in self.config.backup_endpoints.split(",")]
            if len(baks) > len(self.endpoints):
                raise ValueError(
                    f"backup_endpoints names {len(baks)} entries for "
                    f"{len(self.endpoints)} pservers")
            self.backup_map = {ep: bak for ep, bak
                               in zip(self.endpoints, baks) if bak}

        block0 = self.origin_program.global_block
        self.opt_ops = [op for op in block0.ops if _is_optimize_op(op)]
        self.lr_ops = [op for op in block0.ops if _is_lr_op(op)]
        self.lr_names = sorted({n for op in self.opt_ops
                                for n in op.input("LearningRate")})

        # params whose gradient is a SelectedRows sparse slice
        # (lookup_table is_sparse): never sliced — row-slicing a sparse
        # grad needs a split_selected_rows + per-section id rebasing; keep
        # the whole table on one pserver so global row ids stay valid
        # (reference handles this case via the distributed-table path,
        # distribute_transpiler.py _distributed_lookup_table).
        self.sparse_params = {
            op.input("W")[0] for op in block0.ops
            if op.type == "lookup_table" and op.attr("is_sparse", False)}

        # distributed lookup tables: sharded by row range across ALL
        # pservers, served by remote prefetch (reference
        # _distributed_lookup_table, layers/nn.py:272-326,
        # operators/prefetch_op.cc:27)
        self.dist_table_ops: Dict[str, List] = {}
        for op in block0.ops:
            if op.type == "lookup_table" and op.attr("is_distributed", False):
                pad = op.attr("padding_idx", -1)
                if pad not in (None, -1):
                    raise NotImplementedError(
                        "padding_idx is not supported for distributed "
                        "lookup tables")
                self.dist_table_ops.setdefault(op.input("W")[0], []).append(op)

        # param sections in deterministic program order
        self.sections: List[_Section] = []
        self.table_sections: List[_Section] = []
        self.param_sections: Dict[str, List[_Section]] = {}
        for op in self.opt_ops:
            pname = op.input("Param")[0]
            gname = op.input("Grad")[0]
            pvar = block0.var(pname)
            numel = 1
            for s in pvar.shape:
                numel *= int(s)
            if pname in self.dist_table_ops:
                # one shard per endpoint, contiguous rows, global ids
                # rebased by the trainer-side split_selected_rows
                parts = min(len(self.endpoints), int(pvar.shape[0]))
                base, extra = divmod(int(pvar.shape[0]), parts)
                rows = [base + (1 if i < extra else 0) for i in range(parts)]
                secs, off = [], 0
                for i, r in enumerate(rows):
                    s = _Section(pname, gname, i, off, r, len(rows),
                                 is_table=True)
                    s.endpoint = self.endpoints[i]
                    secs.append(s)
                    off += r
                self.param_sections[pname] = secs
                self.table_sections.extend(secs)
                continue
            if self.config.slice_var_up and pname not in self.sparse_params:
                rows = _split_rows(int(pvar.shape[0]), numel,
                                   len(self.endpoints),
                                   self.config.min_block_size)
            else:
                rows = [int(pvar.shape[0])]
            secs, off = [], 0
            for i, r in enumerate(rows):
                secs.append(_Section(pname, gname, i, off, r, len(rows)))
                off += r
            self.param_sections[pname] = secs
            self.sections.extend(secs)

        # endpoint assignment (RoundRobin / HashName, distribute_transpiler
        # mode selection at :125)
        if self.config.split_method == "HashName":
            for s in self.sections:
                s.endpoint = self.endpoints[
                    zlib.crc32(s.pname.encode()) % len(self.endpoints)]
        else:
            for i, s in enumerate(self.sections):
                s.endpoint = self.endpoints[i % len(self.endpoints)]
        return self

    # -- trainer program (reference get_trainer_program) -------------------
    def get_trainer_program(self) -> Program:
        prog = self.origin_program.clone()
        block = prog.global_block
        block.ops = [op for op in block.ops
                     if not (_is_optimize_op(op) or _is_lr_op(op))]

        rpc_attrs = {"trainer_id": self.trainer_id,
                     OP_ROLE_ATTR: OpRole.RPC}

        # distributed tables: forward lookup → remote prefetch host op
        # (reference rewrite: lookup_table → split_ids/prefetch/merge_ids).
        # The trainer never materializes the table: the grad op reads
        # height/dtype from attrs and the var itself is dropped.
        for i, op in enumerate(block.ops):
            if (op.type == "lookup_table"
                    and op.input("W")[0] in self.dist_table_ops):
                table = op.input("W")[0]
                secs = self.param_sections[table]
                block.ops[i] = Operator(
                    block, "prefetch",
                    {"Ids": op.inputs["Ids"]}, {"Out": op.outputs["Out"]},
                    {**rpc_attrs, "table_name": table,
                     "sections": [[s.endpoint, s.offset, s.rows]
                                  for s in secs]})
            elif (op.type == "lookup_table_grad"
                    and op.input("W")[0] in self.dist_table_ops):
                tvar = self.origin_program.global_block.var(op.input("W")[0])
                op.inputs = {k: v for k, v in op.inputs.items() if k != "W"}
                op.set_attr("height", int(tvar.shape[0]))
                op.set_attr("w_dtype", tvar.dtype)
        for table in self.dist_table_ops:
            block.vars.pop(table, None)

        # device: split grads into sections
        for p, secs in self.param_sections.items():
            if len(secs) == 1 or secs[0].is_table:
                continue
            for s in secs:
                gvar = block.var(s.grad)
                block.create_var(
                    name=s.gname, shape=(s.rows,) + tuple(gvar.shape[1:]),
                    dtype=gvar.dtype)
            block.append_op(
                "split", {"X": [secs[0].grad]},
                {"Out": [s.gname for s in secs]},
                {"axis": 0, "sections": [s.rows for s in secs],
                 OP_ROLE_ATTR: OpRole.Dist})

        # host: split SelectedRows table grads by shard range (global row
        # ids rebased to shard-local; reference split_selected_rows_op)
        for table, secs in self.param_sections.items():
            if not secs[0].is_table:
                continue
            block.append_op(
                "split_selected_rows", {"X": [secs[0].grad]},
                {"Out": [s.gname for s in secs]},
                {**rpc_attrs, "sections": [[s.offset, s.rows] for s in secs]})

        # host: send grad sections → pservers (ep_groups: one batched
        # SEND_VARS frame per endpoint per round)
        send_secs = self.sections + self.table_sections
        block.append_op(
            "send", {"X": [s.gname for s in send_secs]}, {},
            {**rpc_attrs, "epmap": [s.endpoint for s in send_secs],
             "ep_groups": _ep_groups([s.gname for s in send_secs],
                                     [s.endpoint for s in send_secs])})
        if self.sync_mode:
            barrier_attrs = {**rpc_attrs, "endpoints": self.endpoints}
            if self.backup_map:
                # HA mode: barriers carry per-endpoint round seqs so the
                # pserver (or its promoted backup) dedups retransmits —
                # emitted ONLY when a backup exists, keeping the
                # no-backup wire byte-identical
                barrier_attrs["ha"] = True
            block.append_op("send_barrier", {}, {}, barrier_attrs)

        # host: recv param sections ← pservers
        for p, secs in self.param_sections.items():
            if secs[0].is_table:
                continue
            for s in secs:
                if s.sliced:
                    pvar = block.var(p)
                    block.create_var(
                        name=s.pname,
                        shape=(s.rows,) + tuple(pvar.shape[1:]),
                        dtype=pvar.dtype)
        block.append_op(
            "recv", {}, {"Out": [s.pname for s in self.sections]},
            {**rpc_attrs, "epmap": [s.endpoint for s in self.sections],
             "ep_groups": _ep_groups([s.pname for s in self.sections],
                                     [s.endpoint for s in self.sections])})
        if self.sync_mode:
            block.append_op("fetch_barrier", {}, {},
                            {**rpc_attrs, "endpoints": self.endpoints})

        # device: concat sections back into the parameters
        for p, secs in self.param_sections.items():
            if len(secs) == 1 or secs[0].is_table:
                continue
            block.append_op(
                "concat", {"X": [s.pname for s in secs]}, {"Out": [p]},
                {"axis": 0, OP_ROLE_ATTR: OpRole.Dist})
        return prog

    def get_trainer_startup_program(self) -> Program:
        """Trainer startup for pserver mode.

        - distributed-table init is stripped (the table lives only as
          pserver shards; a trainer must not allocate the full [V, D]
          array — the reference equivalently splices table init out);
        - current params are pulled from the pservers after local init
          (recv + concat), so a trainer joining a running or
          checkpoint-recovered cluster starts from the live state, not
          from fresh init (reference startup-program recv splicing)."""
        prog = self.startup_program.clone()
        block = prog.global_block
        if self.dist_table_ops:
            block.ops = [
                op for op in block.ops
                if not (set(op.output_arg_names()) & set(self.dist_table_ops))]
            for table in self.dist_table_ops:
                block.vars.pop(table, None)

        rpc_attrs = {"trainer_id": self.trainer_id,
                     OP_ROLE_ATTR: OpRole.RPC}
        main = self.origin_program.global_block
        for p, secs in self.param_sections.items():
            for s in secs:
                if s.is_table:
                    continue
                pvar = main.var(p)
                block.create_var(
                    name=s.pname, shape=(s.rows,) + tuple(pvar.shape[1:]),
                    dtype=pvar.dtype)
        if self.sections:
            block.append_op(
                "recv", {}, {"Out": [s.pname for s in self.sections]},
                {**rpc_attrs, "epmap": [s.endpoint for s in self.sections],
                 "ep_groups": _ep_groups([s.pname for s in self.sections],
                                         [s.endpoint for s in self.sections])})
            block.append_op("fetch_barrier", {}, {},
                            {**rpc_attrs, "endpoints": self.endpoints})
        for p, secs in self.param_sections.items():
            if len(secs) == 1 or secs[0].is_table:
                continue
            if p not in block.vars:
                pvar = main.var(p)
                block.create_var(name=p, shape=pvar.shape, dtype=pvar.dtype,
                                 persistable=True)
            block.append_op(
                "concat", {"X": [s.pname for s in secs]}, {"Out": [p]},
                {"axis": 0, OP_ROLE_ATTR: OpRole.Dist})
        return prog

    # -- pserver side ------------------------------------------------------
    def _ep_sections(self, endpoint: str) -> List[_Section]:
        return [s for s in self.sections + self.table_sections
                if s.endpoint == endpoint]

    def _acc_name(self, acc: str, sec: _Section) -> str:
        return f"{acc}@BLOCK{sec.index}" if sec.sliced else acc

    def _section_shape(self, var: Variable, sec: _Section, param_shape) -> tuple:
        if var.shape is not None and tuple(var.shape) == tuple(param_shape):
            return (sec.rows,) + tuple(var.shape[1:])
        return tuple(var.shape) if var.shape is not None else None

    def get_pserver_program(self, endpoint: str) -> Program:
        src = self.origin_program.global_block
        prog = Program()
        gb = prog.global_block

        secs = self._ep_sections(endpoint)
        opt_by_param = {op.input("Param")[0]: op for op in self.opt_ops}

        # LR vars live in block 0 of the pserver program
        persist_names: List[str] = []
        # sharded-checkpoint extent table: local persist var -> its
        # row range of the GLOBAL (topology-independent) var, so the
        # checkpoint store can re-shard state onto any other layout.
        # offset None = replicated (identical on every pserver by
        # construction: LR state, per-section scalar accumulators)
        shard_extents: Dict[str, dict] = {}

        def _replicated_extent(name: str, shape) -> None:
            shard_extents[name] = {
                "var": name, "offset": None, "rows": None,
                "global_shape": [int(s) for s in (shape or ())]}
        lr_block_idx = -1
        lr_fetch: List[str] = []
        if self.lr_ops:
            touched = set()
            for op in self.lr_ops:
                touched |= set(op.input_arg_names()) | set(op.output_arg_names())
            for n in sorted(touched):
                v = src.var_or_none(n)
                if v is not None:
                    gb.vars[n] = Variable.from_dict(gb, v.to_dict())
                    if v.persistable:
                        persist_names.append(n)
                        _replicated_extent(n, v.shape)
            with prog.block_guard() as lb:
                for op in self.lr_ops:
                    lb.ops.append(Operator(lb, op.type, op.inputs,
                                           op.outputs, dict(op.attrs)))
            lr_block_idx = lb.idx
            lr_fetch = [n for n in self.lr_names if not src.var(n).persistable]
        for n in self.lr_names:
            v = src.var(n)
            if v.persistable and n not in gb.vars:
                gb.vars[n] = Variable.from_dict(gb, v.to_dict())
                persist_names.append(n)
                _replicated_extent(n, v.shape)

        grad_to_block: Dict[str, int] = {}
        for sec in secs:
            opt_op = opt_by_param[sec.param]
            pvar = src.var(sec.param)
            gb.create_var(name=sec.pname,
                          shape=(sec.rows,) + tuple(pvar.shape[1:]),
                          dtype=pvar.dtype, persistable=True)
            persist_names.append(sec.pname)
            shard_extents[sec.pname] = {
                "var": sec.param, "offset": int(sec.offset),
                "rows": int(sec.rows),
                "global_shape": [int(s) for s in pvar.shape]}
            gvar = src.var_or_none(sec.grad)
            gshape = (sec.rows,) + tuple(pvar.shape[1:])
            gb.create_var(name=sec.gname, shape=gshape,
                          dtype=(gvar.dtype if gvar is not None else pvar.dtype))

            # clone the optimizer op onto the section, renaming param/grad/
            # accumulators (reference _append_pserver_ops)
            def rename(names: List[str]) -> List[str]:
                out = []
                for n in names:
                    if n == sec.param:
                        out.append(sec.pname)
                    elif n == sec.grad:
                        out.append(sec.gname)
                    elif n in self.lr_names:
                        out.append(n)
                    else:
                        out.append(self._acc_name(n, sec))
                        v = src.var(n)
                        nn = self._acc_name(n, sec)
                        if nn not in gb.vars:
                            gb.create_var(
                                name=nn,
                                shape=self._section_shape(v, sec, pvar.shape),
                                dtype=v.dtype, persistable=True)
                            persist_names.append(nn)
                            if v.shape is not None and \
                                    tuple(v.shape) == tuple(pvar.shape):
                                # param-shaped accumulator: rides the
                                # section's row range of the global acc
                                shard_extents[nn] = {
                                    "var": n, "offset": int(sec.offset),
                                    "rows": int(sec.rows),
                                    "global_shape": [int(s)
                                                     for s in v.shape]}
                            else:
                                # scalar/odd-shaped accumulator (e.g.
                                # beta1_pow): every section's copy
                                # evolves identically — replicated
                                _replicated_extent(nn, v.shape)
                                shard_extents[nn]["var"] = n
                return out

            with prog.block_guard() as ob:
                ins = {slot: rename(names)
                       for slot, names in opt_op.inputs.items()}
                outs = {slot: rename(names)
                        for slot, names in opt_op.outputs.items()}
                ob.ops.append(Operator(ob, opt_op.type, ins, outs,
                                       dict(opt_op.attrs)))
            grad_to_block[sec.gname] = ob.idx

        gb.append_op(
            "listen_and_serv", {}, {},
            {
                "endpoint": endpoint,
                "ps_index": self.endpoints.index(endpoint),
                "sync_mode": self.sync_mode,
                "Fanin": self.trainers,
                "grad_to_block_id": grad_to_block,
                "lr_block": lr_block_idx,
                "lr_fetch": lr_fetch,
                "dense_merge": "mean",
                "checkpoint_dir": self.config.checkpoint_dir,
                "checkpoint_every_rounds": self.config.checkpoint_every_rounds,
                "ckpt_sharded": bool(self.config.checkpoint_sharded),
                "shard_extents": (shard_extents
                                  if self.config.checkpoint_sharded else {}),
                "ckpt_writers": len(self.endpoints),
                "persist_names": sorted(set(persist_names)),
                "dist_tables": {
                    s.param: {"var": s.pname, "offset": s.offset,
                              "rows": s.rows}
                    for s in secs if s.is_table},
                "backup_endpoint": self.backup_map.get(endpoint),
                "lease_ttl": self.config.lease_ttl,
                OP_ROLE_ATTR: OpRole.RPC,
            })
        return prog

    def get_backup_program(self, endpoint: str) -> Program:
        """The HA backup replica's program for ``endpoint``: identical
        optimize blocks (replication replays applied batches through
        them, so primary and backup state evolve in lockstep), but the
        ``listen_and_serv`` binds the backup's OWN physical address,
        heartbeats as a registry standby for the primary's logical key,
        and holds back primary-only duties (checkpoints, onward
        replication) until promoted.  Initialize it with the SAME
        ``get_startup_program(endpoint)`` — bit-identical named draws
        put both replicas at the same starting state."""
        bak = self.backup_map.get(endpoint)
        if not bak:
            raise ValueError(f"no backup configured for {endpoint!r} "
                             "(DistributeTranspilerConfig.backup_endpoints)")
        prog = self.get_pserver_program(endpoint)
        for op in prog.global_block.ops:
            if op.type == "listen_and_serv":
                op.attrs["bind_endpoint"] = bak
                op.attrs["is_backup"] = True
                op.attrs["replica_id"] = 1
                op.attrs["backup_endpoint"] = None
        return prog

    def get_startup_program(self, endpoint: str) -> Program:
        """Pserver startup: initialize this endpoint's param sections (and
        accumulators / LR vars) with values identical to the local run.

        Sliced vars draw the full named init and slice out their rows, so
        the full array exists transiently *inside the startup executable*
        (freed by XLA when startup returns; steady-state holds only the
        shard).  For tables too large even for that, pre-shard offline and
        load with io.load_vars instead of initializer ops."""
        src_startup = self.startup_program.global_block
        src_main = self.origin_program.global_block
        init_by_out: Dict[str, Operator] = {}
        for op in src_startup.ops:
            for n in op.output_arg_names():
                init_by_out[n] = op

        prog = Program()
        prog.random_seed = self.startup_program.random_seed
        gb = prog.global_block
        opt_by_param = {op.input("Param")[0]: op for op in self.opt_ops}

        def clone_init(src_name: str, out_name: str, shape=None):
            """Clone the startup op initializing ``src_name``, retargeting
            output (and optionally shape) to ``out_name``."""
            op = init_by_out.get(src_name)
            if op is None:
                return
            attrs = dict(op.attrs)
            if shape is not None and "shape" in attrs:
                attrs["shape"] = list(shape)
            outs = {slot: [out_name if n == src_name else n for n in names]
                    for slot, names in op.outputs.items()}
            gb.ops.append(Operator(gb, op.type, op.inputs, outs, attrs))

        needed_lr = set(self.lr_names)
        if self.lr_ops:
            for op in self.lr_ops:
                needed_lr |= {n for n in op.input_arg_names()
                              if src_main.var_or_none(n) is not None
                              and src_main.var(n).persistable}
        for n in sorted(needed_lr):
            v = src_main.var_or_none(n)
            if v is not None and v.persistable:
                gb.vars[n] = Variable.from_dict(gb, v.to_dict())
                clone_init(n, n)

        for sec in self._ep_sections(endpoint):
            pvar = src_main.var(sec.param)
            sec_shape = (sec.rows,) + tuple(pvar.shape[1:])
            gb.create_var(name=sec.pname, shape=sec_shape, dtype=pvar.dtype,
                          persistable=True)
            if not sec.sliced:
                clone_init(sec.param, sec.pname)
            else:
                # same named draw as the local init, then slice out our rows
                full = f"{sec.param}@FULL"
                if full not in gb.vars:
                    gb.create_var(name=full, shape=pvar.shape,
                                  dtype=pvar.dtype)
                    clone_init(sec.param, full)
                gb.append_op(
                    "slice", {"Input": [full]}, {"Out": [sec.pname]},
                    {"axes": [0], "starts": [sec.offset],
                     "ends": [sec.offset + sec.rows]})

            opt_op = opt_by_param[sec.param]
            for n in set(opt_op.input_arg_names()):
                if n in (sec.param, sec.grad) or n in self.lr_names:
                    continue
                v = src_main.var(n)
                nn = self._acc_name(n, sec)
                shape = self._section_shape(v, sec, pvar.shape)
                if nn in gb.vars:
                    continue
                gb.create_var(name=nn, shape=shape, dtype=v.dtype,
                              persistable=True)
                clone_init(n, nn, shape=shape)
        return prog
