"""Elastic task master: leased data-chunk dispatch with retry + snapshot.

TPU-native redesign of the Go fault-tolerant master
(``go/master/service.go``): trainers are stateless task consumers —
``GetTask:368`` leases a chunk with a timeout (``checkTimeoutFunc:341``),
``TaskFinished:411`` retires it, ``TaskFailed:455`` requeues until
``failureMax`` (``processFailedTask:313``), and every state change is
snapshotted (``snapshot:207``) so a restarted master ``recover:166``s with
pending leases requeued.  The etcd store becomes an atomically-replaced
local snapshot file (the coordination point on a TPU pod is the shared
filesystem / the single master process, not a quorum store).

Rides the same framed-TCP transport as the pserver ops; a master is just
another ``RPCServer`` service.  The trainer-side ``task_reader`` wraps
GetTask/TaskFinished into a plain sample iterator — the role of the v2
``cloud_reader`` (``python/paddle/v2/reader/creator.py:91-109``).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import transport
from .transport import OK, RPCServer
from ..observability import health as _health
from ..observability import stats as _obs_stats
from ..observability.trace import flags_on as _telemetry_on

GET_TASK = 16
TASK_FINISHED = 17
TASK_FAILED = 18
SET_DATASET = 19
MASTER_STATE = 20

# name these in the transport's RPC counters (rpc.*.requests.get_task)
transport.MSG_NAMES.update({GET_TASK: "get_task",
                            TASK_FINISHED: "task_finished",
                            TASK_FAILED: "task_failed",
                            SET_DATASET: "set_dataset",
                            MASTER_STATE: "master_state"})


class TaskMaster:
    """Service object for an RPCServer (go/master/service.go:89)."""

    def __init__(self, snapshot_path: Optional[str] = None,
                 lease_timeout: float = 10.0, failure_max: int = 3,
                 snapshot_every: int = 1,
                 health_source: Optional[Callable[[], Dict]] = None):
        self.snapshot_path = snapshot_path
        self.lease_timeout = lease_timeout
        self.failure_max = failure_max
        # fleet-health integration (observability/health.py): a callable
        # returning {trainer_id: state}; leases owned by DEAD trainers are
        # requeued immediately instead of waiting out lease_timeout
        self.health_source = health_source
        # durability/throughput knob: snapshot every N state transitions
        # (1 = every transition, like the Go master's per-change etcd put)
        self.snapshot_every = max(1, snapshot_every)
        self._transitions = 0
        self.lock = threading.Lock()
        self.todo: deque = deque()          # [task dict]
        self.pending: Dict[int, dict] = {}  # id -> {task, deadline, owner}
        self.done: List[int] = []
        self.failures: Dict[int, int] = {}
        self.discarded: List[int] = []
        self.next_id = 0
        self.pass_id = 0
        self._pass_rolled = True  # no pass in flight yet
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- persistence (service.go:207 snapshot / :166 recover) --------------
    def _snapshot(self, force: bool = False) -> None:
        if not self.snapshot_path:
            return
        self._transitions += 1
        if not force and self._transitions % self.snapshot_every:
            return
        state = {
            "todo": list(self.todo),
            "pending": [e["task"] for e in self.pending.values()],
            "done": self.done,
            "failures": {str(k): v for k, v in self.failures.items()},
            "discarded": self.discarded,
            "next_id": self.next_id,
            "pass_id": self.pass_id,
            "pass_rolled": self._pass_rolled,
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)  # atomic like the etcd put

    def _recover(self) -> None:
        with open(self.snapshot_path) as f:
            state = json.load(f)
        # leases die with the old master: pending goes back to todo
        self.todo = deque(state["todo"] + state["pending"])
        self.done = state["done"]
        self.failures = {int(k): v for k, v in state["failures"].items()}
        self.discarded = state.get("discarded", [])
        self.next_id = state["next_id"]
        self.pass_id = state.get("pass_id", 0)
        self._pass_rolled = state.get("pass_rolled", not (self.todo or self.pending))

    # -- core ops (locked) -------------------------------------------------
    def set_dataset(self, chunks: List) -> None:
        """Partition a chunk list into tasks (service.go:280 SetDataset +
        partition:106).  Idempotent while a pass is in flight; starting a
        new pass prunes the previous pass's bookkeeping."""
        with self.lock:
            if self.todo or self.pending:
                return
            self.done.clear()
            self.failures.clear()
            self.discarded.clear()
            self._pass_rolled = False
            for payload in chunks:
                self.todo.append({"id": self.next_id, "payload": payload,
                                  "pass": self.pass_id})
                self.next_id += 1
            self._snapshot(force=True)

    def set_health_source(self, fn: Optional[Callable[[], Dict]]) -> None:
        self.health_source = fn

    def _dead_owners(self) -> set:
        if self.health_source is None:
            return set()
        try:
            states = self.health_source() or {}
        except Exception:
            return set()       # health plane down ≠ workers dead
        return {owner for owner, state in states.items()
                if state == _health.DEAD}

    def _requeue_expired(self) -> None:
        now = time.monotonic()
        dead = self._dead_owners()
        expired = [tid for tid, e in self.pending.items()
                   if e["deadline"] <= now or e["owner"] in dead]
        n_dead = sum(1 for tid in expired
                     if self.pending[tid]["owner"] in dead
                     and self.pending[tid]["deadline"] > now)
        if n_dead:
            if _telemetry_on():
                # leases reclaimed EARLY because the health registry
                # declared the owner DEAD (vs. riding out lease_timeout)
                _obs_stats.counter("master.dead_requeues").inc(n_dead)
            # post-mortem breadcrumb: which trainers' work got reclaimed
            from ..observability import flight as _flight
            _flight.note("master_dead_requeue", n=n_dead,
                         owners=sorted({self.pending[tid]["owner"]
                                        for tid in expired
                                        if self.pending[tid]["owner"]
                                        in dead}))
        for tid in expired:
            task = self.pending.pop(tid)["task"]
            self._note_failure(task)

    def _note_failure(self, task: dict) -> None:
        tid = task["id"]
        self.failures[tid] = self.failures.get(tid, 0) + 1
        if self.failures[tid] > self.failure_max:
            self.discarded.append(tid)  # service.go:313 processFailedTask
        else:
            self.todo.append(task)

    def get_task(self, owner: int) -> Optional[dict]:
        with self.lock:
            self._requeue_expired()
            if not self.todo:
                if not self.pending and not self._pass_rolled:
                    self.pass_id += 1  # pass finished (rolls over once)
                    self._pass_rolled = True
                    self._snapshot(force=True)
                return None
            task = self.todo.popleft()
            self.pending[task["id"]] = {
                "task": task, "owner": owner,
                "deadline": time.monotonic() + self.lease_timeout}
            self._snapshot()
            return task

    def task_finished(self, task_id: int) -> None:
        with self.lock:
            if task_id in self.pending:
                self.pending.pop(task_id)
                self.done.append(task_id)
                self.failures.pop(task_id, None)
                self._snapshot()

    def task_failed(self, task_id: int) -> None:
        with self.lock:
            entry = self.pending.pop(task_id, None)
            if entry is not None:
                self._note_failure(entry["task"])
                self._snapshot()

    def state(self) -> dict:
        with self.lock:
            self._requeue_expired()
            return {"todo": len(self.todo), "pending": len(self.pending),
                    "done": sorted(self.done),
                    "discarded": sorted(self.discarded),
                    "pass_id": self.pass_id}

    # -- transport glue ----------------------------------------------------
    def handle(self, msg_type, trainer_id, name, payload):
        if msg_type == GET_TASK:
            task = self.get_task(trainer_id)
            return OK, json.dumps(task).encode("utf-8")
        if msg_type == TASK_FINISHED:
            self.task_finished(int(name))
            return OK, b""
        if msg_type == TASK_FAILED:
            self.task_failed(int(name))
            return OK, b""
        if msg_type == SET_DATASET:
            self.set_dataset(json.loads(bytes(payload).decode("utf-8")))
            return OK, b""
        if msg_type == MASTER_STATE:
            return OK, json.dumps(self.state()).encode("utf-8")
        raise ValueError(f"unknown master message type {msg_type}")


def registry_health_source(registry_ep: str, trainer_id: int = 0,
                           cache_ttl: float = 5.0) -> Callable[[], Dict]:
    """Health source for a TaskMaster: pulls the discovery registry's
    REG_HEALTH table and maps it to {trainer_id: state}.  Cached for
    ``cache_ttl`` so the master's hot path (every get_task holds the
    lock through ``_requeue_expired``) does at most one RPC per ttl.

    Only ``role == "TRAINER"`` heartbeats map to lease owners: pserver
    Heartbeats (ps_ops) carry the default RPC-client trainer_id of 0,
    and a dead *pserver* must not get healthy trainer 0's leases
    reclaimed and its tasks failure-counted toward discard."""
    from . import registry as _registry_mod
    client = transport.RPCClient(trainer_id)
    cache = {"t": float("-inf"), "val": {}}

    def source() -> Dict[int, str]:
        now = time.monotonic()
        if now - cache["t"] >= cache_ttl:
            # stamp BEFORE the fetch: while the registry is unreachable
            # the connect stall must happen at most once per cache_ttl,
            # not on every get_task under the master lock (the stale
            # table keeps serving in between).  The stall bound is kept
            # BELOW cache_ttl so back-to-back refreshes cannot chain —
            # worst case the lock loses stall/cache_ttl of its duty
            # cycle to a black-holed registry, not all of it.
            cache["t"] = now
            snap = _registry_mod.fetch_health(
                client, registry_ep,
                connect_timeout=min(2.0, max(0.5, cache_ttl / 2.0)))
            cache["val"] = {info["trainer_id"]: info["state"]
                            for info in snap.values()
                            if info.get("trainer_id") is not None
                            and info.get("role") == "TRAINER"}
        return cache["val"]

    return source


def serve_master(endpoint: str, snapshot_path: Optional[str] = None,
                 lease_timeout: float = 10.0, failure_max: int = 3,
                 health_source: Optional[Callable[[], Dict]] = None):
    """Start a master service; returns (master, server) — call
    ``server.stop()`` to kill it (tests simulate master failure this way)."""
    master = TaskMaster(snapshot_path, lease_timeout, failure_max,
                        health_source=health_source)
    server = RPCServer(endpoint, master)
    # /statusz shows this process's queue depths when it hosts a master;
    # the provider is keyed by port (a failover test can host two
    # masters in one process) and torn down with the server, so a
    # stopped master is neither kept alive nor still reported
    from ..observability import debug_server as _debug_server
    provider_key = f"master:{server.port}"
    _debug_server.register_provider(provider_key, master.state)
    impl_stop = server.stop

    def stop_and_unregister():
        _debug_server.unregister_provider(provider_key)
        impl_stop()

    server.stop = stop_and_unregister
    server.start()
    return master, server


class MasterClient:
    """Trainer-side master client (go/master/client.go + c bindings)."""

    def __init__(self, endpoint: str, trainer_id: int = 0):
        self.endpoint = endpoint
        self._rpc = transport.get_client(trainer_id)

    def set_dataset(self, chunks: List) -> None:
        self._rpc._request(self.endpoint, SET_DATASET,
                           payload=json.dumps(chunks).encode("utf-8"))

    def get_task(self) -> Optional[dict]:
        out = self._rpc._request(self.endpoint, GET_TASK)
        return json.loads(bytes(out).decode("utf-8"))

    def task_finished(self, task_id: int) -> None:
        self._rpc._request(self.endpoint, TASK_FINISHED, str(task_id))

    def task_failed(self, task_id: int) -> None:
        self._rpc._request(self.endpoint, TASK_FAILED, str(task_id))

    def state(self) -> dict:
        out = self._rpc._request(self.endpoint, MASTER_STATE)
        return json.loads(bytes(out).decode("utf-8"))


def task_reader(client: MasterClient, make_reader: Callable,
                poll_interval: float = 0.2):
    """Sample iterator over master-leased tasks (cloud_reader analogue:
    python/paddle/v2/reader/creator.py:91-109).  ``make_reader(payload)``
    yields the samples of one chunk.  Stops when the pass is exhausted;
    a chunk whose reader raises is reported failed (and will be retried
    by another consumer) before the error propagates."""
    while True:
        task = client.get_task()
        if task is None:
            # distinguish "pass done" from "all chunks leased elsewhere"
            st = client.state()
            if st["pending"] == 0 and st["todo"] == 0:
                return
            time.sleep(poll_interval)
            continue
        try:
            yield from make_reader(task["payload"])
        except Exception:
            client.task_failed(task["id"])
            raise
        client.task_finished(task["id"])
