"""Elastic task master: leased data-chunk dispatch with retry + snapshot.

TPU-native redesign of the Go fault-tolerant master
(``go/master/service.go``): trainers are stateless task consumers —
``GetTask:368`` leases a chunk with a timeout (``checkTimeoutFunc:341``),
``TaskFinished:411`` retires it, ``TaskFailed:455`` requeues until
``failureMax`` (``processFailedTask:313``), and every state change is
snapshotted (``snapshot:207``) so a restarted master ``recover:166``s with
pending leases requeued.  The etcd store becomes an atomically-replaced
local snapshot file (the coordination point on a TPU pod is the shared
filesystem / the single master process, not a quorum store).

Rides the same framed-TCP transport as the pserver ops; a master is just
another ``RPCServer`` service.  The trainer-side ``task_reader`` wraps
GetTask/TaskFinished into a plain sample iterator — the role of the v2
``cloud_reader`` (``python/paddle/v2/reader/creator.py:91-109``).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import transport
from .transport import OK, RPCServer
from ..observability import health as _health
from ..observability import stats as _obs_stats
from ..observability.trace import flags_on as _telemetry_on

CKPT_CUT = 15
GET_TASK = 16
TASK_FINISHED = 17
TASK_FAILED = 18
SET_DATASET = 19
MASTER_STATE = 20

# name these in the transport's RPC counters (rpc.*.requests.get_task)
transport.MSG_NAMES.update({CKPT_CUT: "ckpt_cut",
                            GET_TASK: "get_task",
                            TASK_FINISHED: "task_finished",
                            TASK_FAILED: "task_failed",
                            SET_DATASET: "set_dataset",
                            MASTER_STATE: "master_state"})


class TaskMaster:
    """Service object for an RPCServer (go/master/service.go:89)."""

    def __init__(self, snapshot_path: Optional[str] = None,
                 lease_timeout: float = 10.0, failure_max: int = 3,
                 snapshot_every: int = 1,
                 health_source: Optional[Callable[[], Dict]] = None,
                 publish_fn: Optional[Callable[[dict], None]] = None,
                 leader: bool = True):
        self.snapshot_path = snapshot_path
        self.lease_timeout = lease_timeout
        self.failure_max = failure_max
        # HA: ``publish_fn(state)`` mirrors every snapshotted transition
        # into the registry (the per-change etcd put); ``leader=False``
        # starts the master as a STANDBY that mirrors but refuses task
        # ops until promoted (serve_master_ha flips it)
        self.publish_fn = publish_fn
        self.leader = leader
        # fleet-health integration (observability/health.py): a callable
        # returning {trainer_id: state}; leases owned by DEAD trainers are
        # requeued immediately instead of waiting out lease_timeout
        self.health_source = health_source
        # durability/throughput knob: snapshot every N state transitions
        # (1 = every transition, like the Go master's per-change etcd put)
        self.snapshot_every = max(1, snapshot_every)
        self._transitions = 0
        self.lock = threading.Lock()
        # publish staging: _snapshot (called with self.lock held) only
        # STASHES the state; the registry RPC happens in _flush_publish
        # AFTER the lock is released, so a slow registry can never stall
        # the task-handout plane.  _pub_pending always holds the NEWEST
        # full table, so a racing later flush covers an earlier one.
        self._pub_lock = threading.Lock()
        self._pub_pending: Optional[dict] = None
        self._pub_seq = -1
        self.todo: deque = deque()          # [task dict]
        self.pending: Dict[int, dict] = {}  # id -> {task, deadline, owner}
        self.done: List[int] = []
        self.failures: Dict[int, int] = {}
        self.discarded: List[int] = []
        self.next_id = 0
        self.pass_id = 0
        self._pass_rolled = True  # no pass in flight yet
        # fleet checkpoint cut: the last stamped (step, root) — rides
        # every snapshot/publish so standby mirrors, a restarted master
        # and late joiners all agree which step the fleet cut at
        self.ckpt_cut: Optional[dict] = None
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- persistence (service.go:207 snapshot / :166 recover) --------------
    def _state_dict(self) -> dict:
        """Serialized task/lease table (call with self.lock held).
        ``pending`` keeps each lease's OWNER so a standby mirror can
        re-issue the exact lease table on takeover; ``seq`` orders
        mirrors (a stale publish must never overwrite a newer one)."""
        return {
            "todo": list(self.todo),
            "pending": [{"task": e["task"], "owner": e["owner"]}
                        for e in self.pending.values()],
            "done": list(self.done),
            "failures": {str(k): v for k, v in self.failures.items()},
            "discarded": list(self.discarded),
            "next_id": self.next_id,
            "pass_id": self.pass_id,
            "pass_rolled": self._pass_rolled,
            "ckpt_cut": self.ckpt_cut,
            "seq": self._transitions,
        }

    def _snapshot(self, force: bool = False) -> None:
        if not self.snapshot_path and self.publish_fn is None:
            return
        self._transitions += 1
        if not force and self._transitions % self.snapshot_every:
            return
        state = self._state_dict()
        if self.snapshot_path:
            tmp = self.snapshot_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, self.snapshot_path)  # atomic like the etcd put
        if self.publish_fn is not None:
            self._pub_pending = state   # flushed after the lock drops

    def _flush_publish(self) -> None:
        """Mirror the latest stashed state into the registry — called by
        every task op AFTER releasing self.lock (the per-change etcd put,
        off the hot path).  An op returns only once a table containing
        its transition has been published (by itself or by a racing
        later flush, whose state supersedes ours)."""
        if self.publish_fn is None or self._pub_pending is None:
            return
        with self._pub_lock:
            with self.lock:
                state, self._pub_pending = self._pub_pending, None
                publish = self.publish_fn
            if state is None or publish is None:
                return
            if state["seq"] <= self._pub_seq:
                return               # a newer table already went out
            try:
                publish(state)
                self._pub_seq = state["seq"]
            except Exception as e:
                # a briefly-unreachable registry must not fail task ops;
                # the NEXT transition re-publishes the whole table
                from ..observability import flight as _flight
                _flight.note("master_publish_failed", error=repr(e)[:200],
                             seq=state["seq"])

    @staticmethod
    def _pending_tasks(state: dict) -> List[dict]:
        """Tasks of the serialized pending list — entries are rich
        ({"task","owner"}) since the HA mirror, bare task dicts before."""
        return [e["task"] if isinstance(e, dict) and "task" in e else e
                for e in state.get("pending", [])]

    def _recover(self) -> None:
        with open(self.snapshot_path) as f:
            state = json.load(f)
        # leases die with the old master: pending goes back to todo
        self.todo = deque(state["todo"] + self._pending_tasks(state))
        self.done = state["done"]
        self.failures = {int(k): v for k, v in state["failures"].items()}
        self.discarded = state.get("discarded", [])
        self.next_id = state["next_id"]
        self.pass_id = state.get("pass_id", 0)
        self._pass_rolled = state.get("pass_rolled", not (self.todo or self.pending))
        self.ckpt_cut = state.get("ckpt_cut")

    # -- HA standby mirror / takeover --------------------------------------
    def adopt_state(self, state: dict, takeover: bool = False) -> bool:
        """Load a mirrored lease table (REG_SNAPSHOT watch replay).

        While STANDBY this runs repeatedly — newest seq wins, so the
        mirror is always one publish behind the leader at worst.  On
        ``takeover`` the outstanding leases are RE-ISSUED idempotently:
        each stays pending under its original owner with a fresh
        deadline (deadlines are local monotonic clocks and died with
        the old leader) — never requeued, so no task is double-granted
        while its trainer still works it, and never dropped, so a
        finished/timed-out lease still resolves exactly once."""
        with self.lock:
            seq = int(state.get("seq", 0))
            if not takeover and seq <= self._transitions:
                return False      # stale mirror: keep the newer table
            now = time.monotonic()
            self.todo = deque(state.get("todo", []))
            self.pending = {}
            for e in state.get("pending", []):
                task = e["task"] if isinstance(e, dict) and "task" in e else e
                owner = e.get("owner", -1) if isinstance(e, dict) else -1
                self.pending[task["id"]] = {
                    "task": task, "owner": owner,
                    "deadline": now + self.lease_timeout}
            self.done = list(state.get("done", []))
            self.failures = {int(k): v
                             for k, v in state.get("failures", {}).items()}
            self.discarded = list(state.get("discarded", []))
            self.next_id = int(state.get("next_id", 0))
            self.pass_id = int(state.get("pass_id", 0))
            self._pass_rolled = bool(state.get(
                "pass_rolled", not (self.todo or self.pending)))
            self.ckpt_cut = state.get("ckpt_cut")
            self._transitions = seq
            return True

    # -- core ops (locked) -------------------------------------------------
    def set_dataset(self, chunks: List) -> None:
        """Partition a chunk list into tasks (service.go:280 SetDataset +
        partition:106).  Idempotent while a pass is in flight; starting a
        new pass prunes the previous pass's bookkeeping."""
        try:
            with self.lock:
                if self.todo or self.pending:
                    return
                self.done.clear()
                self.failures.clear()
                self.discarded.clear()
                self._pass_rolled = False
                for payload in chunks:
                    self.todo.append({"id": self.next_id,
                                      "payload": payload,
                                      "pass": self.pass_id})
                    self.next_id += 1
                self._snapshot(force=True)
        finally:
            self._flush_publish()

    def set_health_source(self, fn: Optional[Callable[[], Dict]]) -> None:
        self.health_source = fn

    def _dead_owners(self) -> set:
        if self.health_source is None:
            return set()
        try:
            states = self.health_source() or {}
        except Exception:
            return set()       # health plane down ≠ workers dead
        return {owner for owner, state in states.items()
                if state == _health.DEAD}

    def _requeue_expired(self) -> None:
        now = time.monotonic()
        dead = self._dead_owners()
        expired = [tid for tid, e in self.pending.items()
                   if e["deadline"] <= now or e["owner"] in dead]
        n_dead = sum(1 for tid in expired
                     if self.pending[tid]["owner"] in dead
                     and self.pending[tid]["deadline"] > now)
        if n_dead:
            if _telemetry_on():
                # leases reclaimed EARLY because the health registry
                # declared the owner DEAD (vs. riding out lease_timeout)
                _obs_stats.counter("master.dead_requeues").inc(n_dead)
            # post-mortem breadcrumb: which trainers' work got reclaimed
            from ..observability import flight as _flight
            _flight.note("master_dead_requeue", n=n_dead,
                         owners=sorted({self.pending[tid]["owner"]
                                        for tid in expired
                                        if self.pending[tid]["owner"]
                                        in dead}))
        for tid in expired:
            task = self.pending.pop(tid)["task"]
            self._note_failure(task)

    def _note_failure(self, task: dict) -> None:
        tid = task["id"]
        self.failures[tid] = self.failures.get(tid, 0) + 1
        if self.failures[tid] > self.failure_max:
            self.discarded.append(tid)  # service.go:313 processFailedTask
        else:
            self.todo.append(task)

    def get_task(self, owner: int) -> Optional[dict]:
        try:
            with self.lock:
                self._requeue_expired()
                if not self.todo:
                    if not self.pending and not self._pass_rolled:
                        self.pass_id += 1  # pass finished (rolls once)
                        self._pass_rolled = True
                        self._snapshot(force=True)
                    return None
                task = self.todo.popleft()
                self.pending[task["id"]] = {
                    "task": task, "owner": owner,
                    "deadline": time.monotonic() + self.lease_timeout}
                # chaos injection point: kill_after:lease_grant dies
                # HERE — lease recorded in memory only, neither
                # published nor answered (the mid-handout window the HA
                # tests verify)
                from . import faults as _faults
                _faults.event("lease_grant")
                self._snapshot()
                return task
        finally:
            self._flush_publish()

    def task_finished(self, task_id: int) -> None:
        try:
            with self.lock:
                if task_id in self.pending:
                    self.pending.pop(task_id)
                    self.done.append(task_id)
                    self.failures.pop(task_id, None)
                    self._snapshot()
        finally:
            self._flush_publish()

    def task_failed(self, task_id: int) -> None:
        try:
            with self.lock:
                entry = self.pending.pop(task_id, None)
                if entry is not None:
                    self._note_failure(entry["task"])
                    self._snapshot()
        finally:
            self._flush_publish()

    def stamp_checkpoint(self, step: int, root: Optional[str] = None,
                         meta: Optional[dict] = None) -> dict:
        """Stamp the fleet's checkpoint cut: 'the consistent snapshot
        of this job is step ``step`` under ``root``'.  Rides the normal
        snapshot/publish path, so the stamp survives master failover
        (standby mirrors carry it) and restart (snapshot file), and a
        joining worker can ask any master replica which step to hydrate
        from instead of guessing from the filesystem."""
        try:
            with self.lock:
                self.ckpt_cut = {"step": int(step), "root": root,
                                 **(meta or {})}
                self._snapshot(force=True)
                cut = dict(self.ckpt_cut)
        finally:
            self._flush_publish()
        if _telemetry_on():
            _obs_stats.counter(
                "master.ckpt_cuts",
                "fleet checkpoint cuts stamped through the master's "
                "snapshot/publish path").inc()
        return cut

    def checkpoint_cut(self) -> Optional[dict]:
        with self.lock:
            return dict(self.ckpt_cut) if self.ckpt_cut else None

    def state(self) -> dict:
        with self.lock:
            self._requeue_expired()
            return {"todo": len(self.todo), "pending": len(self.pending),
                    "done": sorted(self.done),
                    "discarded": sorted(self.discarded),
                    "pass_id": self.pass_id,
                    "ckpt_cut": (dict(self.ckpt_cut)
                                 if self.ckpt_cut else None)}

    # -- transport glue ----------------------------------------------------
    def handle(self, msg_type, trainer_id, name, payload):
        if not self.leader and msg_type in (GET_TASK, TASK_FINISHED,
                                            TASK_FAILED, SET_DATASET,
                                            CKPT_CUT):
            # a STANDBY mirrors but must not act: granting from the
            # mirror while the leader lives would double-grant.  Only
            # the registry's promotion (serve_master_ha) flips this.
            return transport.ERR, b"master standby: not the leader"
        if msg_type == GET_TASK:
            task = self.get_task(trainer_id)
            return OK, json.dumps(task).encode("utf-8")
        if msg_type == TASK_FINISHED:
            self.task_finished(int(name))
            return OK, b""
        if msg_type == TASK_FAILED:
            self.task_failed(int(name))
            return OK, b""
        if msg_type == SET_DATASET:
            self.set_dataset(json.loads(bytes(payload).decode("utf-8")))
            return OK, b""
        if msg_type == MASTER_STATE:
            return OK, json.dumps(self.state()).encode("utf-8")
        if msg_type == CKPT_CUT:
            info = json.loads(bytes(payload).decode("utf-8")) \
                if payload else {}
            cut = self.stamp_checkpoint(int(name), info.pop("root", None),
                                        meta=info or None)
            return OK, json.dumps(cut).encode("utf-8")
        raise ValueError(f"unknown master message type {msg_type}")


def registry_health_source(registry_ep: str, trainer_id: int = 0,
                           cache_ttl: float = 5.0) -> Callable[[], Dict]:
    """Health source for a TaskMaster: pulls the discovery registry's
    REG_HEALTH table and maps it to {trainer_id: state}.  Cached for
    ``cache_ttl`` so the master's hot path (every get_task holds the
    lock through ``_requeue_expired``) does at most one RPC per ttl.

    Only ``role == "TRAINER"`` heartbeats map to lease owners: pserver
    Heartbeats (ps_ops) carry the default RPC-client trainer_id of 0,
    and a dead *pserver* must not get healthy trainer 0's leases
    reclaimed and its tasks failure-counted toward discard."""
    from . import registry as _registry_mod
    client = transport.RPCClient(trainer_id)
    cache = {"t": float("-inf"), "val": {}}

    def source() -> Dict[int, str]:
        now = time.monotonic()
        if now - cache["t"] >= cache_ttl:
            # stamp BEFORE the fetch: while the registry is unreachable
            # the connect stall must happen at most once per cache_ttl,
            # not on every get_task under the master lock (the stale
            # table keeps serving in between).  The stall bound is kept
            # BELOW cache_ttl so back-to-back refreshes cannot chain —
            # worst case the lock loses stall/cache_ttl of its duty
            # cycle to a black-holed registry, not all of it.
            cache["t"] = now
            snap = _registry_mod.fetch_health(
                client, registry_ep,
                connect_timeout=min(2.0, max(0.5, cache_ttl / 2.0)))
            cache["val"] = {info["trainer_id"]: info["state"]
                            for info in snap.values()
                            if info.get("trainer_id") is not None
                            and info.get("role") == "TRAINER"}
        return cache["val"]

    return source


def serve_master(endpoint: str, snapshot_path: Optional[str] = None,
                 lease_timeout: float = 10.0, failure_max: int = 3,
                 health_source: Optional[Callable[[], Dict]] = None):
    """Start a master service; returns (master, server) — call
    ``server.stop()`` to kill it (tests simulate master failure this way)."""
    master = TaskMaster(snapshot_path, lease_timeout, failure_max,
                        health_source=health_source)
    server = RPCServer(endpoint, master)
    # /statusz shows this process's queue depths when it hosts a master;
    # the provider is keyed by port (a failover test can host two
    # masters in one process) and torn down with the server, so a
    # stopped master is neither kept alive nor still reported
    from ..observability import debug_server as _debug_server
    provider_key = f"master:{server.port}"
    _debug_server.register_provider(provider_key, master.state)
    impl_stop = server.stop

    def stop_and_unregister():
        _debug_server.unregister_provider(provider_key)
        impl_stop()

    server.stop = stop_and_unregister
    server.start()
    return master, server


MASTER_LOGICAL = "__master__"


class HAMaster:
    """One master CANDIDATE in the HA control plane (use
    :func:`serve_master_ha`).

    Election rides the registry's standby machinery
    (``distributed/registry.py`` "HA layer"): every candidate heartbeats
    the shared logical key ``__master__`` with ``standby=<candidate_id>,
    elect=True`` — the first candidate up wins the initial election, and
    on the leader's lease expiry the lowest-id live standby is promoted.
    The LEADER publishes its task/lease table into the registry on every
    snapshotted transition (``TaskMaster.publish_fn`` — the per-change
    etcd put of go/master/service.go:207); STANDBYS mirror it via
    REG_SNAPSHOT watch replay (newest seq wins) and refuse task ops.
    On promotion the new leader re-issues the mirrored in-flight leases
    idempotently (``adopt_state(takeover=True)``): same task, same
    owner, fresh deadline — no double-grant, no orphan — and trainers
    re-resolve ``__master__`` through their normal failover path.
    """

    def __init__(self, endpoint: str, registry_ep: str, candidate_id: int,
                 logical: str = MASTER_LOGICAL,
                 snapshot_path: Optional[str] = None,
                 lease_timeout: float = 10.0, failure_max: int = 3,
                 snapshot_every: int = 1,
                 lease_ttl: Optional[float] = None,
                 health_source: Optional[Callable[[], Dict]] = None):
        from . import registry as _registry_mod
        self._registry_mod = _registry_mod
        self.logical = logical
        self.registry_ep = registry_ep
        self.candidate_id = int(candidate_id)
        self._client = transport.RPCClient(0)
        self.master = TaskMaster(snapshot_path, lease_timeout, failure_max,
                                 snapshot_every=snapshot_every,
                                 health_source=health_source, leader=False)
        self.server = RPCServer(endpoint, self.master)
        from ..observability import debug_server as _debug_server
        self._provider_key = f"master:{self.server.port}"
        _debug_server.register_provider(
            self._provider_key,
            lambda: {**self.master.state(),
                     "leader": self.master.leader,
                     "candidate_id": self.candidate_id})
        self.server.start()
        host = endpoint.rsplit(":", 1)[0]
        self.physical = f"{host}:{self.server.port}"
        self._stop_evt = threading.Event()
        self.heartbeat = _registry_mod.Heartbeat(
            registry_ep, logical, self.physical,
            ttl=lease_ttl or _registry_mod.DEFAULT_TTL, role="MASTER",
            standby=self.candidate_id, elect=True,
            on_promote=self._takeover, on_demote=self._step_down)
        # may promote synchronously (first candidate up leads)
        self.heartbeat.start()
        self._watcher = threading.Thread(
            target=self._mirror_loop, daemon=True,
            name=f"master-mirror-{self.candidate_id}")
        self._watcher.start()

    @property
    def is_leader(self) -> bool:
        return self.master.leader

    def _publish(self, state: dict) -> None:
        self._registry_mod.publish_data(self._client, self.registry_ep,
                                        self.logical, state)

    def _pull_mirror(self) -> Optional[dict]:
        snap = self._registry_mod.fetch_snapshot(
            self._client, self.registry_ep,
            connect_timeout=min(2.0, self.heartbeat.ttl))
        return (snap.get("data") or {}).get(self.logical)

    def _takeover(self) -> None:
        """Registry promoted this candidate: adopt the newest mirrored
        lease table and start leading (+ publishing)."""
        from ..observability import flight as _flight
        try:
            data = self._pull_mirror()
            if data:
                self.master.adopt_state(data, takeover=True)
        except Exception as e:
            # lead from the last WATCHED mirror: strictly no worse than
            # the old master dying with an unreachable registry
            _flight.note("master_takeover_mirror_pull_failed",
                         error=repr(e)[:200])
        self.master.publish_fn = self._publish
        self.master._pub_seq = -1   # fresh leadership: no stale guard
        self.master.leader = True
        if _telemetry_on():
            _obs_stats.counter(
                "master.takeovers",
                "standby masters promoted to leader").inc()
        st = self.master.state()
        _flight.note("master_takeover", candidate=self.candidate_id,
                     physical=self.physical, pending=st["pending"],
                     todo=st["todo"])
        # republish immediately so the NEXT standby mirrors the adopted
        # table (seq re-stamped under our leadership)
        with self.master.lock:
            self.master._snapshot(force=True)
        self.master._flush_publish()

    def _step_down(self) -> None:
        """The registry FENCED this leader's claim: a standby was
        promoted over it while it was partitioned/away.  A deposed
        leader must stop granting immediately — trainers whose TCP
        connection to it never failed would otherwise keep drawing
        leases from the stale table while the new leader re-issues the
        same ones (double-grant).  Flip back to standby duty: refuse
        task ops, stop publishing (our mirror would clobber the new
        leader's), re-file candidacy, and resume mirroring."""
        from ..observability import flight as _flight
        with self.master.lock:
            self.master.leader = False
            self.master.publish_fn = None
            self.master._pub_pending = None
        if _telemetry_on():
            _obs_stats.counter(
                "master.stepdowns",
                "deposed leaders that stepped back to standby after "
                "the registry fenced their claim").inc()
        _flight.note("master_step_down", candidate=self.candidate_id,
                     physical=self.physical)
        # resume candidacy + watch replay (the heartbeat thread is the
        # caller, so candidacy resumes on its next refresh)
        self.heartbeat.promoted = False
        self.heartbeat._demoted = False   # re-arm: fences can recur
        if not self._watcher.is_alive():
            self._watcher = threading.Thread(
                target=self._mirror_loop, daemon=True,
                name=f"master-mirror-{self.candidate_id}")
            self._watcher.start()

    def _mirror_loop(self) -> None:
        """Standby watch replay: poll REG_SNAPSHOT until promoted."""
        period = max(0.1, min(1.0, self.heartbeat.ttl / 2.0))
        while not self._stop_evt.wait(period):
            if self.master.leader:
                return            # mirroring duty ends at promotion
            try:
                data = self._pull_mirror()
                if data:
                    self.master.adopt_state(data)
            except Exception:
                pass              # registry briefly down: keep trying

    def stop(self, bye: bool = True) -> None:
        from ..observability import debug_server as _debug_server
        self._stop_evt.set()
        self.heartbeat.stop(bye=bye)
        _debug_server.unregister_provider(self._provider_key)
        self.server.stop()


def serve_master_ha(endpoint: str, registry_ep: str, candidate_id: int,
                    **kwargs) -> HAMaster:
    """Start one HA master candidate (see :class:`HAMaster`).  Start
    several with distinct ``candidate_id``s for a leader + standbys;
    trainers point their :class:`MasterClient` at the LOGICAL key
    ``MASTER_LOGICAL`` with the registry configured and follow the
    leader through promotions via the normal failover path."""
    return HAMaster(endpoint, registry_ep, candidate_id, **kwargs)


class MasterClient:
    """Trainer-side master client (go/master/client.go + c bindings).

    Point ``endpoint`` at :data:`MASTER_LOGICAL` with a registry
    (``registry_ep`` or ``FLAGS_pserver_registry``) to follow an HA
    master fleet through promotions: connection failures re-resolve the
    logical key (the promoted standby), and the short window where the
    freshly-promoted master has not yet learned of its promotion (its
    next lease refresh delivers the news) is absorbed by a bounded
    retry on the standby's "not the leader" refusal."""

    # how long to ride out the promotion-notification window before
    # surfacing "not the leader" — a few lease terms on any sane config
    NOT_LEADER_GRACE_S = 30.0

    def __init__(self, endpoint: str, trainer_id: int = 0,
                 registry_ep: Optional[str] = None):
        self.endpoint = endpoint
        if registry_ep is not None:
            self._rpc = transport.RPCClient(trainer_id)
            self._rpc.set_registry(registry_ep)
        else:
            self._rpc = transport.get_client(trainer_id)

    def _request(self, msg_type: int, name: str = "", payload=b""):
        deadline = time.monotonic() + self.NOT_LEADER_GRACE_S
        while True:
            try:
                return self._rpc._request(self.endpoint, msg_type, name,
                                          payload)
            except RuntimeError as e:
                if "not the leader" not in str(e) \
                        or time.monotonic() > deadline:
                    raise
                # a standby answered: promotion is in flight (the
                # registry routed us here, so it IS the winner — it
                # just hasn't heard yet).  Brief poll, then retry.
                time.sleep(0.2)

    def set_dataset(self, chunks: List) -> None:
        self._request(SET_DATASET,
                      payload=json.dumps(chunks).encode("utf-8"))

    def get_task(self) -> Optional[dict]:
        out = self._request(GET_TASK)
        return json.loads(bytes(out).decode("utf-8"))

    def task_finished(self, task_id: int) -> None:
        self._request(TASK_FINISHED, str(task_id))

    def task_failed(self, task_id: int) -> None:
        self._request(TASK_FAILED, str(task_id))

    def stamp_checkpoint(self, step: int, root: Optional[str] = None,
                         meta: Optional[dict] = None) -> dict:
        """Stamp the fleet checkpoint cut at the (leader) master; the
        stamp is published/mirrored like every lease-table transition."""
        payload = dict(meta or {})
        if root is not None:
            payload["root"] = root
        out = self._request(CKPT_CUT, str(int(step)),
                            json.dumps(payload).encode("utf-8"))
        return json.loads(bytes(out).decode("utf-8"))

    def checkpoint_cut(self) -> Optional[dict]:
        """The fleet's last stamped cut ({"step", "root", ...}) or None."""
        return self.state().get("ckpt_cut")

    def state(self) -> dict:
        out = self._request(MASTER_STATE)
        return json.loads(bytes(out).decode("utf-8"))


def task_reader(client: MasterClient, make_reader: Callable,
                poll_interval: float = 0.2):
    """Sample iterator over master-leased tasks (cloud_reader analogue:
    python/paddle/v2/reader/creator.py:91-109).  ``make_reader(payload)``
    yields the samples of one chunk.  Stops when the pass is exhausted;
    a chunk whose reader raises is reported failed (and will be retried
    by another consumer) before the error propagates."""
    while True:
        task = client.get_task()
        if task is None:
            # distinguish "pass done" from "all chunks leased elsewhere"
            st = client.state()
            if st["pending"] == 0 and st["todo"] == 0:
                return
            time.sleep(poll_interval)
            continue
        try:
            yield from make_reader(task["payload"])
        except Exception:
            client.task_failed(task["id"])
            raise
        client.task_finished(task["id"])
