"""RPC host ops: send / recv / barriers / prefetch / listen_and_serv.

These register with ``core/host_ops.py`` and run between jitted device
segments.  Reference kernels: ``operators/send_op.cc:29``,
``recv_op.cc:28``, ``send_barrier_op.cc``, ``fetch_barrier_op.cc``,
``prefetch_op.cc:27``, ``checkpoint_notify_op.cc:28`` and the pserver
event loop ``listen_and_serv_op.cc`` (``RunSyncLoop:102``,
``RunAsyncLoop:213``).

The pserver applies optimizer *sub-blocks* exactly like the reference
(``listen_and_serv_op.cc:55-74`` ParallelExecuteBlocks), except each block
is lowered+jitted once by the standard Executor and re-run per round — the
op-loop becomes an XLA executable per optimize block.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict, deque
from typing import Dict, List

import numpy as np

from ..core.host_ops import register_host_op
from ..core.program import Operator, Program, Variable
from ..core.selected_rows import SelectedRows
from ..observability import flight as _flight
from ..observability import stats as _obs_stats
from ..observability import trace as _trace
from ..observability.trace import flags_on as _telemetry_on
from . import faults as _faults
from . import transport
from .transport import (BATCH_BARRIER, CHECKPOINT_NOTIFY, COMPLETE,
                        FETCH_BARRIER, GET_VAR, GET_VARS, OK, PREFETCH,
                        REPLICATE, SEND_VAR, SEND_VARS, serde)


def _to_host(value):
    """Device value → numpy-backed value for the wire."""
    if isinstance(value, SelectedRows):
        return SelectedRows(np.asarray(value.rows), np.asarray(value.values),
                            value.height)
    return np.asarray(value)


def _start_readback(value) -> None:
    """Kick off a non-blocking device→host copy (jax
    ``copy_to_host_async``) so every var's readback overlaps the others
    AND the first endpoint's wire time; the later ``np.asarray`` then
    just waits on an already-in-flight transfer.  No-op for values
    already on host."""
    parts = ((value.rows, value.values)
             if isinstance(value, SelectedRows) else (value,))
    for p in parts:
        start = getattr(p, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:  # pragma: no cover - committed-to-device etc.
                pass


def _batching_on() -> bool:
    from ..core import flags
    try:
        return bool(flags.get_flags("rpc_batch_vars"))
    except KeyError:  # pragma: no cover
        return True


def _ep_groups(op, names):
    """[(endpoint, [name, ...]), ...] for a send/recv op: the
    transpiler-emitted grouping when present (``ep_groups`` attr),
    otherwise grouped at runtime with the same transpiler helper."""
    groups = op.attr("ep_groups", None)
    if groups:
        return [(ep, list(ns)) for ep, ns in groups]
    from .transpiler import _ep_groups as _group
    return [(ep, ns) for ep, ns in _group(names, op.attr("epmap"))]


# ---------------------------------------------------------------------------
# trainer-side ops
# ---------------------------------------------------------------------------

@register_host_op("send")
def _send(exe, program, op, scope):
    names = op.input("X")
    epmap = op.attr("epmap")
    client = transport.get_client(op.attr("trainer_id", 0))
    varmap = op.attr("varmap", {})

    vals = {}
    for name in names:
        val = scope.find_var(name)
        if val is None:
            raise RuntimeError(f"send: variable {name!r} not found in scope")
        vals[name] = val
    # overlapped readback: start EVERY device→host materialization
    # before the first byte hits the wire
    for val in vals.values():
        _start_readback(val)

    if not _batching_on():
        client.parallel([
            (client.send_var, ep, varmap.get(name, name), _to_host(vals[name]))
            for name, ep in zip(names, epmap)])
        return
    # one batched SEND_VARS per pserver instead of one RPC per variable
    client.parallel([
        (client.send_vars, ep,
         [(varmap.get(n, n), _to_host(vals[n])) for n in group])
        for ep, group in _ep_groups(op, names)])


@register_host_op("send_barrier")
def _send_barrier(exe, program, op, scope):
    client = transport.get_client(op.attr("trainer_id", 0))
    if op.attr("ha", False):
        # HA mode (a backup is configured): barriers carry a per-endpoint
        # round sequence the pserver dedups on, so a retry after a
        # connection drop or a promotion cannot close a round twice —
        # which in turn makes the barrier safely retryable
        client.parallel([(client.batch_barrier, ep,
                          client.next_barrier_seq(ep))
                         for ep in op.attr("endpoints")])
        return
    client.parallel([(client.batch_barrier, ep)
                     for ep in op.attr("endpoints")])


@register_host_op("recv")
def _recv(exe, program, op, scope):
    names = op.output("Out")
    epmap = op.attr("epmap")
    client = transport.get_client(op.attr("trainer_id", 0))
    varmap = op.attr("varmap", {})
    if not _batching_on():
        vals = client.parallel([(client.get_var, ep, varmap.get(n, n))
                                for n, ep in zip(names, epmap)])
        for name, val in zip(names, vals):
            scope.set_var(name, val)
        return
    # one batched GET_VARS per pserver; results scatter back by group.
    # copy=False: the views are consumed (device-put by the next concat
    # segment) and replaced within the round, so the zero-copy read
    # path is safe here — public get_vars callers default to owned
    # copies instead
    groups = _ep_groups(op, names)
    results = client.parallel([
        (client.get_vars, ep, [varmap.get(n, n) for n in group], False)
        for ep, group in groups])
    for (ep, group), vals in zip(groups, results):
        for name, val in zip(group, vals):
            scope.set_var(name, val)


@register_host_op("fetch_barrier")
def _fetch_barrier(exe, program, op, scope):
    client = transport.get_client(op.attr("trainer_id", 0))
    client.parallel([(client.fetch_barrier, ep)
                     for ep in op.attr("endpoints")])


def ckpt_notify_name(dirname: str, step=None) -> str:
    """Wire encoding of a checkpoint notify: the dirname, optionally
    carrying an explicit fleet-cut step id (``<dir>@@step=<N>``).  A
    bare dirname keeps the legacy wire byte-identical."""
    return dirname if step is None else f"{dirname}@@step={int(step)}"


def parse_ckpt_notify(name: str):
    """Inverse of :func:`ckpt_notify_name`: (dirname, step-or-None)."""
    if "@@step=" in name:
        dirname, _, step = name.rpartition("@@step=")
        try:
            return dirname, int(step)
        except ValueError:
            pass
    return name, None


def broadcast_checkpoint_notify(client, endpoints, dirname, step=None,
                                connect_timeout: float = 10.0
                                ) -> List[tuple]:
    """Best-effort-ALL checkpoint-notify fan-out: every endpoint is
    notified even when an earlier one fails; failures are counted
    (``rpc.ckpt_notify_failures``), summarized per endpoint in a flight
    note + warning, and only an ALL-endpoints failure raises (nothing
    checkpointed at all).  Returns ``[(endpoint, error-or-None), ...]``.

    Rationale: a checkpoint is an optimization of future recovery — one
    unreachable pserver must not abort the other shards' snapshots (the
    step simply won't commit until that writer returns), and it must
    never kill the training step that triggered the notify.  The same
    logic bounds the connect: a dead endpoint costs ``connect_timeout``
    per attempt, not the transport's full crash-recovery grace — while
    still riding the failover-aware client path, so an HA promotion
    retargets the notify at the promoted replica instead of counting a
    spurious failure."""
    name = ckpt_notify_name(dirname, step)

    def _notify(ep):
        try:
            client.checkpoint_notify(ep, name,
                                     connect_timeout=connect_timeout)
            return (ep, None)
        except Exception as e:  # noqa: BLE001 - summarized below
            return (ep, e)

    results = client.parallel([(_notify, ep) for ep in endpoints])
    failures = [(ep, e) for ep, e in results if e is not None]
    if failures:
        if _telemetry_on():
            _obs_stats.counter(
                "rpc.ckpt_notify_failures",
                "checkpoint_notify fan-out endpoints that failed "
                "(best-effort-all: the rest were still notified)"
            ).inc(len(failures))
        summary = {ep: repr(e)[:120] for ep, e in failures}
        _flight.note("ckpt_notify_failures", dirname=dirname, step=step,
                     failed=len(failures), total=len(endpoints),
                     errors=summary)
        import warnings
        warnings.warn(
            f"checkpoint_notify: {len(failures)}/{len(endpoints)} "
            f"endpoints failed (best-effort, rest notified): {summary}")
        if len(failures) == len(endpoints):
            raise RuntimeError(
                f"checkpoint_notify failed on EVERY endpoint: {summary}")
    return results


@register_host_op("checkpoint_notify")
def _checkpoint_notify(exe, program, op, scope):
    client = transport.get_client(op.attr("trainer_id", 0))
    broadcast_checkpoint_notify(client, op.attr("endpoints"),
                                op.attr("dirname"),
                                step=op.attr("step", None))


@register_host_op("prefetch")
def _prefetch(exe, program, op, scope):
    """Distributed-table row fetch (prefetch_op.cc:27): ids → per-shard
    remote gather → rows reassembled in id order, shaped exactly like the
    local ``lookup_table`` output (trailing [..., 1] ids dim squeezed)."""
    ids_name = op.input("Ids")[0]
    out_name = op.output("Out")[0]
    table = op.attr("table_name")
    sections = op.attr("sections")    # [[endpoint, row_offset, rows], ...]
    client = transport.get_client(op.attr("trainer_id", 0))
    ids_arr = np.asarray(scope.find_var(ids_name))
    ids = ids_arr.reshape(-1).astype(np.int64)

    calls, masks = [], []
    for ep, offset, rows in sections:
        mask = (ids >= offset) & (ids < offset + rows)
        local = ids[mask] - offset
        masks.append(mask)
        calls.append((client.prefetch, ep, table, local))
    results = client.parallel(calls)
    width = results[0].shape[-1]
    out = np.zeros((ids.shape[0], width), results[0].dtype)
    for mask, rows in zip(masks, results):
        out[mask] = rows
    lead = (ids_arr.shape[:-1] if ids_arr.ndim >= 2 and ids_arr.shape[-1] == 1
            else ids_arr.shape)
    scope.set_var(out_name, out.reshape(tuple(lead) + (width,)))


@register_host_op("split_selected_rows")
def _split_selected_rows(exe, program, op, scope):
    """Split a SelectedRows gradient into per-shard slices with row ids
    rebased to shard-local (reference split_selected_rows_op.cc)."""
    x = scope.find_var(op.input("X")[0])
    if not isinstance(x, SelectedRows):
        raise TypeError(
            f"split_selected_rows: {op.input('X')[0]!r} is not a "
            f"SelectedRows gradient (got {type(x).__name__}); distributed "
            "tables require embedding(is_sparse=True)")
    rows = np.asarray(x.rows)
    vals = np.asarray(x.values)
    for out_name, (offset, cnt) in zip(op.output("Out"), op.attr("sections")):
        m = (rows >= offset) & (rows < offset + cnt)
        scope.set_var(out_name,
                      SelectedRows(rows[m] - offset, vals[m], cnt))


# ---------------------------------------------------------------------------
# pserver-side: listen_and_serv
# ---------------------------------------------------------------------------

def _block_program(ps_program: Program, block_idx: int) -> Program:
    """Standalone program from one optimize sub-block (vars resolved
    against block 0), runnable by the standard Executor."""
    sub = Program()
    gb = sub.global_block
    for src in (ps_program.global_block, ps_program.blocks[block_idx]):
        for name, v in src.vars.items():
            if name not in gb.vars:
                gb.vars[name] = Variable.from_dict(gb, v.to_dict())
    for op in ps_program.blocks[block_idx].ops:
        gb.ops.append(Operator(gb, op.type, op.inputs, op.outputs,
                               dict(op.attrs)))
    return sub


class PServerLoop:
    """The pserver service + event loop (listen_and_serv_op.cc).

    Sync mode (RunSyncLoop:102): grads buffer per (trainer, round); the
    last batch-barrier of a round merges grads (mean for dense, concat for
    SelectedRows), runs the LR block then every optimize block, and bumps
    ``applied_rounds``.  A GET from trainer *t* blocks until
    ``applied_rounds >= rounds_sent(t)`` — the request-type condition
    barrier of ``rpc_server.cc`` reduced to one monotonic counter.

    Async mode (RunAsyncLoop:213): each incoming grad is applied
    immediately through its optimize block under a per-block lock
    (hogwild across params, serialized per param).

    HA replication (the go/pserver fault-tolerance story, survey §2.11):
    with a ``backup_endpoint`` configured, the PRIMARY forwards every
    state-bearing frame (SEND_VAR/SEND_VARS/BATCH_BARRIER/COMPLETE) to
    its backup under a monotonic apply-sequence number BEFORE buffering
    or applying it locally, so anything a trainer got an OK for also
    exists at the backup — primary death loses no acknowledged state.
    The BACKUP (``is_backup``) runs the same loop fed by REPLICATE
    frames: same barrier accounting, same optimize blocks, identical
    state evolution.  Promotion is pure routing — the registry flips the
    logical endpoint to the backup's address on the primary's lease
    expiry, trainers re-resolve, and the already-warm backup serves
    their next request (no checkpoint rollback, no replay).
    """

    def __init__(self, executor, program: Program, op, scope):
        self.exe = executor
        self.scope = scope
        self.op = op
        self.sync_mode = bool(op.attr("sync_mode", True))
        self.num_trainers = int(op.attr("Fanin", 1))
        self.grad_to_block = dict(op.attr("grad_to_block_id", {}))
        self.lr_block = int(op.attr("lr_block", -1))
        self.lr_fetch = list(op.attr("lr_fetch", []))
        self.dense_merge = op.attr("dense_merge", "mean")
        self.persist_names = list(op.attr("persist_names", []))
        self.dist_tables = dict(op.attr("dist_tables", {}))
        # {table: {"var": shard var, "offset": o, "rows": r}}

        self.block_progs = {int(b): _block_program(program, int(b))
                            for b in self.grad_to_block.values()}
        if self.lr_block >= 0:
            self.lr_prog = _block_program(program, self.lr_block)
        else:
            self.lr_prog = None

        self.lock = threading.Condition()
        self.open_round: Dict[int, dict] = defaultdict(dict)
        self.closed: Dict[int, deque] = defaultdict(deque)
        self.rounds_sent: Dict[int, int] = defaultdict(int)
        self.applied_rounds = 0
        self.n_complete = 0
        self.exit = False
        self.error: Exception = None
        self.block_locks: Dict[int, threading.Lock] = defaultdict(threading.Lock)
        # RLock: the hogwild checkpoint runs under lr_lock and its
        # _read_var snapshots re-enter it for LR-program vars
        self.lr_lock = threading.RLock()
        self._async_sends = 0
        # which optimize block WRITES each persistable var: wire/checkpoint
        # readers must snapshot under that block's lock (see _read_var)
        self.var_to_block: Dict[str, int] = {}
        for bidx, bprog in self.block_progs.items():
            blk = bprog.global_block
            for bop in blk.ops:
                for n in bop.output_arg_names():
                    v = blk.var_or_none(n)
                    if v is not None and v.persistable:
                        self.var_to_block.setdefault(n, bidx)

        # HA replication state (module docstring "HA replication")
        self.backup_endpoint = op.attr("backup_endpoint", None) or None
        self.is_backup = bool(op.attr("is_backup", False))
        self.repl_lock = threading.Lock()   # seq assignment + wire order
        self.repl_seq = 0                   # primary: next seq to stream
        self.repl_last = -1                 # backup: last applied seq
        self._backup_down = False
        self._repl_client = None
        # staleness fencing: a backup that MISSED acknowledged frames
        # (apply-seq gap, or the primary revoked it after a replication
        # loss) can never serve primary duty — it withdraws candidacy
        # (on_stale, wired to Heartbeat.withdraw by listen_and_serv) and
        # refuses the rest of the stream
        self.stale = False
        self.on_stale = None
        # HA barrier dedup: last round seq seen per trainer (mirrors to
        # the backup through the replicated barrier, so a post-promotion
        # retry of the in-flight barrier is recognized there too)
        self.last_barrier_seq: Dict[int, int] = {}

        # periodic self-checkpoint + recovery (go/pserver/service.go:346
        # checkpoint / :175 LoadCheckpoint)
        from ..core import flags as _flags
        self.logical = op.attr("endpoint")
        self.registry_ep = op.attr("registry_endpoint", None) or None
        if self.registry_ep is None:
            try:
                self.registry_ep = _flags.get_flags("pserver_registry") \
                    or None
            except KeyError:  # pragma: no cover
                self.registry_ep = None
        try:
            self._profile_period = int(
                _flags.get_flags("rpc_server_profile_period") or 0)
        except KeyError:  # pragma: no cover
            self._profile_period = 0
        self._profile_lock = threading.Lock()
        self._req_count = 0
        self._profile_t0 = time.monotonic()

        self.ckpt_dir = op.attr("checkpoint_dir") or None
        self.ckpt_every = int(op.attr("checkpoint_every_rounds", 0) or 0)
        # sharded-checkpoint plane (paddle_tpu/checkpoint/): extent
        # table mapping each local persist var onto its global row
        # range, the expected writer count for two-phase commit, and
        # one AsyncSnapshotter per target dirname
        self.ckpt_sharded = bool(op.attr("ckpt_sharded", False))
        self.shard_extents = dict(op.attr("shard_extents", {}) or {})
        self.ckpt_writers = int(op.attr("ckpt_writers", 1) or 1)
        self._snapshotters: Dict[str, object] = {}
        self.recovered_step = None
        if self.ckpt_dir:
            if self.ckpt_sharded:
                self._recover_sharded()
            elif os.path.exists(self._ckpt_path()):
                with np.load(self._ckpt_path()) as data:
                    for n in data.files:
                        self.scope.set_var(n, data[n])
        self._warm_start()

    def _recover_sharded(self) -> None:
        """Hydrate this pserver's sections from the newest COMPLETE
        sharded checkpoint step — written by ANY topology (a restarted
        peer of the same fleet, or a differently-sized previous fleet:
        the N→M resize path).  No COMPLETE step means a fresh start."""
        from .. import checkpoint as _ckpt
        step = _ckpt.latest_complete_step(self.ckpt_dir)
        if step is None:
            return
        vals = _ckpt.load_locals(self.ckpt_dir, step, self.shard_extents)
        for n, v in vals.items():
            self.scope.set_var(n, v)
        self.recovered_step = step
        _flight.note("pserver_sharded_recover", step=step,
                     nvars=len(vals), ps_index=self.op.attr("ps_index", 0))

    def _warm_start(self) -> None:
        """Elastic-restart hydration (FLAGS_compile_cache_dir): load
        the LR + optimize block executables from the persistent compile
        cache — stored by a previous incarnation of this shard — so a
        restarted pserver's first round costs a deserialize, not an XLA
        compile.  ``hydrate_only``: a COLD cache must not block the
        port bind / heartbeat registration behind serial compiles
        (trainer wait_server_ready probes would time out), so disk
        misses keep the old lazy compile-at-first-round behavior (which
        also stores the entries this hydration reads next restart).
        Grad inputs that exist only at runtime lower from their static
        var declarations; a wrong guess degrades to a counted recompile
        on first dispatch, never a failed round."""
        from ..core import compile_cache as _compile_cache
        if not _compile_cache.enabled():
            return
        try:
            warmed = {"persistent_hits": 0, "skipped": 0}
            progs = [(self.lr_prog, self.lr_fetch)] if self.lr_prog else []
            progs += [(p, []) for _, p in sorted(self.block_progs.items())]
            for prog, fetches in progs:
                res = self.exe.warm_start(prog, feed_specs={},
                                          fetch_list=fetches,
                                          scope=self.scope,
                                          hydrate_only=True)
                warmed["persistent_hits"] += res["persistent_hits"]
                warmed["skipped"] += len(res["skipped"])
            _flight.note("pserver_warm_start", **warmed)
        except Exception as e:  # warm start is an optimization, never fatal
            _flight.note("pserver_warm_start_failed", error=repr(e)[:200])

    # -- self-profiling (reference FLAGS_rpc_server_profile_period,
    # python/paddle/fluid/__init__.py:121 + rpc_server.cc profiling):
    # every N handled requests, log one line of request-rate stats
    def _profile_tick(self):
        period = self._profile_period
        if not period:
            return
        with self._profile_lock:
            self._req_count += 1
            if self._req_count % period:
                return
            now = time.monotonic()
            dt = max(now - self._profile_t0, 1e-9)
            rate = period / dt
            count = self._req_count
            self._profile_t0 = now
        print(f"[pserver {self.op.attr('endpoint')}] handled "
              f"{count} requests ({rate:.0f} req/s over the "
              f"last {period})", flush=True)

    def _ckpt_path(self) -> str:
        # keyed by shard index, not endpoint: a restarted pserver may come
        # back on a different host:port but owns the same param shards
        idx = self.op.attr("ps_index", 0)
        return os.path.join(self.ckpt_dir, f"pserver_{idx}.npz")

    def _checkpoint(self, dirname: str = None, step: int = None) -> None:
        dirname = dirname or self.ckpt_dir
        if self.ckpt_sharded and dirname:
            self._sharded_checkpoint(dirname, step)
            return
        os.makedirs(dirname, exist_ok=True)
        path = os.path.join(dirname,
                            f"pserver_{self.op.attr('ps_index', 0)}.npz")
        # _read_var: block-lock-coherent snapshots — an async checkpoint
        # racing a hogwild apply must not read a donated (deleted) buffer
        arrs = {}
        for n in self.persist_names:
            v = self._read_var(n)
            if v is not None:
                arrs[n] = np.asarray(v)
        tmp = path + ".tmp.npz"
        np.savez(tmp, **arrs)
        os.replace(tmp, path)  # atomic like the Go rename

    # -- sharded async checkpoints (paddle_tpu/checkpoint/) ----------------
    def _collect_persist(self, step=None) -> Dict[str, np.ndarray]:
        """Phase-1 collect for the async snapshotter: host snapshots of
        every persist var, coherent with concurrent applies.  Vars are
        grouped by the lock that guards them (_read_var's invariant);
        within one lock hold every device→host copy is kicked async
        first (``copy_to_host_async``) and only then materialized, so
        the waits overlap instead of serializing — the step loop pays
        one lock-scoped overlapped readback, nothing else."""
        out: Dict[str, np.ndarray] = {}
        groups: Dict[tuple, List[str]] = defaultdict(list)
        for n in self.persist_names:
            bidx = self.var_to_block.get(n)
            if bidx is not None:
                groups[("block", bidx)].append(n)
            elif n in self.lr_fetch:
                groups[("lr",)].append(n)
            else:
                groups[("free",)].append(n)

        def grab(names):
            vals = {n: self.scope.find_var(n) for n in names}
            for v in vals.values():
                if v is not None:
                    _start_readback(v)
            for n, v in vals.items():
                if v is not None:
                    out[n] = np.asarray(_to_host(v))

        for key, names in groups.items():
            if key[0] == "block":
                with self.block_locks[key[1]]:
                    grab(names)
            elif key[0] == "lr":
                with self.lr_lock:
                    grab(names)
            else:
                grab(names)
        return out

    def _sharded_checkpoint(self, dirname: str, step: int = None) -> None:
        """Async sharded snapshot: enqueue and return — serialization,
        fsync and the two-phase commit run on the snapshotter's
        background thread.  ``step`` defaults to the applied round
        count, which sync-mode barriers make identical across the fleet
        at the moment each pserver passes the same round: periodic
        every-N-round snapshots and an explicit checkpoint_notify
        between rounds are both consistent cuts.  (Async/hogwild mode
        has no fleet-wide round; give notify an explicit step there —
        per-writer pieces only commit when step ids agree.)"""
        from .. import checkpoint as _ckpt
        explicit = step is not None
        if step is None:
            # monotonic across restarts/resizes: a recovered pserver's
            # round counter restarts at 0, but its checkpoint step ids
            # continue from the step it hydrated
            rounds = (self.applied_rounds if self.sync_mode else
                      self._async_sends // max(1, len(self.grad_to_block)))
            step = (self.recovered_step or 0) + rounds
        snap = self._snapshotters.get(dirname)
        if snap is None:
            idx = int(self.op.attr("ps_index", 0))
            snap = _ckpt.AsyncSnapshotter(
                dirname, f"ps{idx}", self._collect_persist,
                extents=self.shard_extents,
                topology={"kind": "pserver",
                          "num_pservers": self.ckpt_writers,
                          "sync_mode": bool(self.sync_mode)},
                expected_writers=[f"ps{i}"
                                  for i in range(self.ckpt_writers)])
            self._snapshotters[dirname] = snap
        if explicit:
            # an EXPLICIT fleet cut (checkpoint_notify with a step id)
            # must not be skip-dropped behind an in-flight periodic
            # write — without this writer's piece the step can never
            # commit and the cut caller burns its whole commit-poll
            # timeout.  Drain the in-flight write first (bounded; this
            # blocks only the notify RPC handler thread, never the
            # apply loop), then take the cut; a still-failed accept is
            # loud.
            snap.flush(timeout=60.0)
            if not snap.snapshot(step):
                _flight.note("ckpt_cut_dropped", dirname=dirname,
                             step=step,
                             ps_index=self.op.attr("ps_index", 0))
        else:
            snap.snapshot(step)

    def close_snapshotters(self) -> None:
        """Drain in-flight async checkpoint writes (clean shutdown: the
        writer threads are daemons and would die mid-write at interpreter
        exit, leaving an uncommittable piece — harmless for correctness,
        wasteful for recovery freshness)."""
        for snap in self._snapshotters.values():
            try:
                snap.close(timeout=30.0)
            except Exception:  # pragma: no cover - shutdown best-effort
                pass

    # -- optimize-block execution -----------------------------------------
    def _run_lr(self):
        if self.lr_prog is None:
            return
        vals = self.exe.run(self.lr_prog, feed={}, fetch_list=self.lr_fetch,
                            scope=self.scope, return_numpy=False)
        for n, v in zip(self.lr_fetch, vals):
            self.scope.set_var(n, v)

    def _run_block(self, block_idx: int):
        self.exe.run(self.block_progs[block_idx], feed={}, fetch_list=[],
                     scope=self.scope)

    def _merge_grads(self, per_trainer: List[dict]) -> set:
        """Merge buffered grads into scope; returns the block indices
        that actually received gradients this round (a round closed with
        a grad missing — possible only under faults/promotion windows —
        must not re-apply that block with its STALE previous grad)."""
        touched = set()
        for gname, bidx in self.grad_to_block.items():
            vals = [buf[gname] for buf in per_trainer if gname in buf]
            if not vals:
                continue
            touched.add(bidx)
            if isinstance(vals[0], SelectedRows):
                rows = np.concatenate([np.asarray(v.rows) for v in vals])
                data = np.concatenate([np.asarray(v.values) for v in vals])
                if self.dense_merge == "mean":
                    data = data / float(self.num_trainers)
                merged = SelectedRows(rows, data, vals[0].height)
            else:
                merged = np.sum(np.stack(vals), axis=0)
                if self.dense_merge == "mean":
                    merged = merged / float(self.num_trainers)
            self.scope.set_var(gname, merged)
        return touched

    # -- HA replication (primary side) -------------------------------------
    def _replicate(self, kind: str, trainer_id: int, name: str,
                   payload) -> None:
        """Stream one state-bearing frame to the backup, in apply order,
        under a monotonic sequence number.  Synchronous BEFORE the local
        buffer/apply: anything the trainer gets an OK for exists at the
        backup first (zero acknowledged-state loss on primary death).
        A dead backup degrades replication loudly — training continues
        unreplicated rather than stalling on a lost replica."""
        if self.backup_endpoint is None or self._backup_down:
            return
        hdr = {"kind": kind, "tid": int(trainer_id), "name": name}
        with self.repl_lock:
            # the lock covers send+ack so wire order == seq order even
            # across the striped client connections
            hdr["seq"] = self.repl_seq
            if self._repl_client is None:
                self._repl_client = transport.RPCClient(0)
            frames = [payload] if not isinstance(payload, list) else payload
            try:
                try:
                    self._repl_client._raw_request(
                        self.backup_endpoint, REPLICATE, json.dumps(hdr),
                        frames)
                except ConnectionError:
                    # one retry on a fresh connection: a transient TCP
                    # reset must not permanently degrade replication.
                    # Safe to re-send — the backup dedups seq==repl_last
                    # retransmits.  A RuntimeError (the backup REFUSED
                    # the frame: promoted, or already stale) is
                    # authoritative and never retried.
                    self._repl_client._raw_request(
                        self.backup_endpoint, REPLICATE, json.dumps(hdr),
                        frames)
                self.repl_seq += 1
                if _telemetry_on():
                    _obs_stats.counter(
                        "pserver.replicated_frames",
                        "state-bearing frames streamed to the backup "
                        "replica").inc()
            except (ConnectionError, RuntimeError) as e:
                self._mark_backup_lost(e)

    def _mark_backup_lost(self, e: Exception) -> None:
        """Give up on the backup (call with repl_lock held): training
        continues unreplicated — loudly — and, since the backup is now
        missing frames trainers were acked for, its candidacy is revoked
        at the registry (the promotion authority) so a later primary
        death can never promote a silently-rolled-back replica."""
        self._backup_down = True
        if _telemetry_on():
            _obs_stats.counter(
                "pserver.replication_lost",
                "backup replicas given up on after a forward "
                "error (replication degraded, training "
                "continues)").inc()
        print(f"[pserver-replication] backup "
              f"{self.backup_endpoint} lost ({e!r}); continuing "
              "UNREPLICATED", flush=True)
        _flight.note("replication_lost",
                     backup=self.backup_endpoint,
                     error=repr(e)[:200], seq=self.repl_seq)
        if self.registry_ep:
            threading.Thread(
                target=self._revoke_backup_loop, daemon=True,
                name="pserver-revoke-backup").start()

    def _revoke_backup_loop(self) -> None:
        """Background best-effort: strike the lost backup's candidacy at
        the registry, retrying until it lands or the loop exits (the
        registry itself may be briefly unreachable in the same fault)."""
        from . import registry as registry_mod
        client = transport.RPCClient(0)
        while not self.exit:
            try:
                registry_mod.revoke_standby(
                    client, self.registry_ep, self.logical,
                    self.backup_endpoint)
                if _telemetry_on():
                    _obs_stats.counter(
                        "pserver.backup_revokes",
                        "lost backups whose standby candidacy was "
                        "revoked at the registry").inc()
                _flight.note("backup_candidacy_revoked",
                             backup=self.backup_endpoint,
                             logical=self.logical)
                return
            except Exception:
                time.sleep(1.0)

    def mark_stale(self, reason: str) -> None:
        """Backup side of the same invariant: this replica missed
        acknowledged frames (apply-seq gap, or the primary revoked it)
        and can never serve primary duty — withdraw standby candidacy
        and refuse the rest of the stream."""
        if self.stale:
            return
        self.stale = True
        if _telemetry_on():
            _obs_stats.counter(
                "pserver.backup_stale",
                "backup replicas fenced as stale (missed acknowledged "
                "frames; candidacy withdrawn)").inc()
        print(f"[pserver-replication] backup {self.op.attr('endpoint')} "
              f"is STALE ({reason}); withdrawing candidacy", flush=True)
        _flight.note("backup_stale", endpoint=self.op.attr("endpoint"),
                     reason=reason)
        cb = self.on_stale
        if cb is not None:
            try:
                cb()
            except Exception as e:
                _flight.note("on_stale_failed", error=repr(e)[:200])

    def fence(self) -> None:
        """The registry refused this worker's primary claim: a backup
        was promoted over it while it was partitioned/away (the zombie-
        primary case).  A fenced primary must stop serving immediately —
        still-connected trainers would keep feeding a deposed replica —
        so the loop exits dirty (flight post-mortem) and a supervisor
        restarts it as a fresh standby."""
        if _telemetry_on():
            _obs_stats.counter(
                "pserver.fenced",
                "deposed primaries shut down after the registry "
                "refused their claim").inc()
        _flight.note("pserver_fenced", endpoint=self.op.attr("endpoint"))
        with self.lock:
            if self.error is None:
                self.error = RuntimeError(
                    "fenced: a backup was promoted over this pserver")
            self.exit = True
            self.lock.notify_all()

    def promote(self) -> None:
        """Backup → primary flip (the registry told our heartbeat we now
        own the logical endpoint).  Routing already changed; this just
        re-arms the duties a standby holds back (checkpoints)."""
        if self.stale:
            # should be unreachable (a stale backup withdrew candidacy
            # and was revoked at the registry) — but if every fence
            # failed, say so as loudly as possible: trainers are about
            # to see silently rolled-back state
            _flight.note("stale_backup_promoted",
                         endpoint=self.op.attr("endpoint"),
                         repl_last=self.repl_last)
            print(f"[pserver] WARNING: STALE backup "
                  f"{self.op.attr('endpoint')} promoted — acknowledged "
                  "state has been lost", flush=True)
        self.is_backup = False
        _flight.note("backup_promoted",
                     endpoint=self.op.attr("endpoint"),
                     applied_rounds=self.applied_rounds,
                     repl_last=self.repl_last)

    def _handle_barrier(self, trainer_id: int, name: str) -> None:
        """Close trainer ``trainer_id``'s round.  ``name`` (HA mode)
        carries the trainer's round seq: an exact retransmit — a retry
        after a drop/promotion of a barrier the server already applied —
        is recognized and ignored, making the barrier idempotent."""
        if not self.sync_mode:
            return
        with self.lock:
            if name:
                seq = int(name)
                if self.last_barrier_seq.get(trainer_id) == seq:
                    if _telemetry_on():
                        _obs_stats.counter(
                            "pserver.barrier_dups",
                            "retransmitted HA barriers ignored by "
                            "round-seq dedup").inc()
                    return
                self.last_barrier_seq[trainer_id] = seq
            self.closed[trainer_id].append(
                self.open_round.pop(trainer_id, {}))
            self.rounds_sent[trainer_id] += 1
            ready = all(self.closed[t] for t in range(self.num_trainers))
            if ready:
                self._apply_round()
                self.lock.notify_all()

    def _apply_round(self):
        _faults.event("apply_round")
        per_trainer = [self.closed[t].popleft()
                       for t in range(self.num_trainers) if self.closed[t]]
        try:
            # child of the round-closing BATCH_BARRIER's server span
            # (the inbound wire context): in a stitched trace the apply
            # work hangs under the barrier that triggered it, which is
            # exactly where "why was this batch_barrier slow" lives
            with _trace.start_span("pserver::apply_round", cat="pserver",
                                   root=False,
                                   tags={"round": self.applied_rounds + 1}):
                touched = self._merge_grads(per_trainer)
                with self.lr_lock:
                    self._run_lr()
                for bidx in sorted(touched):
                    # block lock even in sync mode: the protocol barriers
                    # make reader overlap impossible in the NORMAL flow,
                    # but HA promotion/fault edges can let a GET arrive
                    # mid-apply, and _read_var's snapshot coherence
                    # invariant ("readers snapshot under the writer
                    # block's lock") must hold for every _run_block site
                    with self.block_locks[bidx]:
                        self._run_block(bidx)
        except Exception as e:
            # record + still advance the round so waiting GETs wake up and
            # surface the error instead of deadlocking (exception_holder.h
            # role in the reference's threaded executor)
            self.error = e
            _flight.note("pserver_apply_error", error=repr(e)[:200],
                         round=self.applied_rounds + 1)
            raise
        finally:
            self.applied_rounds += 1
            self.lock.notify_all()  # caller holds the condition
        # a failed snapshot must not poison training: in-memory state is
        # intact, so warn and carry on (next interval retries).  A
        # BACKUP holds periodic checkpoints back (the primary owns the
        # shard file; promotion re-arms them via promote())
        if self.ckpt_dir and self.ckpt_every > 0 and not self.is_backup \
                and self.applied_rounds % self.ckpt_every == 0:
            try:
                self._checkpoint()
            except Exception as e:
                import warnings
                warnings.warn(f"pserver checkpoint failed (continuing): {e}")

    def _apply_async(self, name, value) -> None:
        """Async-mode apply of ONE incoming var (RunAsyncLoop:213
        hogwild): no scaling, no barriers; LR block advances once per
        virtual round."""
        _faults.event("apply_async")
        bidx = self.grad_to_block.get(name)
        if bidx is None:
            # plain var write (e.g. startup broadcast)
            with self.lock:
                self.scope.set_var(name, value)
            return
        with self.lr_lock:
            n_grads = max(1, len(self.grad_to_block))
            if self._async_sends % n_grads == 0:
                self._run_lr()
            self._async_sends += 1
            ckpt_now = (
                self.ckpt_dir and self.ckpt_every > 0
                and not self.is_backup
                and self._async_sends %
                (n_grads * self.ckpt_every) == 0)
        # child of the SEND_VAR(S) server span: the per-var hogwild
        # apply, lock wait included (a hot block lock shows up as a
        # long apply_async under a short wire span)
        with _trace.start_span("pserver::apply_async", cat="pserver",
                               root=False, tags={"var": name}):
            with self.block_locks[bidx]:
                self.scope.set_var(name, value)
                self._run_block(bidx)
        if ckpt_now:
            # hogwild checkpoint: per-var snapshot consistency
            # only, like the Go async pserver (service.go:346)
            with self.lr_lock:
                self._checkpoint()

    def _read_var(self, name):
        """Snapshot one scope var to host for the wire/checkpoint, coherent
        with concurrent applies.  The optimize-block executor dispatch
        DONATES the param's device buffer, so an unlocked reader that
        grabbed the Array just before an async (hogwild) apply can hold a
        deleted buffer by the time it serializes — the intermittent
        async-mode 'Array has been deleted' crash (test_dist_train
        deflake, PR 10).  Reading under the var's writer-block lock (the
        same lock _apply_async runs the block under) pins apply/read
        interleaving to whole blocks; LR-program vars snapshot under
        lr_lock for the same reason.  Returns a host value or None."""
        bidx = self.var_to_block.get(name)
        if bidx is not None:
            with self.block_locks[bidx]:
                val = self.scope.find_var(name)
                return None if val is None else _to_host(val)
        if name in self.lr_fetch:
            with self.lr_lock:
                val = self.scope.find_var(name)
                return None if val is None else _to_host(val)
        val = self.scope.find_var(name)
        return None if val is None else _to_host(val)

    def _wait_round(self, trainer_id) -> None:
        """Sync-mode read barrier: block until every round this trainer
        has closed is applied (rpc_server.cc request-type condition
        barrier reduced to one monotonic counter)."""
        if self.sync_mode:
            with self.lock:
                target = self.rounds_sent[trainer_id]
                while self.applied_rounds < target and not self.exit:
                    self.lock.wait(timeout=1.0)
        if self.error is not None:
            raise RuntimeError(
                f"pserver optimize pass failed: {self.error!r}")

    # -- incoming state-bearing frames (direct AND replicated) -------------
    def _handle_send_var(self, trainer_id: int, name: str, value) -> None:
        if self.sync_mode:
            with self.lock:
                self.open_round[trainer_id][name] = value
        else:
            self._apply_async(name, value)

    def _handle_send_vars(self, trainer_id: int, pairs) -> None:
        if self.sync_mode:
            # the whole batch lands under ONE lock acquisition; each
            # var still counts individually toward the round, so a
            # batch of N is indistinguishable from N SEND_VARs to
            # the batch_barrier accounting
            with self.lock:
                buf = self.open_round[trainer_id]
                for n, v in pairs:
                    buf[n] = v
        else:
            for n, v in pairs:
                self._apply_async(n, v)

    def _handle_complete(self, trainer_id: int) -> None:
        with self.lock:
            self.n_complete += 1
            if self.n_complete >= self.num_trainers:
                self.exit = True
            self.lock.notify_all()

    # -- service entry (one call per request, many threads) ----------------
    def handle(self, msg_type, trainer_id, name, payload):
        self._profile_tick()
        if msg_type == SEND_VAR:
            self._replicate("send_var", trainer_id, name, payload)
            self._handle_send_var(trainer_id, name,
                                  serde.loads_value(payload))
            return OK, b""

        if msg_type == SEND_VARS:
            self._replicate("send_vars", trainer_id, name, payload)
            # zero-copy decode: values are views over the recv buffer
            # (pinned by the arrays; merge/apply never mutates in place)
            self._handle_send_vars(trainer_id,
                                   serde.loads_batch(payload, copy=False))
            return OK, b""

        if msg_type == BATCH_BARRIER:
            self._replicate("batch_barrier", trainer_id, name, b"")
            self._handle_barrier(trainer_id, name)
            return OK, b""

        if msg_type == REPLICATE:
            return self._handle_replicate(name, payload)

        if msg_type == GET_VAR:
            self._wait_round(trainer_id)
            val = self._read_var(name)
            if val is None:
                raise KeyError(f"pserver has no variable {name!r}")
            return OK, serde.dumps_value(val)

        if msg_type == GET_VARS:
            # one round-barrier wait covers the whole batch, then the
            # reply streams every tensor scatter-gather (buffer list)
            names = [n for n, _ in serde.loads_batch(payload)]
            self._wait_round(trainer_id)
            pairs = []
            for n in names:
                val = self._read_var(n)
                if val is None:
                    raise KeyError(f"pserver has no variable {n!r}")
                pairs.append((n, val))
            return OK, serde.dumps_batch_vec(pairs)

        if msg_type == PREFETCH:
            # same round barrier as GET: the next forward's lookup must see
            # this round's sparse update applied
            self._wait_round(trainer_id)
            info = self.dist_tables[name]
            ids = np.asarray(serde.loads_value(payload)).reshape(-1)
            table = np.asarray(self._read_var(info["var"]))
            return OK, serde.dumps_value(table[ids])

        if msg_type == FETCH_BARRIER:
            return OK, b""

        if msg_type == CHECKPOINT_NOTIFY:
            dirname, step = parse_ckpt_notify(name)
            self._checkpoint(dirname=dirname, step=step)
            return OK, b""

        if msg_type == COMPLETE:
            self._replicate("complete", trainer_id, name, b"")
            self._handle_complete(trainer_id)
            return OK, b""

        raise ValueError(f"unknown message type {msg_type}")

    def _handle_replicate(self, name: str, payload):
        """Backup side of the replication stream: apply one forwarded
        frame through the SAME paths a direct frame takes (identical
        state evolution), guarded by the monotonic apply-seq so a
        duplicate is ignored and a gap is loud."""
        if not self.is_backup:
            # a PROMOTED backup (or any primary) must fence its old
            # peer's stream: a zombie primary that lost its lease but
            # can still reach this address would otherwise keep mutating
            # round/barrier state here, silently diverging the replica.
            # The refusal surfaces as a RuntimeError at the sender,
            # which gives up replication (authoritative, never retried).
            if _telemetry_on():
                _obs_stats.counter(
                    "pserver.replication_refused",
                    "replicated frames refused (receiver is not a "
                    "backup: promoted, or a misdirected stream)").inc()
            _flight.note("replication_refused",
                         endpoint=self.op.attr("endpoint"),
                         reason="not_backup")
            raise RuntimeError(
                "replication refused: not a backup (a promoted primary "
                "fences its deposed peer's stream)")
        if self.stale:
            raise RuntimeError(
                "replication refused: backup is stale (missed "
                "acknowledged frames)")
        hdr = json.loads(name)
        seq, kind, tid = int(hdr["seq"]), hdr["kind"], int(hdr["tid"])
        with self.repl_lock:
            if seq == self.repl_last:
                # exact retransmit (the primary retried a frame whose
                # ACK was lost): already applied, idempotently ignored
                if _telemetry_on():
                    _obs_stats.counter(
                        "pserver.replication_dups",
                        "replicated frames ignored as duplicates by "
                        "apply-seq").inc()
                return OK, b""
            last = self.repl_last
            gap = seq != last + 1
            if not gap:
                self.repl_last = seq
        if gap:
            # frames lost between primary and backup (it forwards
            # synchronously BEFORE acking, so a gap means acknowledged
            # state this replica will never have — a primary restart,
            # an epoch anomaly, or an injected fault).  This replica is
            # permanently stale: withdraw candidacy and refuse, loudly —
            # a promotion here would silently roll trainers back.
            if _telemetry_on():
                _obs_stats.counter(
                    "pserver.replication_gaps",
                    "apply-seq gaps observed in the replication "
                    "stream").inc()
            _flight.note("replication_gap", last=last, got=seq)
            self.mark_stale(f"apply-seq gap: last={last} got={seq}")
            raise RuntimeError(
                f"replication refused: apply-seq gap (last={last}, "
                f"got={seq}) — backup is stale")
        if kind == "send_var":
            self._handle_send_var(tid, hdr["name"],
                                  serde.loads_value(payload))
        elif kind == "send_vars":
            self._handle_send_vars(tid,
                                   serde.loads_batch(payload, copy=False))
        elif kind == "batch_barrier":
            self._handle_barrier(tid, hdr.get("name", ""))
        elif kind == "complete":
            self._handle_complete(tid)
        else:
            raise ValueError(f"unknown replicated frame kind {kind!r}")
        return OK, b""

    def wait_exit(self):
        with self.lock:
            while not self.exit:
                self.lock.wait(timeout=0.5)


@register_host_op("listen_and_serv")
def _listen_and_serv(exe, program, op, scope):
    from ..core import flags
    from . import registry as registry_mod

    loop = PServerLoop(exe, program, op, scope)
    # bind_endpoint lets a RESTARTED pserver come up on a fresh port while
    # keeping its logical identity (the transpiler-time endpoint attr and
    # the ps_index-keyed shard checkpoint) — the etcd re-claim path of
    # go/pserver/etcd_client.go
    bind_ep = op.attr("bind_endpoint", None) or op.attr("endpoint")
    server = transport.RPCServer(bind_ep, loop)
    server.start()
    hb = None
    registry_ep = (op.attr("registry_endpoint", None)
                   or flags.get_flags("pserver_registry") or None)
    if registry_ep:
        host = bind_ep.rsplit(":", 1)[0]
        ttl = float(op.attr("lease_ttl", 0) or registry_mod.DEFAULT_TTL)
        if loop.is_backup:
            # a BACKUP heartbeats as a standby under the SAME logical
            # key: invisible to trainers while the primary's lease is
            # live; on the primary's lease expiry the registry promotes
            # it and the next refresh response flips this loop to
            # primary duty (promotion rides the keepalive — no new RPC)
            hb = registry_mod.Heartbeat(
                registry_ep, op.attr("endpoint"),
                f"{host}:{server.port}", ttl=ttl, role="PSERVER",
                standby=int(op.attr("replica_id", 1)),
                on_promote=loop.promote,
                on_revoke=lambda: loop.mark_stale(
                    "candidacy revoked by the registry"))
            # a gap-fenced backup withdraws its own candidacy
            loop.on_stale = hb.withdraw
        else:
            # on_demote: the zombie-primary fence — if a backup was
            # promoted over this worker while it was partitioned, stop
            # serving instead of feeding still-connected trainers from
            # a deposed replica
            hb = registry_mod.Heartbeat(registry_ep, op.attr("endpoint"),
                                        f"{host}:{server.port}", ttl=ttl,
                                        role="PSERVER",
                                        on_demote=loop.fence)
        hb.start()
    clean = False
    try:
        loop.wait_exit()
        clean = loop.error is None
    finally:
        if hb is not None:
            # a clean end of training (every trainer said COMPLETE, no
            # apply error) says goodbye; anything else is a DIRTY exit —
            # the lease ages out and, when armed, the flight recorder
            # writes this pserver's post-mortem
            hb.stop(bye=clean)
        loop.close_snapshotters()
        server.stop()


@register_host_op("delete_var")
def _delete_var(exe, program, op, scope):
    """delete_var_op.cc: drop variables from the scope (frees device
    buffers; the reference used it for eager GC of step scopes)."""
    for name in op.input("X"):
        scope.erase(name)
