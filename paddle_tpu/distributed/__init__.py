"""Distributed parameter-server stack (TPU-native redesign).

The reference implements pserver-mode distribution as gRPC variable
transport (``paddle/fluid/operators/distributed/grpc_client.h:175``,
``grpc_server.cc:82``), RPC ops run by the op-loop executor
(``send_op.cc:29``, ``recv_op.cc:28``, ``listen_and_serv_op.cc:102,213``)
and a program rewrite (``python/paddle/fluid/transpiler/
distribute_transpiler.py:144,237``).

Here the same capability is built TPU-first:

- device compute stays whole-block-jitted; RPC ops are *host ops*
  (``core/host_ops.py``) run between device segments by the Executor;
- variable transport is a framed-TCP service (``transport.py`` +
  ``serde.py``) carrying dense tensors and SelectedRows sparse slices over
  DCN — the role NCCL cannot play for sparse/pserver traffic;
- ``DistributeTranspiler`` rewrites the trainer program (grads → send /
  params ← recv) and emits per-endpoint pserver programs whose optimize
  sub-blocks the pserver event loop executes as jitted mini-programs.
"""
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from . import ps_ops  # noqa: F401  (registers the host ops)
from . import transport
from .transport import wait_server_ready
from .master import MasterClient, TaskMaster, serve_master, task_reader  # noqa: F401


def notify_complete(endpoints, trainer_id: int = 0) -> None:
    """Tell every pserver this trainer is done (reference SendComplete,
    ``executor.cc:86-92`` / ``grpc_client.h`` AsyncSendComplete).  When all
    trainers have completed, ``listen_and_serv`` returns."""
    client = transport.get_client(trainer_id)
    client.parallel([(client.complete, ep) for ep in endpoints])


def notify_checkpoint(endpoints, dirname, step=None,
                      trainer_id: int = 0,
                      connect_timeout: float = 10.0):
    """Ask every pserver to checkpoint into ``dirname`` — the fleet-cut
    trigger of the elastic-resize story.  ``step`` stamps an explicit
    cut step id (sharded checkpoints commit two-phase once every
    pserver's piece for that step lands; poll
    ``checkpoint.wait_step_complete`` on a shared filesystem to learn
    the commit happened).  Best-effort-ALL fan-out: one unreachable
    pserver is counted + summarized, the rest are still notified, and
    only an all-endpoints failure raises.  Returns
    ``[(endpoint, error-or-None), ...]``."""
    client = transport.get_client(trainer_id)
    return ps_ops.broadcast_checkpoint_notify(
        client, endpoints, dirname, step=step,
        connect_timeout=connect_timeout)


__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "notify_checkpoint", "notify_complete", "wait_server_ready"]
