"""Service discovery / elastic re-binding for pserver mode.

Reference: the etcd-backed discovery of the Go pserver world —
``go/pserver/etcd_client.go:1`` (pservers register themselves under TTL
leases and claim shard slots) and ``go/pserver/client/etcd_client.go:1``
(trainers watch and re-resolve endpoints when the membership changes).

TPU-native redesign: one small registry service riding the SAME framed-TCP
transport as the variable RPC (no external etcd).  Keys are the LOGICAL
pserver endpoints the transpiler baked into the program (stable identity ≙
the etcd shard key); values are the CURRENT physical endpoint plus a TTL
lease refreshed by a heartbeat thread.  A pserver that dies and restarts
elsewhere re-registers the same logical key from its shard checkpoint;
trainers re-resolve on connection failure and carry on — no trainer
restart (the ``client.Client`` re-dial path of the reference).

The registry doubles as the fleet's health plane
(``observability/health.py``): each lease refresh may piggyback a
heartbeat payload (role, step counter, last error) that lands in a
:class:`HealthTable` with HEALTHY → SUSPECT → DEAD miss-threshold
transitions; ``REG_HEALTH`` returns the table, and a ``TaskMaster``
consulting it requeues a DEAD trainer's task leases immediately.

Enabled by ``FLAGS_pserver_registry=<host:port>`` on trainers and
pservers; off (empty) keeps the static-endpoint behavior.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from . import transport
from ..observability.health import HealthTable

# message types (continuing transport's numbering)
REG_SET = 8
REG_GET = 9
REG_HEALTH = 10

# let the transport's RPC counters name these requests
# (rpc.client.requests.reg_set, not requests.8)
transport.MSG_NAMES.update({REG_SET: "reg_set", REG_GET: "reg_get",
                            REG_HEALTH: "reg_health"})

DEFAULT_TTL = 10.0


class RegistryService:
    """handle() contract of transport.RPCServer services."""

    def __init__(self, health: Optional[HealthTable] = None):
        self._lock = threading.Lock()
        self._map: Dict[str, Tuple[str, float]] = {}  # logical -> (phys, expiry)
        self.health = health if health is not None else HealthTable()

    def handle(self, msg_type, trainer_id, name, payload):
        if msg_type == REG_SET:
            body = json.loads(bytes(payload).decode("utf-8"))
            if body.get("bye"):
                # graceful exit: drop the lease AND the health entry so a
                # cleanly-finished worker never shows up as DEAD
                with self._lock:
                    self._map.pop(name, None)
                self.health.forget(name)
                return transport.OK, b""
            ttl = float(body["ttl"])
            with self._lock:
                # sweep expired leases so retired logical endpoints don't
                # accumulate forever (REG_GET only reaps its own key)
                now = time.monotonic()
                for k in [k for k, (_, exp) in self._map.items()
                          if exp < now]:
                    del self._map[k]
                self._map[name] = (body["endpoint"], now + ttl)
            hb = body.get("health")
            if hb is not None:
                self.health.observe(
                    name, ttl=ttl, role=hb.get("role", ""),
                    step=hb.get("step"), last_error=hb.get("last_error"),
                    trainer_id=hb.get("trainer_id"))
            return transport.OK, b""
        if msg_type == REG_GET:
            with self._lock:
                ent = self._map.get(name)
                if ent is not None and ent[1] < time.monotonic():
                    del self._map[name]     # lease expired (lazy reap)
                    ent = None
            if ent is None:
                return transport.ERR, f"no live pserver for {name!r}".encode()
            return transport.OK, ent[0].encode("utf-8")
        if msg_type == REG_HEALTH:
            return transport.OK, json.dumps(
                self.health.snapshot()).encode("utf-8")
        return transport.ERR, f"registry: unknown msg {msg_type}".encode()


class RegistryServer:
    def __init__(self, endpoint: str,
                 health: Optional[HealthTable] = None):
        self.service = RegistryService(health)
        self._server = transport.RPCServer(endpoint, self.service)

    @property
    def health(self) -> HealthTable:
        return self.service.health

    @property
    def port(self) -> int:
        return self._server.port

    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop()


def register(client: "transport.RPCClient", registry_ep: str, logical: str,
             physical: str, ttl: float = DEFAULT_TTL,
             health: Optional[dict] = None) -> None:
    body = {"endpoint": physical, "ttl": ttl}
    if health is not None:
        body["health"] = health
    client._raw_request(registry_ep, REG_SET, logical,
                        json.dumps(body).encode("utf-8"), retry_all=True)


def deregister(client: "transport.RPCClient", registry_ep: str,
               logical: str) -> None:
    """Graceful goodbye: remove the lease and the health entry (a clean
    exit must not age into SUSPECT/DEAD on the registry's books)."""
    client._raw_request(registry_ep, REG_SET, logical,
                        json.dumps({"bye": True}).encode("utf-8"),
                        retry_all=True)


def resolve(client: "transport.RPCClient", registry_ep: str,
            logical: str) -> Optional[str]:
    try:
        out = client._raw_request(registry_ep, REG_GET, logical, b"",
                                  retry_all=True)
        return bytes(out).decode("utf-8")
    except RuntimeError:
        return None          # not registered / lease expired


def fetch_health(client: "transport.RPCClient", registry_ep: str,
                 connect_timeout: Optional[float] = None) -> Dict[str, dict]:
    """The registry's health table: {worker: {state, role, step, ...}}."""
    out = client._raw_request(registry_ep, REG_HEALTH, retry_all=True,
                              connect_timeout=connect_timeout)
    return json.loads(bytes(out).decode("utf-8"))


class Heartbeat:
    """Daemon lease-refresher (etcd_client.go keepalive analogue).

    ``health_fn`` (optional) is called per refresh and its dict — role,
    step counter, last_error, trainer_id — rides the REG_SET into the
    registry's :class:`HealthTable`; a worker whose heartbeat stops is
    marked SUSPECT then DEAD by miss thresholds (health.py).  Static
    fields can be passed as ``role``/``trainer_id`` without a callable.
    """

    def __init__(self, registry_ep: str, logical: str, physical: str,
                 ttl: float = DEFAULT_TTL, trainer_id: int = 0,
                 role: str = "", health_fn: Optional[Callable[[], dict]] = None):
        self.registry_ep = registry_ep
        self.logical = logical
        self.physical = physical
        self.ttl = ttl
        self.role = role
        self.trainer_id = trainer_id
        self.health_fn = health_fn
        self._client = transport.RPCClient(trainer_id)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"registry-hb-{logical}")

    def _health_payload(self) -> dict:
        hb = {"role": self.role, "trainer_id": self.trainer_id}
        if self.health_fn is not None:
            try:
                hb.update(self.health_fn() or {})
            except Exception as e:  # a broken probe must not stop the lease
                hb["last_error"] = repr(e)[:200]
        return hb

    def _register_once(self) -> None:
        register(self._client, self.registry_ep, self.logical,
                 self.physical, self.ttl, health=self._health_payload())

    def start(self):
        self._register_once()
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.ttl / 3.0):
            try:
                self._register_once()
            except Exception:
                pass             # registry briefly down: keep trying

    def stop(self, bye: bool = False):
        """Stop refreshing.  ``bye=True`` additionally deregisters (the
        clean-shutdown path); the default leaves the lease to expire —
        which is also what an actual crash looks like to the registry,
        so it counts as a DIRTY exit: with the flight recorder armed
        (``FLAGS_flight_record_dir``) this worker writes its post-mortem
        (recent + in-flight spans, log events, step tail) on the way
        out — the registry's DEAD gauge flip gets a black box to read."""
        self._stop.set()
        if bye:
            try:
                deregister(self._client, self.registry_ep, self.logical)
            except Exception:
                pass         # registry already gone: nothing to clean
        else:
            from ..observability import flight as _flight
            _flight.dirty_exit(f"heartbeat_stop:{self.logical}")
