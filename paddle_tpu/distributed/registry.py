"""Service discovery / elastic re-binding / HA promotion for pserver mode.

Reference: the etcd-backed discovery of the Go pserver world —
``go/pserver/etcd_client.go:1`` (pservers register themselves under TTL
leases and claim shard slots) and ``go/pserver/client/etcd_client.go:1``
(trainers watch and re-resolve endpoints when the membership changes).

TPU-native redesign: one small registry service riding the SAME framed-TCP
transport as the variable RPC (no external etcd).  Keys are the LOGICAL
pserver endpoints the transpiler baked into the program (stable identity ≙
the etcd shard key); values are the CURRENT physical endpoint plus a TTL
lease refreshed by a heartbeat thread.  A pserver that dies and restarts
elsewhere re-registers the same logical key from its shard checkpoint;
trainers re-resolve on connection failure and carry on — no trainer
restart (the ``client.Client`` re-dial path of the reference).

The registry doubles as the fleet's health plane
(``observability/health.py``): each lease refresh may piggyback a
heartbeat payload (role, step counter, last error) that lands in a
:class:`HealthTable` with HEALTHY → SUSPECT → DEAD miss-threshold
transitions; ``REG_HEALTH`` returns the table, and a ``TaskMaster``
consulting it requeues a DEAD trainer's task leases immediately.

HA layer (the etcd lease/election analogue, rebuilt on the same table):

- **Standby registrations** — a replica registers under the SAME logical
  key with ``standby=<candidate_id>``.  While the primary's lease is
  live, standbys are invisible to ``REG_GET``.  When the primary's lease
  expires (the registry's own DEAD transition for that key), the lowest-
  id live standby is *promoted*: its address becomes the logical key's
  resolution, the promotion is appended to an ordered log, and the
  promoted worker learns of it in its next lease-refresh response (no
  extra RPC).  ``elect=True`` standbys (master candidates) also win an
  INITIAL election when no primary ever registered — lowest id wins —
  while plain standbys (pserver backups) only ever succeed a primary
  that existed, so a backup that boots first cannot steal the key.
- **Data mirror** — a registration may carry an opaque ``data`` payload
  (the HA master publishes its task-lease table here on every state
  transition, the per-change etcd put of ``go/master/service.go:207``).
- **REG_SNAPSHOT / watch replay** — returns the whole table (leases,
  standbys, data, promotion log) plus a monotonic change ``seq``;
  a standby polls it and applies snapshots with a newer seq — the etcd
  watch loop collapsed into cheap snapshot replay.

Enabled by ``FLAGS_pserver_registry=<host:port>`` on trainers and
pservers; off (empty) keeps the static-endpoint behavior.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import transport
from ..observability import canary as _canary
from ..observability import memory as _memory
from ..observability import flight as _flight
from ..observability import slo as _slo
from ..observability import stats as _obs_stats
from ..observability.health import HealthTable
from ..observability.trace import flags_on as _telemetry_on

# message types (continuing transport's numbering)
REG_SET = 8
REG_GET = 9
REG_HEALTH = 10
REG_SNAPSHOT = 13

# let the transport's RPC counters name these requests
# (rpc.client.requests.reg_set, not requests.8)
transport.MSG_NAMES.update({REG_SET: "reg_set", REG_GET: "reg_get",
                            REG_HEALTH: "reg_health",
                            REG_SNAPSHOT: "reg_snapshot"})

DEFAULT_TTL = 10.0

# promotion log retention: enough for any chaos scenario's full history
# without letting a long-lived registry grow without bound
_PROMOTION_LOG = 256


class _Standby:
    __slots__ = ("cand", "endpoint", "expiry", "ttl", "elect")

    def __init__(self, cand: int, endpoint: str, expiry: float, ttl: float,
                 elect: bool):
        self.cand = cand
        self.endpoint = endpoint
        self.expiry = expiry
        self.ttl = ttl
        self.elect = elect


class RegistryService:
    """handle() contract of transport.RPCServer services."""

    def __init__(self, health: Optional[HealthTable] = None):
        self._lock = threading.Lock()
        self._map: Dict[str, Tuple[str, float]] = {}  # logical -> (phys, expiry)
        # HA state --------------------------------------------------------
        self._standby: Dict[str, Dict[int, _Standby]] = {}
        self._had_primary: set = set()      # logicals that EVER had a primary
        # promotion fencing: logical -> the address DEPOSED by the last
        # promotion.  A zombie primary (lease lost to a partition, or a
        # supervisor restart with pre-promotion state) re-claiming its
        # old key while the promoted holder is live would flip-flop the
        # fleet between divergent replicas — it is refused ("demoted")
        # and must re-join as a standby.  The fence lifts if the
        # promoted holder itself dies with no standby (better the
        # zombie than nobody).
        self._fenced: Dict[str, str] = {}
        # revoked standbys: logical -> {physical endpoints}.  A primary
        # that LOST replication to its backup revokes the backup's
        # candidacy here (the registry is the promotion authority): a
        # replica missing acknowledged frames must never be promoted —
        # silent state rollback is worse than no failover.  Permanent
        # for the registry's lifetime (there is no resync protocol; a
        # resynced replacement re-joins under a fresh address).
        self._revoked: Dict[str, set] = {}
        self._data: Dict[str, object] = {}  # logical -> opaque mirror payload
        self._seq = 0                       # bumped on every table change
        self._promotions: List[dict] = []   # ordered promotion log
        self.health = health if health is not None else HealthTable()

    # -- HA helpers (call with self._lock held) ---------------------------
    def _promote_if_needed(self, logical: str, now: float) -> None:
        """The lease-expiry → promotion transition for one logical key:
        when the primary's lease is gone, hand the key to the lowest-id
        live standby (initial election requires ``elect``)."""
        ent = self._map.get(logical)
        if ent is not None and ent[1] >= now:
            return                      # primary lease still live
        cands = self._standby.get(logical)
        if not cands:
            return
        revoked = self._revoked.get(logical, ())
        live = [s for s in cands.values()
                if s.expiry >= now and s.endpoint not in revoked]
        if logical not in self._had_primary:
            live = [s for s in live if s.elect]
        if not live:
            return
        winner = min(live, key=lambda s: s.cand)
        old = ent[0] if ent is not None else None
        self._map[logical] = (winner.endpoint, now + winner.ttl)
        self._had_primary.add(logical)
        if old is not None and old != winner.endpoint:
            self._fenced[logical] = old
        del cands[winner.cand]
        self._seq += 1
        self._promotions.append({
            "ts": time.time(), "logical": logical, "old": old,
            "new": winner.endpoint, "cand": winner.cand, "seq": self._seq})
        del self._promotions[:-_PROMOTION_LOG]
        if _telemetry_on():
            _obs_stats.counter(
                "registry.promotions",
                "standby replicas promoted to primary after the "
                "primary's lease expired").inc()
        # the flight-recorder note chain a chaos post-mortem reads:
        # primary death (lease expiry) -> THIS promotion -> the trainers'
        # rpc_failover re-resolutions
        _flight.note("registry_promote", logical=logical, old=old,
                     new=winner.endpoint, cand=winner.cand)

    def _sweep(self, now: float) -> None:
        """Reap expired leases; promotion gets first claim on every key
        that just lost its primary (the DEAD transition must hand over,
        not silently forget)."""
        for k in list(self._standby):
            self._promote_if_needed(k, now)
            cands = self._standby[k]
            for cid in [c for c, s in cands.items() if s.expiry < now]:
                del cands[cid]
                self._seq += 1
            if not cands:
                del self._standby[k]
        for k in [k for k, (_, exp) in self._map.items() if exp < now]:
            del self._map[k]
            self._seq += 1

    def handle(self, msg_type, trainer_id, name, payload):
        if msg_type == REG_SET:
            body = json.loads(bytes(payload).decode("utf-8"))
            if body.get("bye"):
                # graceful exit: drop the lease AND the health entry so a
                # cleanly-finished worker never shows up as DEAD
                with self._lock:
                    if self._map.pop(name, None) is not None:
                        self._seq += 1
                    cands = self._standby.get(name)
                    cand = body.get("standby")
                    if cands is not None and cand is not None \
                            and cands.pop(int(cand), None) is not None:
                        self._seq += 1
                self.health.forget(name)
                return transport.OK, b""
            if body.get("revoke_standby"):
                # a primary lost replication to this standby: the replica
                # is missing acknowledged frames and must never win a
                # promotion.  Strike its candidacy and remember the
                # address (see self._revoked).
                target = body["revoke_standby"]
                with self._lock:
                    self._revoked.setdefault(name, set()).add(target)
                    cands = self._standby.get(name)
                    if cands is not None:
                        for cid in [c for c, s in cands.items()
                                    if s.endpoint == target]:
                            del cands[cid]
                        if not cands:
                            del self._standby[name]
                    self._seq += 1
                if _telemetry_on():
                    _obs_stats.counter(
                        "registry.standby_revokes",
                        "standby candidacies revoked after the primary "
                        "lost replication to them").inc()
                _flight.note("standby_revoked", logical=name,
                             endpoint=target)
                return transport.OK, b"{}"
            if "endpoint" not in body:
                # data-only publish (the HA master's per-transition state
                # put): no lease touched, just the mirror payload + seq
                with self._lock:
                    self._data[name] = body.get("data")
                    self._seq += 1
                return transport.OK, b"{}"
            if body.get("observe"):
                # health-only refresh (a withdrawn standby keeps its
                # fleet-health presence without renewing any candidacy
                # or claiming the key)
                hb = body.get("health")
                if hb is not None:
                    self.health.observe(
                        name, ttl=float(body["ttl"]),
                        role=hb.get("role", ""), step=hb.get("step"),
                        last_error=hb.get("last_error"),
                        trainer_id=hb.get("trainer_id"),
                        standby=hb.get("standby"), slo=hb.get("slo"),
                        slo_rules=hb.get("slo_rules"),
                        canary=hb.get("canary"),
                        canary_targets=hb.get("canary_targets"),
                        memory=hb.get("memory"),
                        memory_pools=hb.get("memory_pools"))
                return transport.OK, b"{}"
            ttl = float(body["ttl"])
            now = time.monotonic()
            resp = {}
            cand = body.get("standby")
            with self._lock:
                # sweep expired leases so retired logical endpoints don't
                # accumulate forever (REG_GET only reaps its own key) —
                # and so a standby whose primary just expired promotes
                self._sweep(now)
                if cand is not None and \
                        body["endpoint"] in self._revoked.get(name, ()):
                    # this replica's candidacy was revoked (it is missing
                    # acknowledged frames): refuse — it must re-join
                    # under a fresh, resynced incarnation
                    ent = self._map.get(name)
                    resp["revoked"] = True
                    resp["leader"] = (ent[0] if ent is not None
                                      and ent[1] >= now else None)
                elif cand is not None:
                    cand = int(cand)
                    ent = self._map.get(name)
                    if not (ent is not None and ent[1] >= now
                            and ent[0] == body["endpoint"]):
                        # file/refresh the candidacy BEFORE the promotion
                        # check, so this very registration can win an
                        # election (first elect-candidate up leads)
                        sb = self._standby.setdefault(name, {})
                        if cand not in sb:
                            self._seq += 1
                        sb[cand] = _Standby(cand, body["endpoint"],
                                            now + ttl, ttl,
                                            bool(body.get("elect")))
                    self._promote_if_needed(name, now)
                    ent = self._map.get(name)
                    if ent is not None and ent[0] == body["endpoint"]:
                        # this standby has been PROMOTED (by this refresh,
                        # or by an earlier REG_GET): refresh the primary
                        # lease it now holds and tell it so
                        self._map[name] = (body["endpoint"], now + ttl)
                        sb = self._standby.get(name)
                        if sb is not None:
                            sb.pop(cand, None)
                        resp["promoted"] = True
                    else:
                        resp["leader"] = ent[0] if ent is not None else None
                else:
                    ent = self._map.get(name)
                    if ent is not None and ent[1] >= now \
                            and ent[0] != body["endpoint"] \
                            and self._fenced.get(name) == body["endpoint"]:
                        # the address deposed by the last promotion is
                        # back while the promoted holder is LIVE: refuse
                        # the claim (see _fenced above)
                        resp["demoted"] = True
                        resp["leader"] = ent[0]
                    else:
                        if ent is None or ent[1] < now:
                            self._fenced.pop(name, None)
                        if (ent or (None,))[0] != body["endpoint"]:
                            self._seq += 1
                        self._map[name] = (body["endpoint"], now + ttl)
                        self._had_primary.add(name)
                if "data" in body:
                    self._data[name] = body["data"]
                    self._seq += 1
            hb = body.get("health")
            if hb is not None:
                self.health.observe(
                    name, ttl=ttl, role=hb.get("role", ""),
                    step=hb.get("step"), last_error=hb.get("last_error"),
                    trainer_id=hb.get("trainer_id"),
                    standby=hb.get("standby"), slo=hb.get("slo"),
                    slo_rules=hb.get("slo_rules"),
                    canary=hb.get("canary"),
                    canary_targets=hb.get("canary_targets"),
                    memory=hb.get("memory"),
                    memory_pools=hb.get("memory_pools"))
            # plain primary registrations keep the PR-5 empty response
            # byte-identical; only HA registrations carry an answer
            return (transport.OK,
                    json.dumps(resp).encode("utf-8") if resp else b"")
        if msg_type == REG_GET:
            now = time.monotonic()
            with self._lock:
                self._promote_if_needed(name, now)
                ent = self._map.get(name)
                if ent is not None and ent[1] < now:
                    del self._map[name]     # lease expired (lazy reap)
                    self._seq += 1
                    ent = None
            if ent is None:
                return transport.ERR, f"no live pserver for {name!r}".encode()
            return transport.OK, ent[0].encode("utf-8")
        if msg_type == REG_HEALTH:
            return transport.OK, json.dumps(
                self.health.snapshot()).encode("utf-8")
        if msg_type == REG_SNAPSHOT:
            return transport.OK, json.dumps(self.snapshot()).encode("utf-8")
        return transport.ERR, f"registry: unknown msg {msg_type}".encode()

    def snapshot(self) -> dict:
        """The whole table with a monotonic ``seq`` — the watch-replay
        payload a standby master mirrors.  Expiries are exported as
        REMAINING seconds (monotonic clocks don't cross processes)."""
        now = time.monotonic()
        with self._lock:
            self._sweep(now)
            return {
                "seq": self._seq,
                "leases": {k: {"endpoint": ep,
                               "ttl_left": round(exp - now, 3)}
                           for k, (ep, exp) in self._map.items()},
                "standbys": {k: {str(s.cand): {"endpoint": s.endpoint,
                                               "ttl_left": round(
                                                   s.expiry - now, 3),
                                               "elect": s.elect}
                                 for s in cands.values()}
                             for k, cands in self._standby.items()},
                "data": dict(self._data),
                "promotions": [dict(p) for p in self._promotions],
                "revoked": {k: sorted(v)
                            for k, v in self._revoked.items() if v},
            }


class RegistryServer:
    def __init__(self, endpoint: str,
                 health: Optional[HealthTable] = None):
        self.service = RegistryService(health)
        self._server = transport.RPCServer(endpoint, self.service)

    @property
    def health(self) -> HealthTable:
        return self.service.health

    @property
    def port(self) -> int:
        return self._server.port

    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop()


def register(client: "transport.RPCClient", registry_ep: str, logical: str,
             physical: str, ttl: float = DEFAULT_TTL,
             health: Optional[dict] = None,
             standby: Optional[int] = None, elect: bool = False,
             data=None, observe: bool = False) -> dict:
    """One lease refresh.  ``standby=<candidate_id>`` registers as a
    replica for ``logical`` instead of claiming it (``elect=True`` also
    competes in the initial election); ``data`` publishes an opaque
    mirror payload next to the lease.  Returns the registry's response —
    ``{"promoted": True}`` tells a standby it now OWNS the key."""
    body = {"endpoint": physical, "ttl": ttl}
    if observe:
        body["observe"] = True  # health-only: renew/claim nothing
    if health is not None:
        body["health"] = health
    if standby is not None:
        body["standby"] = int(standby)
        if elect:
            body["elect"] = True
    if data is not None:
        body["data"] = data
    out = client._raw_request(registry_ep, REG_SET, logical,
                              json.dumps(body).encode("utf-8"),
                              retry_all=True)
    out = bytes(out)
    return json.loads(out.decode("utf-8")) if out else {}


def revoke_standby(client: "transport.RPCClient", registry_ep: str,
                   logical: str, endpoint: str) -> None:
    """Strike ``endpoint``'s standby candidacy for ``logical``: a
    primary that lost replication calls this so its now-stale backup —
    missing frames trainers were acked for — can never be promoted.
    Permanent for the registry's lifetime (no resync protocol exists; a
    resynced replacement re-joins under a fresh address)."""
    client._raw_request(registry_ep, REG_SET, logical,
                        json.dumps({"revoke_standby": endpoint,
                                    "ttl": 0}).encode("utf-8"),
                        retry_all=True)


def publish_data(client: "transport.RPCClient", registry_ep: str,
                 logical: str, data) -> None:
    """Data-only put (no lease touched): the HA master's per-transition
    state mirror (the etcd put of go/master/service.go:207)."""
    client._raw_request(registry_ep, REG_SET, logical,
                        json.dumps({"data": data}).encode("utf-8"),
                        retry_all=True)


def deregister(client: "transport.RPCClient", registry_ep: str,
               logical: str, standby: Optional[int] = None) -> None:
    """Graceful goodbye: remove the lease and the health entry (a clean
    exit must not age into SUSPECT/DEAD on the registry's books)."""
    body = {"bye": True}
    if standby is not None:
        body["standby"] = int(standby)
    client._raw_request(registry_ep, REG_SET, logical,
                        json.dumps(body).encode("utf-8"),
                        retry_all=True)


def resolve(client: "transport.RPCClient", registry_ep: str,
            logical: str) -> Optional[str]:
    try:
        out = client._raw_request(registry_ep, REG_GET, logical, b"",
                                  retry_all=True)
        return bytes(out).decode("utf-8")
    except RuntimeError:
        return None          # not registered / lease expired


def fetch_health(client: "transport.RPCClient", registry_ep: str,
                 connect_timeout: Optional[float] = None) -> Dict[str, dict]:
    """The registry's health table: {worker: {state, role, step, ...}}."""
    out = client._raw_request(registry_ep, REG_HEALTH, retry_all=True,
                              connect_timeout=connect_timeout)
    return json.loads(bytes(out).decode("utf-8"))


def fetch_snapshot(client: "transport.RPCClient", registry_ep: str,
                   connect_timeout: Optional[float] = None) -> dict:
    """One REG_SNAPSHOT: the full lease/standby/data table plus change
    seq — the standby master's watch-replay pull."""
    out = client._raw_request(registry_ep, REG_SNAPSHOT, retry_all=True,
                              connect_timeout=connect_timeout)
    return json.loads(bytes(out).decode("utf-8"))


class Heartbeat:
    """Daemon lease-refresher (etcd_client.go keepalive analogue).

    ``health_fn`` (optional) is called per refresh and its dict — role,
    step counter, last_error, trainer_id — rides the REG_SET into the
    registry's :class:`HealthTable`; a worker whose heartbeat stops is
    marked SUSPECT then DEAD by miss thresholds (health.py).  Static
    fields can be passed as ``role``/``trainer_id`` without a callable.

    HA extensions: ``standby=<candidate_id>`` heartbeats as a replica of
    ``logical`` (``elect=True`` competes in the initial election); when
    the registry answers a refresh with ``promoted``, the heartbeat
    flips itself to primary mode and fires ``on_promote()`` exactly once
    — promotion rides the lease keepalive, no extra RPC.  ``data_fn``
    (optional) publishes its return value next to the lease on every
    refresh (the leader master's state mirror).
    """

    def __init__(self, registry_ep: str, logical: str, physical: str,
                 ttl: float = DEFAULT_TTL, trainer_id: int = 0,
                 role: str = "", health_fn: Optional[Callable[[], dict]] = None,
                 standby: Optional[int] = None, elect: bool = False,
                 data_fn: Optional[Callable[[], object]] = None,
                 on_promote: Optional[Callable[[], None]] = None,
                 on_demote: Optional[Callable[[], None]] = None,
                 on_revoke: Optional[Callable[[], None]] = None):
        self.registry_ep = registry_ep
        self.logical = logical
        self.physical = physical
        self.ttl = ttl
        self.role = role
        self.trainer_id = trainer_id
        self.health_fn = health_fn
        self.standby = standby
        self.elect = elect
        self.data_fn = data_fn
        self.on_promote = on_promote
        self.on_demote = on_demote
        self.on_revoke = on_revoke
        self.promoted = standby is None
        self._demoted = False
        self._revoked = False
        self._observe = False   # withdraw(): health-only refreshes
        self._client = transport.RPCClient(trainer_id)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"registry-hb-{logical}")

    def _health_payload(self) -> dict:
        hb = {"role": self.role, "trainer_id": self.trainer_id}
        if self.standby is not None and not self.promoted:
            # fleet health view shows who is warm-sparing this key
            hb["standby"] = self.standby
        # SLO watchdog dimension (observability/slo.py): when this
        # process runs a watchdog, its breach state rides every
        # heartbeat — the fleet health table / ElasticController /
        # supervisor consume it with zero new RPCs.  No watchdog (the
        # default): nothing added, the payload stays byte-identical
        slo_dim = _slo.health_dimension()
        if slo_dim:
            hb.update(slo_dim)
        # correctness dimension (observability/canary.py): same
        # discipline — a process running an armed prober stamps its
        # golden-canary verdict on every heartbeat; flag off adds
        # nothing (payload byte-identical)
        canary_dim = _canary.health_dimension()
        if canary_dim:
            hb.update(canary_dim)
        # memory dimension (observability/memory.py): a process running
        # the leak sentinel stamps its last refcount-audit verdict on
        # every heartbeat; flag off adds nothing (payload byte-identical)
        mem_dim = _memory.health_dimension()
        if mem_dim:
            hb.update(mem_dim)
        if self.health_fn is not None:
            try:
                hb.update(self.health_fn() or {})
            except Exception as e:  # a broken probe must not stop the lease
                hb["last_error"] = repr(e)[:200]
        return hb

    def _register_once(self) -> None:
        if self._observe:
            # withdrawn (stale replica): keep the fleet-health presence,
            # renew no candidacy, claim nothing
            register(self._client, self.registry_ep, self.logical,
                     self.physical, self.ttl,
                     health=self._health_payload(), observe=True)
            return
        data = None
        if self.data_fn is not None:
            try:
                data = self.data_fn()
            except Exception:  # a broken publisher must not stop the lease
                data = None
        resp = register(self._client, self.registry_ep, self.logical,
                        self.physical, self.ttl,
                        health=self._health_payload(),
                        standby=None if self.promoted else self.standby,
                        elect=self.elect, data=data)
        if resp.get("promoted") and not self.promoted:
            self.promoted = True
            _flight.note("heartbeat_promoted", logical=self.logical,
                         physical=self.physical, cand=self.standby)
            if self.on_promote is not None:
                try:
                    self.on_promote()
                except Exception as e:
                    _flight.note("on_promote_failed", error=repr(e)[:200])
        elif resp.get("demoted") and not self._demoted:
            # the registry fenced this worker's claim: a backup was
            # promoted over it while it was away (partition / restart
            # with pre-promotion state).  Keep heartbeating — the fleet
            # health view should still see the process — but say it
            # ONCE, loudly: this replica must not serve primary duty
            self._demoted = True
            print(f"[registry] {self.logical}: claim REFUSED — "
                  f"{resp.get('leader')} was promoted over this worker; "
                  "re-join as a standby", flush=True)
            _flight.note("heartbeat_demoted", logical=self.logical,
                         physical=self.physical,
                         leader=resp.get("leader"))
            if self.on_demote is not None:
                try:
                    self.on_demote()
                except Exception as e:
                    _flight.note("on_demote_failed", error=repr(e)[:200])
        elif resp.get("revoked") and not self._revoked:
            # the primary struck this replica's candidacy (replication
            # was lost: we are missing acknowledged frames and must
            # never be promoted)
            self._revoked = True
            print(f"[registry] {self.logical}: standby candidacy "
                  "REVOKED (replication lost — this replica is stale)",
                  flush=True)
            _flight.note("heartbeat_revoked", logical=self.logical,
                         physical=self.physical)
            if self.on_revoke is not None:
                try:
                    self.on_revoke()
                except Exception as e:
                    _flight.note("on_revoke_failed", error=repr(e)[:200])

    def withdraw(self) -> None:
        """Drop out of candidacy (a stale replica must never be
        promoted): future refreshes become health-only, and the current
        standby entry is struck immediately (best-effort — if the
        registry is briefly unreachable the entry still ages out within
        one ttl, since observe-mode refreshes never renew it)."""
        if self._observe:
            return
        self._observe = True
        _flight.note("heartbeat_withdrawn", logical=self.logical,
                     physical=self.physical)
        try:
            revoke_standby(self._client, self.registry_ep, self.logical,
                           self.physical)
        except Exception:
            pass

    def start(self):
        self._register_once()
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.ttl / 3.0):
            try:
                self._register_once()
            except Exception:
                pass             # registry briefly down: keep trying

    def stop(self, bye: bool = False):
        """Stop refreshing.  ``bye=True`` additionally deregisters (the
        clean-shutdown path); the default leaves the lease to expire —
        which is also what an actual crash looks like to the registry,
        so it counts as a DIRTY exit: with the flight recorder armed
        (``FLAGS_flight_record_dir``) this worker writes its post-mortem
        (recent + in-flight spans, log events, step tail) on the way
        out — the registry's DEAD gauge flip gets a black box to read."""
        self._stop.set()
        if bye:
            # quiesce the refresher FIRST: an in-flight REG_SET landing
            # after the goodbye would re-file the lease we just dropped
            # (bounded join — a black-holed registry must not hang the
            # clean-shutdown path)
            if self._thread.is_alive() \
                    and self._thread is not threading.current_thread():
                self._thread.join(timeout=max(2.0, 2 * self.ttl))
            try:
                deregister(self._client, self.registry_ep, self.logical,
                           standby=None if self.promoted else self.standby)
            except Exception:
                pass         # registry already gone: nothing to clean
        else:
            _flight.dirty_exit(f"heartbeat_stop:{self.logical}")
