"""Service discovery / elastic re-binding for pserver mode.

Reference: the etcd-backed discovery of the Go pserver world —
``go/pserver/etcd_client.go:1`` (pservers register themselves under TTL
leases and claim shard slots) and ``go/pserver/client/etcd_client.go:1``
(trainers watch and re-resolve endpoints when the membership changes).

TPU-native redesign: one small registry service riding the SAME framed-TCP
transport as the variable RPC (no external etcd).  Keys are the LOGICAL
pserver endpoints the transpiler baked into the program (stable identity ≙
the etcd shard key); values are the CURRENT physical endpoint plus a TTL
lease refreshed by a heartbeat thread.  A pserver that dies and restarts
elsewhere re-registers the same logical key from its shard checkpoint;
trainers re-resolve on connection failure and carry on — no trainer
restart (the ``client.Client`` re-dial path of the reference).

Enabled by ``FLAGS_pserver_registry=<host:port>`` on trainers and
pservers; off (empty) keeps the static-endpoint behavior.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional, Tuple

from . import transport

# message types (continuing transport's numbering)
REG_SET = 8
REG_GET = 9

DEFAULT_TTL = 10.0


class RegistryService:
    """handle() contract of transport.RPCServer services."""

    def __init__(self):
        self._lock = threading.Lock()
        self._map: Dict[str, Tuple[str, float]] = {}  # logical -> (phys, expiry)

    def handle(self, msg_type, trainer_id, name, payload):
        if msg_type == REG_SET:
            body = json.loads(payload.decode("utf-8"))
            with self._lock:
                # sweep expired leases so retired logical endpoints don't
                # accumulate forever (REG_GET only reaps its own key)
                now = time.monotonic()
                for k in [k for k, (_, exp) in self._map.items()
                          if exp < now]:
                    del self._map[k]
                self._map[name] = (body["endpoint"],
                                   now + float(body["ttl"]))
            return transport.OK, b""
        if msg_type == REG_GET:
            with self._lock:
                ent = self._map.get(name)
                if ent is not None and ent[1] < time.monotonic():
                    del self._map[name]     # lease expired (lazy reap)
                    ent = None
            if ent is None:
                return transport.ERR, f"no live pserver for {name!r}".encode()
            return transport.OK, ent[0].encode("utf-8")
        return transport.ERR, f"registry: unknown msg {msg_type}".encode()


class RegistryServer:
    def __init__(self, endpoint: str):
        self.service = RegistryService()
        self._server = transport.RPCServer(endpoint, self.service)

    @property
    def port(self) -> int:
        return self._server.port

    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop()


def register(client: "transport.RPCClient", registry_ep: str, logical: str,
             physical: str, ttl: float = DEFAULT_TTL) -> None:
    payload = json.dumps({"endpoint": physical, "ttl": ttl}).encode("utf-8")
    client._raw_request(registry_ep, REG_SET, logical, payload,
                        retry_all=True)


def resolve(client: "transport.RPCClient", registry_ep: str,
            logical: str) -> Optional[str]:
    try:
        out = client._raw_request(registry_ep, REG_GET, logical, b"",
                                  retry_all=True)
        return out.decode("utf-8")
    except RuntimeError:
        return None          # not registered / lease expired


class Heartbeat:
    """Daemon lease-refresher (etcd_client.go keepalive analogue)."""

    def __init__(self, registry_ep: str, logical: str, physical: str,
                 ttl: float = DEFAULT_TTL, trainer_id: int = 0):
        self.registry_ep = registry_ep
        self.logical = logical
        self.physical = physical
        self.ttl = ttl
        self._client = transport.RPCClient(trainer_id)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"registry-hb-{logical}")

    def start(self):
        register(self._client, self.registry_ep, self.logical,
                 self.physical, self.ttl)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.ttl / 3.0):
            try:
                register(self._client, self.registry_ep, self.logical,
                         self.physical, self.ttl)
            except Exception:
                pass             # registry briefly down: keep trying

    def stop(self):
        self._stop.set()
