"""Binary serialization of runtime values for the var-transport wire.

Reference analogue: ``VariableMessage`` proto + zero-copy serializers
(``paddle/fluid/operators/distributed/send_recv.proto.in:20-84``,
``grpc_serde.cc:35,147``).  Values are dense ndarrays or SelectedRows
sparse slices; payloads are raw row-major bytes with a small header.

Two forms per direction:

- ``dumps_value``/``loads_value``: one contiguous ``bytes`` payload
  (one memcpy each way — the original wire form, still used for small
  control payloads and by legacy peers).
- ``dumps_value_vec``/``loads_value(copy=False)``: the scatter-gather
  form.  ``dumps_value_vec`` returns a **buffer list**
  ``[header, memoryview(raw tensor bytes), ...]`` that the transport
  hands to ``socket.sendmsg``/``writev`` — the tensor bytes go from the
  ndarray straight to the kernel, no Python-level concat copy (the
  ``grpc_serde.cc:35`` zero-copy ByteBuffer role).  ``copy=False`` on
  load returns ``np.frombuffer`` views over the receive buffer: a
  100 MB gradient costs zero Python-level copies each way.

  View aliasing rules: ``copy=False`` arrays are **read-only** views
  that keep the receive buffer alive; they are safe to reduce, feed, or
  replace, but not to mutate in place.  Pass ``copy=True`` (default)
  when the caller needs a writable, independently-owned array.

Batched form (``SEND_VARS``/``GET_VARS``): ``dumps_batch_vec``/
``loads_batch`` carry many ``(name, value)`` pairs in one frame —
item = ``u16 name_len | name | u32 value_len | value`` after a ``u32``
count, with every tensor body still a gathered view.
"""
from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.selected_rows import SelectedRows

_DENSE = 0x44      # 'D'
_SELROWS = 0x53    # 'S'
_NONE = 0x4E       # 'N'

_BATCH_COUNT = struct.Struct("<I")
_BATCH_ITEM = struct.Struct("<HI")  # name_len, value_len


def _raw_view(arr: np.ndarray):
    """Contiguous byte view of ``arr`` without copying (the view keeps
    the array alive for the transport's lifetime of the buffer list)."""
    try:
        return memoryview(arr).cast("B")
    except (TypeError, ValueError):  # non-native strides etc.
        return arr.tobytes()


def _dump_dense_vec(arr: np.ndarray) -> list:
    # ascontiguousarray only when needed: it would promote 0-d to (1,)
    # and copy; contiguous inputs (the hot path) pass through untouched
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode("ascii")  # e.g. b'<f4'
    head = struct.pack("<BB", len(dt), arr.ndim) + dt
    head += struct.pack(f"<{arr.ndim}q", *arr.shape)
    return [head, _raw_view(arr)]


def _dump_dense(arr: np.ndarray) -> bytes:
    return b"".join(_dump_dense_vec(arr))


def _load_dense(buf: memoryview, off: int, copy: bool = True):
    dt_len, ndim = struct.unpack_from("<BB", buf, off)
    off += 2
    dt = np.dtype(bytes(buf[off:off + dt_len]).decode("ascii"))
    off += dt_len
    shape = struct.unpack_from(f"<{ndim}q", buf, off)
    off += 8 * ndim
    n = int(np.prod(shape)) if ndim else 1
    nbytes = n * dt.itemsize
    arr = np.frombuffer(buf[off:off + nbytes], dtype=dt).reshape(shape)
    return (arr.copy() if copy else arr), off + nbytes


def dumps_value_vec(value) -> list:
    """value → scatter-gather buffer list (bytes headers + memoryviews
    of the raw tensor bytes; zero tensor copies)."""
    if value is None:
        return [struct.pack("<B", _NONE)]
    if isinstance(value, SelectedRows):
        rows = np.asarray(value.rows)
        vals = np.asarray(value.values)
        return ([struct.pack("<Bq", _SELROWS, int(value.height))]
                + _dump_dense_vec(rows) + _dump_dense_vec(vals))
    return [struct.pack("<B", _DENSE)] + _dump_dense_vec(np.asarray(value))


def dumps_value(value) -> bytes:
    """value: None | ndarray-like | SelectedRows → bytes (one copy)."""
    return b"".join(dumps_value_vec(value))


def _load_value(buf: memoryview, off: int, copy: bool):
    kind = buf[off]
    off += 1
    if kind == _NONE:
        return None, off
    if kind == _SELROWS:
        (height,) = struct.unpack_from("<q", buf, off)
        rows, off = _load_dense(buf, off + 8, copy)
        vals, off = _load_dense(buf, off, copy)
        return SelectedRows(rows, vals, height), off
    arr, off = _load_dense(buf, off, copy)
    return arr, off


def loads_value(data, copy: bool = True):
    """bytes → None | ndarray | SelectedRows (numpy-backed).

    ``copy=False`` returns read-only ``np.frombuffer`` views over
    ``data`` (zero-copy; the views pin the buffer)."""
    value, _ = _load_value(memoryview(data), 0, copy)
    return value


# ---------------------------------------------------------------------------
# batched (name, value) payloads — the SEND_VARS / GET_VARS frame body
# ---------------------------------------------------------------------------

def buffers_nbytes(buffers: Sequence) -> int:
    return sum(len(b) if isinstance(b, (bytes, bytearray))
               else memoryview(b).nbytes for b in buffers)


def value_nbytes(value) -> int:
    """Approximate wire size of a value's tensor payload (headers
    excluded) — the stripe-balancing weight; costs no serialization."""
    if value is None:
        return 1
    if isinstance(value, SelectedRows):
        return (np.asarray(value.rows).nbytes
                + np.asarray(value.values).nbytes)
    return np.asarray(value).nbytes


def dumps_batch_vec(pairs: Sequence[Tuple[str, object]]) -> list:
    """[(name, value)] → scatter-gather buffer list for one batched
    frame.  ``value=None`` items carry no tensor (the GET_VARS request
    form — names only)."""
    out = [_BATCH_COUNT.pack(len(pairs))]
    for name, value in pairs:
        nm = name.encode("utf-8")
        vec = dumps_value_vec(value)
        out.append(_BATCH_ITEM.pack(len(nm), buffers_nbytes(vec)) + nm)
        out.extend(vec)
    return out


def dumps_batch(pairs: Sequence[Tuple[str, object]]) -> bytes:
    return b"".join(dumps_batch_vec(pairs))


def loads_batch(data, copy: bool = False) -> List[Tuple[str, object]]:
    """Batched payload → [(name, value)] in frame order.

    Defaults to ``copy=False`` (the pserver apply path): values are
    read-only views over ``data`` — see the module docstring for the
    aliasing rules."""
    buf = memoryview(data)
    (count,) = _BATCH_COUNT.unpack_from(buf, 0)
    off = _BATCH_COUNT.size
    out: List[Tuple[str, Optional[object]]] = []
    for _ in range(count):
        name_len, value_len = _BATCH_ITEM.unpack_from(buf, off)
        off += _BATCH_ITEM.size
        name = bytes(buf[off:off + name_len]).decode("utf-8")
        off += name_len
        value, end = _load_value(buf, off, copy)
        if end - off != value_len:
            raise ValueError(
                f"corrupt batch item {name!r}: declared {value_len} bytes, "
                f"decoded {end - off}")
        off = end
        out.append((name, value))
    return out
