"""Binary serialization of runtime values for the var-transport wire.

Reference analogue: ``VariableMessage`` proto + zero-copy serializers
(``paddle/fluid/operators/distributed/send_recv.proto.in:20-84``,
``grpc_serde.cc:35,147``).  Values are dense ndarrays or SelectedRows
sparse slices; payloads are raw row-major bytes with a small header, so
a 100MB gradient costs one memcpy, not a pickle walk.
"""
from __future__ import annotations

import struct

import numpy as np

from ..core.selected_rows import SelectedRows

_DENSE = 0x44      # 'D'
_SELROWS = 0x53    # 'S'
_NONE = 0x4E       # 'N'


def _dump_dense(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode("ascii")  # e.g. b'<f4'
    head = struct.pack("<BB", len(dt), arr.ndim) + dt
    head += struct.pack(f"<{arr.ndim}q", *arr.shape)
    return head + arr.tobytes()


def _load_dense(buf: memoryview, off: int):
    dt_len, ndim = struct.unpack_from("<BB", buf, off)
    off += 2
    dt = np.dtype(bytes(buf[off:off + dt_len]).decode("ascii"))
    off += dt_len
    shape = struct.unpack_from(f"<{ndim}q", buf, off)
    off += 8 * ndim
    n = int(np.prod(shape)) if ndim else 1
    nbytes = n * dt.itemsize
    arr = np.frombuffer(buf[off:off + nbytes], dtype=dt).reshape(shape)
    return arr.copy(), off + nbytes


def dumps_value(value) -> bytes:
    """value: None | ndarray-like | SelectedRows → bytes."""
    if value is None:
        return struct.pack("<B", _NONE)
    if isinstance(value, SelectedRows):
        rows = np.asarray(value.rows)
        vals = np.asarray(value.values)
        return (struct.pack("<Bq", _SELROWS, int(value.height))
                + _dump_dense(rows) + _dump_dense(vals))
    return struct.pack("<B", _DENSE) + _dump_dense(np.asarray(value))


def loads_value(data: bytes):
    """bytes → None | ndarray | SelectedRows (numpy-backed)."""
    buf = memoryview(data)
    kind = buf[0]
    if kind == _NONE:
        return None
    if kind == _SELROWS:
        (height,) = struct.unpack_from("<q", buf, 1)
        rows, off = _load_dense(buf, 9)
        vals, _ = _load_dense(buf, off)
        return SelectedRows(rows, vals, height)
    arr, _ = _load_dense(buf, 1)
    return arr
