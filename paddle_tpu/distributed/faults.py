"""Fault injection for the chaos suite: scripted wire/process failures.

The HA control plane (pserver replication, master failover) is only as
real as the failures it has survived, so the transport and the pserver
loop carry *injection points* that a chaos scenario arms with rules —
via ``FLAGS_fault_inject`` at process start, or at runtime through the
debug server's ``/chaosz`` endpoint (``tools/chaos.py`` drives a live
fleet that way).

Rule grammar (semicolon-separated rules)::

    kind[:target][:k=v[,k=v...]]

kinds
    ``drop_conn``     server: close the connection WITHOUT responding to
                      a matching request — the lost-response window of a
                      peer dying mid-request (retry/at-most-once paths).
    ``delay``         sleep ``ms`` before handling (server side) or
                      before sending (client side, ``side=client``).
    ``kill_after``    hard-kill THIS process (``os._exit(137)``) when the
                      matching request/event counter reaches ``n`` — the
                      "kill primary pserver after N batches" scenario.
    ``refuse_accept`` server: close every new connection immediately
                      (accept-then-slam), bounded by ``for_s``/``times``.
    ``diskfull``      file-write hook (``io_fault``): raise
                      ``OSError(ENOSPC)`` at a matching write — the
                      disk filling up mid-snapshot (the checkpoint
                      store's two-phase commit must leave the previous
                      COMPLETE step authoritative).
    ``io_err``        file-write hook: raise ``OSError(EIO)`` — a dying
                      disk / dead mount at a matching write.
    ``corrupt``       data-corruption hook (``corrupt_fault``): flip
                      ``bits`` bits of one element of a reply buffer or
                      parameter shard at a matching site — silent data
                      corruption, injectable like every other fault
                      class (the correctness plane's chaos hook:
                      ``corrupt:serving_reply:n=1`` corrupts the first
                      reply; the divergence sentinel / canary prober
                      must then detect AND name the replica).
    ``oom``           device-memory hook (``oom_fault``): raise a
                      realistic ``RESOURCE_EXHAUSTED`` out-of-memory
                      error at a matching dispatch site
                      (``decode_step``, ``serving_dispatch``) — the
                      memory plane's chaos hook, so OOM forensics and
                      the decode engine's preempt-and-recover path are
                      drillable without real HBM pressure
                      (``oom:decode_step:n=3`` OOMs the third step).

target
    an RPC message name (``send_vars``, ``batch_barrier``, ``get_task``,
    ...), a loop event (``apply_round``, ``apply_async``,
    ``lease_grant``), a file-write site (``ckpt_write`` — every
    checkpoint-store / io.py atomic write), a corruption site
    (``serving_reply``, ``param_shard`` — optionally replica-qualified
    as ``serving_reply@r1``), or ``*`` / empty for any.

params
    ``n=N``      trigger from the Nth matching hit (default 1)
    ``p=0.x``    per-hit probability once armed (default 1.0)
    ``times=K``  stop after K firings (default unlimited; kill fires once)
    ``ms=X``     delay milliseconds (``delay`` kind; default 100)
    ``bits=B``   bits to flip per firing (``corrupt`` kind; default 1)
    ``for_s=X``  rule disarms X seconds after installation
    ``side=client|server|any``  which hook honors it (default any)

Example: kill the primary pserver mid-round after 3 applied rounds::

    FLAGS_fault_inject="kill_after:apply_round:n=3"

Flap the wire under barriers, 30% of them, for 5 seconds::

    FLAGS_fault_inject="drop_conn:batch_barrier:p=0.3,for_s=5"

With the flag unset and no runtime rules installed (the default), every
hook is one cheap guard — no threads, no RPCs, no wire changes; the
transport is byte-identical to the fault-free build.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional

from ..observability import flight as _flight
from ..observability import stats as _obs_stats
from ..observability.trace import flags_on as _telemetry_on

DROP_CONN = "drop_conn"
DELAY = "delay"
KILL_AFTER = "kill_after"
REFUSE_ACCEPT = "refuse_accept"
DISKFULL = "diskfull"
IO_ERR = "io_err"
CORRUPT = "corrupt"
OOM = "oom"
_KINDS = (DROP_CONN, DELAY, KILL_AFTER, REFUSE_ACCEPT, DISKFULL, IO_ERR,
          CORRUPT, OOM)
# kinds the file-write hook honors (a wildcard drop_conn rule must not
# be consumed — or fired — by a write site it can't apply to)
_IO_KINDS = (DISKFULL, IO_ERR, DELAY, KILL_AFTER)
# kinds only a dedicated dispatcher may consume — a wire/event hook
# must neither fire them nor burn their budget
_SITE_KINDS = (DISKFULL, IO_ERR, CORRUPT, OOM)

_lock = threading.Lock()
_runtime_rules: List["Rule"] = []
_flag_cache: Dict[str, List["Rule"]] = {}


class Rule:
    __slots__ = ("kind", "target", "n", "p", "times", "ms", "bits",
                 "for_s", "side", "source", "armed_at", "hits", "fires")

    def __init__(self, kind: str, target: str = "", n: int = 1,
                 p: float = 1.0, times: Optional[int] = None,
                 ms: float = 100.0, bits: int = 1,
                 for_s: Optional[float] = None,
                 side: str = "any", source: str = "runtime"):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(one of {', '.join(_KINDS)})")
        self.kind = kind
        self.target = "" if target in ("", "*") else target
        self.n = max(1, int(n))
        self.p = float(p)
        self.times = None if times is None else int(times)
        self.ms = float(ms)
        self.bits = max(1, int(bits))
        self.for_s = None if for_s is None else float(for_s)
        self.side = side
        self.source = source
        self.armed_at = time.monotonic()
        self.hits = 0
        self.fires = 0

    def matches(self, target: str, side: str, now: float) -> bool:
        if self.target and self.target != target:
            return False
        if self.side != "any" and self.side != side:
            return False
        if self.for_s is not None and now - self.armed_at > self.for_s:
            return False
        if self.times is not None and self.fires >= self.times:
            return False
        return True

    def fire(self) -> bool:
        """Count a matching hit; True when the rule actually fires."""
        self.hits += 1
        if self.hits < self.n:
            return False
        if self.p < 1.0 and random.random() >= self.p:
            return False
        self.fires += 1
        return True

    def to_dict(self) -> dict:
        return {"kind": self.kind, "target": self.target or "*",
                "n": self.n, "p": self.p, "times": self.times,
                "ms": self.ms, "bits": self.bits, "for_s": self.for_s,
                "side": self.side, "source": self.source,
                "hits": self.hits, "fires": self.fires}


def parse(spec: str, source: str = "runtime") -> List[Rule]:
    """Parse a rule-spec string; raises ValueError on malformed specs."""
    rules = []
    for part in (p.strip() for p in spec.split(";")):
        if not part:
            continue
        fields = part.split(":", 2)
        kind = fields[0].strip()
        target = fields[1].strip() if len(fields) > 1 else ""
        kwargs = {}
        if len(fields) > 2 and fields[2].strip():
            for kv in fields[2].split(","):
                k, _, v = kv.partition("=")
                k = k.strip()
                if k not in ("n", "p", "times", "ms", "bits", "for_s",
                             "side"):
                    raise ValueError(f"unknown fault param {k!r} in {part!r}")
                kwargs[k] = v.strip() if k == "side" else float(v)
        for k in ("n", "times", "bits"):
            if k in kwargs:
                kwargs[k] = int(kwargs[k])
        rules.append(Rule(kind, target, source=source, **kwargs))
    return rules


def _flag_spec() -> str:
    from ..core import flags
    try:
        return str(flags.get_flags("fault_inject") or "")
    except KeyError:  # pragma: no cover - flag always defined
        return ""


def _flag_rules() -> List[Rule]:
    spec = _flag_spec()
    if not spec:
        return []
    cached = _flag_cache.get(spec)
    if cached is None:
        try:
            cached = parse(spec, source="flag")
        except ValueError:
            # a malformed flag must not take the transport down; loud once
            _flight.note("fault_inject_parse_error", spec=spec[:200])
            cached = []
        _flag_cache.clear()          # flag changed: old parse is garbage
        _flag_cache[spec] = cached
    return cached


def active() -> bool:
    """Cheap guard the hot-path hooks call first."""
    return bool(_runtime_rules) or bool(_flag_spec())


def inject(spec: str) -> List[dict]:
    """Install runtime rules (the /chaosz + tools/chaos.py path)."""
    rules = parse(spec, source="runtime")
    with _lock:
        _runtime_rules.extend(rules)
    _flight.note("fault_injected", spec=spec[:200])
    return [r.to_dict() for r in rules]


def clear() -> int:
    """Remove every runtime-injected rule (flag rules persist)."""
    with _lock:
        n = len(_runtime_rules)
        _runtime_rules.clear()
    if n:
        _flight.note("faults_cleared", n=n)
    return n


def list_rules() -> List[dict]:
    with _lock:
        rules = list(_runtime_rules)
    return [r.to_dict() for r in rules + _flag_rules()]


def _match(target: str, side: str) -> Optional[Rule]:
    now = time.monotonic()
    with _lock:
        rules = list(_runtime_rules)
    for r in rules + _flag_rules():
        # site-only kinds never fire (or burn their budget) on
        # wire/event hooks — io_fault / corrupt_fault dispatch them
        if r.kind in _SITE_KINDS:
            continue
        if r.matches(target, side, now) and r.fire():
            return r
    return None


def _fired(rule: Rule, target: str) -> None:
    if _telemetry_on():
        _obs_stats.counter(
            "faults.fired." + rule.kind,
            "injected faults that actually fired, by kind").inc()
    _flight.note("fault_fired", kind=rule.kind, target=target,
                 hits=rule.hits)


def server_fault(target: str) -> Optional[str]:
    """Hook for the RPC server request loop.  Returns ``None`` (no
    fault), ``"drop_conn"`` (close without responding) — delays sleep
    in place, kills never return."""
    if not active():
        return None
    rule = _match(target, "server")
    if rule is None:
        return None
    return _apply(rule, target)


def client_fault(target: str) -> Optional[str]:
    """Hook before a client sends a request frame.  ``"drop_conn"``
    asks the caller to sever the connection instead of sending.  Only
    rules EXPLICITLY marked ``side=client`` fire here — a default
    (``side=any``) rule belongs to the server hook, so one rule never
    double-fires on both ends of the same request."""
    if not active():
        return None
    now = time.monotonic()
    with _lock:
        rules = list(_runtime_rules)
    for r in rules + _flag_rules():
        if r.kind in _SITE_KINDS:
            continue
        if r.side == "client" and r.matches(target, "client", now) \
                and r.fire():
            return _apply(r, target)
    return None


def event(target: str) -> None:
    """Count a loop event (``apply_round``, ``lease_grant``, ...) —
    only ``kill_after`` and ``delay`` rules are meaningful here."""
    if not active():
        return
    rule = _match(target, "server")
    if rule is not None:
        _apply(rule, target)


def _apply(rule: Rule, target: str) -> Optional[str]:
    _fired(rule, target)
    if rule.kind == DELAY:
        time.sleep(rule.ms / 1e3)
        return None
    if rule.kind == KILL_AFTER:
        # a HARD death (no atexit, no finally, no goodbye): exactly what
        # a kill -9 / machine loss looks like to the rest of the fleet.
        # Flush the flight recorder first — a deliberately-killed worker
        # still leaves its black box (the chaos suite reads it).
        _flight.note("fault_kill", target=target, hits=rule.hits)
        _flight.dump(f"fault_kill_{target}")
        os._exit(137)
    if rule.kind in (DROP_CONN, REFUSE_ACCEPT):
        return DROP_CONN
    return None  # pragma: no cover - all kinds handled


def io_fault(target: str) -> None:
    """Hook at a file-write site (the checkpoint store's atomic-write
    discipline, shared with io.py saves).  A matching ``diskfull`` /
    ``io_err`` rule RAISES the corresponding ``OSError`` (errno ENOSPC
    / EIO) exactly where a real write error would surface, so the
    caller's fault handling — counted fault, flight note, previous
    COMPLETE step stays authoritative — is exercised against the real
    error path, not a mock.  ``delay``/``kill_after`` rules also honor
    write targets (a slow disk, a crash mid-write)."""
    if not active():
        return
    import errno
    now = time.monotonic()
    with _lock:
        rules = list(_runtime_rules)
    for r in rules + _flag_rules():
        if r.kind in _IO_KINDS and r.matches(target, "server", now) \
                and r.fire():
            if r.kind == DISKFULL:
                _fired(r, target)
                raise OSError(errno.ENOSPC,
                              "No space left on device (injected fault)",
                              target)
            if r.kind == IO_ERR:
                _fired(r, target)
                raise OSError(errno.EIO,
                              "Input/output error (injected fault)",
                              target)
            _apply(r, target)   # delay sleeps in place; kill never returns
            return


def corrupt_fault(*targets: str) -> Optional[int]:
    """Hook at a data-corruption site (serving reply assembly, the
    parameter-checksum walk).  Callers pass their site name plus any
    replica-qualified aliases (``"serving_reply@r1"``,
    ``"serving_reply"``) so one rule can hit exactly one replica OR the
    whole site class.  A matching ``corrupt`` rule fires and returns
    the number of bits to flip (``bits`` param); ``None`` = clean.
    Like ``io_fault``, this is the ONLY dispatcher for the kind."""
    if not active():
        return None
    now = time.monotonic()
    with _lock:
        rules = list(_runtime_rules)
    for r in rules + _flag_rules():
        if r.kind != CORRUPT:
            continue
        for t in targets:
            if r.matches(t, "server", now) and r.fire():
                _fired(r, t)
                return r.bits
    return None


def corrupt_array(arr, bits: int = 1):
    """Flip ``bits`` bits of ONE element of ``arr`` (a fresh copy) —
    the silent-data-corruption model: a plausible value, not garbage.
    The largest-magnitude element is hit (a zero bit-flips into a
    denormal no tolerance check could see), and bits flip from the top
    of the element's middle byte — a float's low exponent / high
    mantissa, so the value moves by a factor ~2: far outside any sane
    canary rtol, yet still a finite number the NaN/Inf sentinel cannot
    see."""
    import numpy as np
    a = np.array(arr, copy=True)
    if a.size == 0 or a.dtype.itemsize == 0:
        return a
    flat = a.reshape(-1)
    try:
        elem = int(np.argmax(np.abs(flat).astype(np.float64)))
    except (TypeError, ValueError):
        elem = 0
    view = flat.view(np.uint8)
    itemsize = a.dtype.itemsize
    for b in range(int(bits)):
        # walk down from the top bit of the middle byte, wrapping into
        # neighboring bytes of the same element when bits > 8
        idx = elem * itemsize + (itemsize // 2 + b // 8) % itemsize
        view[idx] ^= np.uint8(1 << (7 - (b % 8)))
    return a


class InjectedResourceExhausted(RuntimeError):
    """The injected OOM: stringifies exactly like an XLA
    ``XlaRuntimeError`` out-of-memory status (``RESOURCE_EXHAUSTED:
    Out of memory while trying to allocate N bytes``), so every
    handler that pattern-matches the real error — the memory plane's
    :func:`~paddle_tpu.observability.memory.is_oom`, the decode
    engine's preempt-and-recover path — takes its production branch."""

    def __init__(self, target: str, nbytes: int = 1 << 30):
        self.target = target
        self.nbytes = int(nbytes)
        super().__init__(
            f"RESOURCE_EXHAUSTED: Out of memory while trying to "
            f"allocate {self.nbytes} bytes (injected fault at "
            f"{target})")


def oom_fault(target: str) -> None:
    """Hook at a device-dispatch site (the decode engine's step loop,
    the serving batcher's predictor dispatch).  A matching ``oom``
    rule RAISES :class:`InjectedResourceExhausted` exactly where a
    real XLA allocation failure would surface, so OOM forensics and
    recovery run against the real error path, not a mock.  Like
    ``io_fault``, this is the ONLY dispatcher for the kind."""
    if not active():
        return
    now = time.monotonic()
    with _lock:
        rules = list(_runtime_rules)
    for r in rules + _flag_rules():
        if r.kind == OOM and r.matches(target, "server", now) \
                and r.fire():
            _fired(r, target)
            raise InjectedResourceExhausted(target)


def accept_fault() -> bool:
    """Hook at connection accept: True = slam the connection shut."""
    if not active():
        return False
    now = time.monotonic()
    with _lock:
        rules = list(_runtime_rules)
    for r in rules + _flag_rules():
        if r.kind == REFUSE_ACCEPT and r.matches("accept", "server", now) \
                and r.fire():
            _fired(r, "accept")
            return True
    return False
