"""LayerHelper: shared machinery for layer functions.

Reference: ``python/paddle/fluid/layer_helper.py:49,288`` — creates
parameters (with startup-program initializer ops), temp output vars, appends
ops to the current block, and applies activations/bias.
"""
from __future__ import annotations

from typing import Optional

from .core import unique_name
from .core.program import (
    OP_ROLE_ATTR,
    Variable,
    default_main_program,
    default_startup_program,
)
from .initializer import (
    ConstantInitializer,
    XavierInitializer,
)
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    # -- programs ----------------------------------------------------------
    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    # -- inputs ------------------------------------------------------------
    def input(self, name="input"):
        inputs = self.kwargs.get(name)
        if isinstance(inputs, (list, tuple)):
            return list(inputs)
        return inputs

    def input_dtype(self, name="input"):
        inputs = self.input(name)
        if isinstance(inputs, list):
            return inputs[0].dtype
        return inputs.dtype

    # -- vars --------------------------------------------------------------
    def create_variable_for_type_inference(self, dtype, shape=None,
                                           stop_gradient=False) -> Variable:
        return self.block.create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            shape=shape,
            stop_gradient=stop_gradient,
        )

    create_tmp_variable = create_variable_for_type_inference

    def create_parameter(
        self,
        attr,
        shape,
        dtype,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Variable:
        attr = ParamAttr.to_attr(attr)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w" if not is_bias else "b"]))
        init = attr.initializer or default_initializer or (
            ConstantInitializer(0.0) if is_bias else XavierInitializer()
        )
        shape = [int(s) for s in shape]
        # declare in main program (used by ops) ...
        param = self.main_program.global_block.create_parameter(
            attr.name, shape, dtype,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
        )
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        param.gradient_clip_attr = attr.gradient_clip
        # ... and in the startup program with its initializer op
        sp_block = self.startup_program.global_block
        if not sp_block.has_var(attr.name):
            sp_param = sp_block.create_parameter(
                attr.name, shape, dtype, trainable=attr.trainable
            )
            init(sp_param, sp_block)
        return param

    def create_global_variable(self, shape, dtype, persistable=False,
                               name=None, stop_gradient=True) -> Variable:
        return self.main_program.global_block.create_var(
            name=name or unique_name.generate(".".join([self.name, "global"])),
            shape=shape, dtype=dtype, persistable=persistable,
            stop_gradient=stop_gradient,
        )

    def create_or_get_global_variable(self, shape, dtype, name, **kw):
        gb = self.main_program.global_block
        if gb.has_var(name):
            return gb.vars[name]
        return self.create_global_variable(shape, dtype, name=name, **kw)

    def set_variable_initializer(self, var, initializer):
        sp_block = self.startup_program.global_block
        if not sp_block.has_var(var.name):
            sv = sp_block.create_var(
                name=var.name, shape=var.shape, dtype=var.dtype,
                persistable=True,
            )
            initializer(sv, sp_block)

    # -- ops ---------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        program = self.main_program
        attrs = dict(attrs or {})
        attrs.setdefault(OP_ROLE_ATTR, program.op_role)
        ins = {k: self._names(v) for k, v in (inputs or {}).items()}
        outs = {k: self._names(v) for k, v in (outputs or {}).items()}
        return self.block.append_op(type, ins, outs, attrs)

    @staticmethod
    def _names(v):
        if isinstance(v, (list, tuple)):
            return [x.name if isinstance(x, Variable) else str(x) for x in v]
        return [v.name if isinstance(v, Variable) else str(v)]

    # -- activation / bias -------------------------------------------------
    def append_activation(self, input_var: Variable) -> Variable:
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(
            input_var.dtype, shape=input_var.shape
        )
        self.append_op(act_type, {"X": [input_var]}, {"Out": [out]}, act)
        return out

    def append_bias_op(self, input_var: Variable, dim_start=1, dim_end=None) -> Variable:
        bias_attr = self.kwargs.get("bias_attr")
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(bias_attr, size, input_var.dtype, is_bias=True)
        out = self.create_variable_for_type_inference(
            input_var.dtype, shape=input_var.shape
        )
        self.append_op(
            "elementwise_add", {"X": [input_var], "Y": [b]}, {"Out": [out]},
            {"axis": dim_start},
        )
        return out
