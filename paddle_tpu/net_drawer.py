"""fluid.net_drawer — program graph drawing CLI shim
(reference python/paddle/fluid/net_drawer.py: graphviz rendering of a
serialized program; the rendering engine here is
``debugger.draw_block_graphviz``)."""
from __future__ import annotations

from .debugger import draw_block_graphviz

__all__ = ["draw_graph"]


def draw_graph(startup_program, main_program, path="network.dot",
               **kwargs):
    """Emit a graphviz dot file for the main program's global block
    (reference net_drawer.draw_graph CLI contract)."""
    draw_block_graphviz(main_program.global_block, path=path, **kwargs)
    return path
