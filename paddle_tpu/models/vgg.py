"""VGG-16 (reference: benchmark/fluid/models/vgg.py)."""
from __future__ import annotations

import paddle_tpu as fluid


def conv_block(input, num_filter, groups, dropouts):
    conv = input
    for _ in range(groups):
        conv = fluid.layers.conv2d(conv, num_filter, 3, padding=1, act="relu")
    return fluid.layers.pool2d(conv, 2, "max", 2)


def vgg16(input, class_dim):
    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])
    drop = fluid.layers.dropout(conv5, 0.5)
    fc1 = fluid.layers.fc(drop, 512, act=None)
    bn = fluid.layers.batch_norm(fc1, act="relu")
    drop2 = fluid.layers.dropout(bn, 0.5)
    fc2 = fluid.layers.fc(drop2, 512, act=None)
    return fluid.layers.fc(fc2, class_dim, act="softmax")


def build(class_dim=10, image_shape=(3, 32, 32), lr=0.01, with_optimizer=True):
    input = fluid.layers.data("data", list(image_shape))
    label = fluid.layers.data("label", [1], dtype="int64")
    predict = vgg16(input, class_dim)
    cost = fluid.layers.cross_entropy(predict, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(predict, label)
    if with_optimizer:
        fluid.optimizer.Adam(lr).minimize(avg_cost)
    return ["data", "label"], avg_cost, acc
