"""Book-chapter models completing the reference test-suite zoo
(reference python/paddle/fluid/tests/book/): fit_a_line, word2vec
(N-gram LM), recommender_system (MovieLens dual-tower), and
label_semantic_roles (stacked bidirectional LSTM + linear-chain CRF).

Each ``build_*`` constructs the full train graph inside the current
program and returns (feed_names, loss, extra) — the same contract as the
other zoo models.  Shapes follow the book configs; vocab sizes are
parameters so tests can shrink them.
"""
from __future__ import annotations

import paddle_tpu as fluid


def build_fit_a_line(feature_dim=13, lr=0.01):
    """test_fit_a_line.py: linear regression on UCI housing."""
    x = fluid.layers.data("x", [feature_dim])
    y = fluid.layers.data("y", [1])
    y_predict = fluid.layers.fc(x, 1)
    cost = fluid.layers.square_error_cost(y_predict, y)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return ["x", "y"], avg_cost, y_predict


def build_word2vec(dict_size=2000, embed_size=32, hidden_size=256,
                   is_sparse=False, lr=0.001):
    """test_word2vec.py: 4-gram neural LM with a shared embedding table."""
    words = []
    embeds = []
    for name in ("firstw", "secondw", "thirdw", "forthw"):
        w = fluid.layers.data(name, [1], dtype="int64")
        words.append(name)
        embeds.append(fluid.layers.embedding(
            w, size=[dict_size, embed_size], is_sparse=is_sparse,
            param_attr=fluid.ParamAttr(name="shared_w")))
    concat = fluid.layers.concat(embeds, axis=1)
    hidden1 = fluid.layers.fc(concat, hidden_size, act="sigmoid")
    predict = fluid.layers.fc(hidden1, dict_size, act="softmax")
    next_word = fluid.layers.data("nextw", [1], dtype="int64")
    cost = fluid.layers.cross_entropy(predict, next_word)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return words + ["nextw"], avg_cost, predict


def build_recommender(usr_dict=100, gender_dict=2, age_dict=7, job_dict=21,
                      mov_dict=200, category_dict=19, title_dict=500,
                      max_title_len=10, max_cat_len=4, is_sparse=False,
                      lr=0.2):
    """test_recommender_system.py: user/movie dual towers -> cos_sim ->
    square error on the rating."""
    def emb_fc(name, vocab, emb_dim, fc_dim, table):
        did = fluid.layers.data(name, [1], dtype="int64")
        e = fluid.layers.embedding(
            did, size=[vocab, emb_dim], is_sparse=is_sparse,
            param_attr=fluid.ParamAttr(name=table))
        return name, fluid.layers.fc(e, fc_dim)

    n1, usr_fc = emb_fc("user_id", usr_dict, 32, 32, "user_table")
    n2, gender_fc = emb_fc("gender_id", gender_dict, 16, 16, "gender_table")
    n3, age_fc = emb_fc("age_id", age_dict, 16, 16, "age_table")
    n4, job_fc = emb_fc("job_id", job_dict, 16, 16, "job_table")
    usr = fluid.layers.fc(
        fluid.layers.concat([usr_fc, gender_fc, age_fc, job_fc], axis=1),
        200, act="tanh")

    n5, mov_fc = emb_fc("movie_id", mov_dict, 32, 32, "movie_table")
    cat = fluid.layers.data("category_id", [1], dtype="int64", lod_level=1)
    cat_emb = fluid.layers.embedding(cat, size=[category_dict, 32],
                                     is_sparse=is_sparse)
    cat_pool = fluid.layers.sequence_pool(cat_emb, "sum")
    title = fluid.layers.data("movie_title", [1], dtype="int64",
                              lod_level=1)
    title_emb = fluid.layers.embedding(title, size=[title_dict, 32],
                                       is_sparse=is_sparse)
    title_conv = fluid.nets.sequence_conv_pool(
        title_emb, num_filters=32, filter_size=3, act="tanh",
        pool_type="sum")
    mov = fluid.layers.fc(
        fluid.layers.concat([mov_fc, cat_pool, title_conv], axis=1),
        200, act="tanh")

    inference = fluid.layers.cos_sim(usr, mov)
    scale_infer = fluid.layers.scale(inference, scale=5.0)
    label = fluid.layers.data("score", [1])
    cost = fluid.layers.square_error_cost(scale_infer, label)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    feeds = [n1, n2, n3, n4, n5, "category_id", "category_id@LEN",
             "movie_title", "movie_title@LEN", "score"]
    return feeds, avg_cost, scale_infer


def db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark,
            word_dict_len, pred_dict_len, mark_dict_len, label_dict_len,
            word_dim=32, mark_dim=5, hidden_dim=512, depth=8):
    """test_label_semantic_roles.py db_lstm: 8 stacked alternating-direction
    LSTMs over summed input projections."""
    predicate_embedding = fluid.layers.embedding(
        predicate, size=[pred_dict_len, word_dim],
        param_attr=fluid.ParamAttr(name="vemb"))
    mark_embedding = fluid.layers.embedding(
        mark, size=[mark_dict_len, mark_dim])
    word_input = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    emb_layers = [
        fluid.layers.embedding(
            x, size=[word_dict_len, word_dim],
            param_attr=fluid.ParamAttr(name="emb", trainable=False))
        for x in word_input]
    emb_layers.append(predicate_embedding)
    emb_layers.append(mark_embedding)

    hidden_0_layers = [fluid.layers.fc(emb, hidden_dim, num_flatten_dims=2)
                       for emb in emb_layers]
    hidden_0 = fluid.layers.sums(hidden_0_layers)
    lstm_0, _ = fluid.layers.dynamic_lstm(hidden_0, hidden_dim)
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = fluid.layers.sums([
            fluid.layers.fc(input_tmp[0], hidden_dim, num_flatten_dims=2),
            fluid.layers.fc(input_tmp[1], hidden_dim, num_flatten_dims=2)])
        lstm, _ = fluid.layers.dynamic_lstm(
            mix_hidden, hidden_dim, is_reverse=((i % 2) == 1))
        input_tmp = [mix_hidden, lstm]
    feature_out = fluid.layers.sums([
        fluid.layers.fc(input_tmp[0], label_dict_len, act="tanh",
                        num_flatten_dims=2),
        fluid.layers.fc(input_tmp[1], label_dict_len, act="tanh",
                        num_flatten_dims=2)])
    return feature_out


def build_label_semantic_roles(word_dict=100, pred_dict=20, mark_dict=2,
                               label_dict=15, max_len=20, word_dim=16,
                               hidden_dim=32, depth=4, lr=0.01):
    """SRL train graph: db_lstm features -> linear_chain_crf loss +
    crf_decoding (the book config shrunk via the kwargs)."""
    names = ["word_data", "verb_data", "ctx_n2_data", "ctx_n1_data",
             "ctx_0_data", "ctx_p1_data", "ctx_p2_data", "mark_data"]
    datas = [fluid.layers.data(n, [1], dtype="int64", lod_level=1)
             for n in names]
    feature_out = db_lstm(*datas, word_dict_len=word_dict,
                          pred_dict_len=pred_dict, mark_dict_len=mark_dict,
                          label_dict_len=label_dict, word_dim=word_dim,
                          mark_dim=5, hidden_dim=hidden_dim, depth=depth)
    target = fluid.layers.data("target", [1], dtype="int64", lod_level=1)
    crf_cost = fluid.layers.linear_chain_crf(
        feature_out, target, param_attr=fluid.ParamAttr(name="crfw"))
    avg_cost = fluid.layers.mean(crf_cost)
    fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    decode = fluid.layers.crf_decoding(
        feature_out, param_attr=fluid.ParamAttr(name="crfw"))
    feeds = []
    for n in names:
        feeds += [n, n + "@LEN"]
    feeds += ["target", "target@LEN"]
    return feeds, avg_cost, decode
