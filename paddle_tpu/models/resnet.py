"""ResNet-50 (reference: benchmark/fluid/models/resnet.py).

bf16-friendly: pass dtype="bfloat16" to keep conv/matmul inputs on the MXU's
native type while BN statistics stay fp32 (ops/nn_ops.py promotes).
"""
from __future__ import annotations

import paddle_tpu as fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  layout="NCHW"):
    conv = fluid.layers.conv2d(input, ch_out, filter_size, stride, padding,
                               act=None, bias_attr=False, data_layout=layout)
    return fluid.layers.batch_norm(conv, act=act, data_layout=layout)


def shortcut(input, ch_out, stride, layout="NCHW"):
    ch_in = input.shape[-1] if layout == "NHWC" else input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             layout=layout)
    return input


def bottleneck_block(input, num_filters, stride, layout="NCHW"):
    conv0 = conv_bn_layer(input, num_filters, 1, 1, 0, layout=layout)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, 1, layout=layout)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, 1, 0, act=None,
                          layout=layout)
    short = shortcut(input, num_filters * 4, stride, layout)
    return fluid.layers.elementwise_add(short, conv2, act="relu")


def basic_block(input, num_filters, stride, layout="NCHW"):
    conv0 = conv_bn_layer(input, num_filters, 3, stride, 1, layout=layout)
    conv1 = conv_bn_layer(conv0, num_filters, 3, 1, 1, act=None,
                          layout=layout)
    short = shortcut(input, num_filters, stride, layout)
    return fluid.layers.elementwise_add(short, conv1, act="relu")


def resnet(input, class_dim, depth=50, layout="NCHW"):
    cfg = {
        18: ([2, 2, 2, 2], basic_block),
        34: ([3, 4, 6, 3], basic_block),
        50: ([3, 4, 6, 3], bottleneck_block),
        101: ([3, 4, 23, 3], bottleneck_block),
        152: ([3, 8, 36, 3], bottleneck_block),
    }
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, 64, 7, 2, 3, layout=layout)
    pool1 = fluid.layers.pool2d(conv1, 3, "max", 2, 1, data_layout=layout)
    res = pool1
    for stage, count in enumerate(stages):
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            res = block_func(res, 64 * (2 ** stage), stride, layout)
    pool2 = fluid.layers.pool2d(res, 7, "avg", global_pooling=True,
                                data_layout=layout)
    out = fluid.layers.fc(pool2, class_dim, act="softmax")
    return out


def build(class_dim=1000, image_shape=(3, 224, 224), depth=50, lr=0.1,
          dtype="float32", with_optimizer=True, layout="NCHW"):
    """layout="NHWC" transposes the NCHW feed once at graph entry and runs
    every conv/bn/pool channels-last — the TPU-preferred layout (channels on
    the 128-wide lane dimension); the feed contract stays NCHW."""
    input = fluid.layers.data("data", list(image_shape), dtype=dtype)
    label = fluid.layers.data("label", [1], dtype="int64")
    if layout == "NHWC":
        input = fluid.layers.transpose(input, [0, 2, 3, 1])
    predict = resnet(input, class_dim, depth, layout)
    cost = fluid.layers.cross_entropy(predict, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(predict, label)
    if with_optimizer:
        opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
        opt.minimize(avg_cost)
    return ["data", "label"], avg_cost, acc
