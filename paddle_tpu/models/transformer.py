"""Transformer-base encoder-decoder for WMT en-de.

Reference spec: ``python/paddle/fluid/tests/unittests/dist_transformer.py``
(Transformer-base: d_model=512, n_head=8, d_ffn=2048, 6+6 layers, shared
post-LN residual structure, noam LR schedule).

TPU-first layout: fixed max sequence length (padded; recompile-bucketed by
the feeder), batch-major [B, T, D], all attention matmuls batched 4-D on the
MXU.  Padding handled by an additive attention bias computed from the
``<name>@LEN`` companion lengths and by masking the token loss.  Under
ParallelExecutor, BuildStrategy.sharding_rules can shard the FFN and
attention projection weights over an ``mp`` axis (tensor parallelism) while
the batch is dp-sharded.
"""
from __future__ import annotations

import warnings

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.initializer import NormalInitializer, NumpyArrayInitializer


def _pos_encoding_table(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float64")
    dim = np.arange(d_model // 2)[None, :].astype("float64")
    angle = pos / np.power(10000.0, 2 * dim / d_model)
    table = np.zeros((max_len, d_model))
    table[:, 0::2] = np.sin(angle)
    table[:, 1::2] = np.cos(angle)
    return table.astype("float32")


def _attn_bias_from_mask(mask_2d, n_head, T_q, causal=False, name=None):
    """mask_2d: [B, T_k] 1/0 validity → additive bias [B, 1, T_q, T_k]
    (broadcast over heads)."""
    bias = fluid.layers.scale(mask_2d, scale=1e9, bias=-1.0,
                              bias_after_scale=False)  # (m-1)*1e9
    bias = fluid.layers.unsqueeze(bias, [1, 2])  # [B,1,1,T_k]
    if causal:
        tri = np.triu(np.full((T_q, T_q), -1e9, "float32"), k=1)
        tri_v = fluid.layers.assign(tri)
        tri_v = fluid.layers.unsqueeze(tri_v, [0, 1])  # [1,1,T,T]
        bias = fluid.layers.elementwise_add(bias, tri_v)
    return bias


def multi_head_attention(q_in, k_in, v_in, attn_bias, d_model, n_head,
                         dropout_rate, param_prefix, kv_mask=None,
                         causal=False, impl="base"):
    d_key = d_model // n_head

    def proj(x, name):
        return fluid.layers.fc(
            x, d_model, num_flatten_dims=2, bias_attr=False,
            param_attr=fluid.ParamAttr(name=f"{param_prefix}.{name}.w"))

    q = proj(q_in, "q")
    k = proj(k_in, "k")
    v = proj(v_in, "v")

    def split_heads(x):
        x = fluid.layers.reshape(x, [0, 0, n_head, d_key])
        return fluid.layers.transpose(x, [0, 2, 1, 3])  # [B,H,T,dk]

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    if impl != "base":
        if kv_mask is None:
            raise ValueError(
                "attention_impl != 'base' requires the [B,T] kv_mask "
                "(padding handled inside fused_attention)")
        from ..layer_helper import LayerHelper
        helper = LayerHelper(param_prefix + ".fa")
        ctx = helper.create_variable_for_type_inference(q.dtype, shape=q.shape)
        inputs = {"Q": [q], "K": [k], "V": [v], "KvMask": [kv_mask]}
        if dropout_rate:
            # per-step int32 seed for the attention-prob dropout (explicit
            # program input → fwd and grad see identical bits on any impl).
            # Drawn in the GLOBAL block: a stateful op inside a While/RNN
            # sub-block would make the sub-block non-differentiable.
            gb = helper.main_program.global_block
            u = gb.create_var(name=helper.name + ".seed_u", dtype="float32",
                              shape=(1,), stop_gradient=True)
            gb.append_op(
                "uniform_random", {}, {"Out": [u.name]},
                {"shape": [1], "dtype": "float32", "min": 0.0, "max": 2.0e9})
            seed = gb.create_var(name=helper.name + ".seed", dtype="int32",
                                 shape=(1,), stop_gradient=True)
            gb.append_op("cast", {"X": [u.name]}, {"Out": [seed.name]},
                         {"out_dtype": "int32"})
            inputs["Seed"] = [seed]
        helper.append_op(
            "fused_attention", inputs, {"Out": [ctx]},
            {"impl": impl, "causal": causal, "scale": d_key ** -0.5,
             "dropout_rate": dropout_rate})
    else:
        scores = fluid.layers.matmul(q, k, transpose_y=True, alpha=d_key ** -0.5)
        if attn_bias is not None:
            scores = fluid.layers.elementwise_add(scores, attn_bias)
        weights = fluid.layers.softmax(scores)
        if dropout_rate:
            weights = fluid.layers.dropout(
                weights, dropout_rate, dropout_implementation="upscale_in_train")
        ctx = fluid.layers.matmul(weights, v)  # [B,H,Tq,dk]
    ctx = fluid.layers.transpose(ctx, [0, 2, 1, 3])
    ctx = fluid.layers.reshape(ctx, [0, 0, d_model])
    return fluid.layers.fc(
        ctx, d_model, num_flatten_dims=2, bias_attr=False,
        param_attr=fluid.ParamAttr(name=f"{param_prefix}.out.w"))


def ffn(x, d_model, d_ffn, param_prefix):
    h = fluid.layers.fc(
        x, d_ffn, num_flatten_dims=2, act="relu",
        param_attr=fluid.ParamAttr(name=f"{param_prefix}.fc1.w"))
    return fluid.layers.fc(
        h, d_model, num_flatten_dims=2,
        param_attr=fluid.ParamAttr(name=f"{param_prefix}.fc2.w"))


def _residual(x, sub, dropout_rate, prefix):
    """post-LN residual (original transformer / dist_transformer.py)."""
    if dropout_rate:
        sub = fluid.layers.dropout(
            sub, dropout_rate, dropout_implementation="upscale_in_train")
    out = fluid.layers.elementwise_add(x, sub)
    return fluid.layers.layer_norm(
        out, begin_norm_axis=2,
        param_attr=fluid.ParamAttr(name=f"{prefix}.ln.scale"),
        bias_attr=fluid.ParamAttr(name=f"{prefix}.ln.bias"))


def encoder_layer(x, bias, d_model, n_head, d_ffn, dropout, prefix,
                  kv_mask=None, impl="base"):
    attn = multi_head_attention(x, x, x, bias, d_model, n_head, dropout,
                                f"{prefix}.attn", kv_mask=kv_mask, impl=impl)
    x = _residual(x, attn, dropout, f"{prefix}.attn")
    f = ffn(x, d_model, d_ffn, f"{prefix}.ffn")
    return _residual(x, f, dropout, f"{prefix}.ffn")


def decoder_layer(x, enc_out, self_bias, cross_bias, d_model, n_head, d_ffn,
                  dropout, prefix, src_mask=None, tgt_mask=None, impl="base"):
    attn = multi_head_attention(x, x, x, self_bias, d_model, n_head, dropout,
                                f"{prefix}.self", kv_mask=tgt_mask,
                                causal=True, impl=impl)
    x = _residual(x, attn, dropout, f"{prefix}.self")
    cross = multi_head_attention(x, enc_out, enc_out, cross_bias, d_model,
                                 n_head, dropout, f"{prefix}.cross",
                                 kv_mask=src_mask, impl=impl)
    x = _residual(x, cross, dropout, f"{prefix}.cross")
    f = ffn(x, d_model, d_ffn, f"{prefix}.ffn")
    return _residual(x, f, dropout, f"{prefix}.ffn")


def _embed(ids, mask, vocab, d_model, max_len, prefix, dtype):
    emb = fluid.layers.embedding(
        ids, [vocab, d_model], dtype=dtype,
        param_attr=fluid.ParamAttr(
            name=f"{prefix}.word_emb",
            initializer=NormalInitializer(0.0, d_model ** -0.5)))
    emb = fluid.layers.scale(emb, scale=d_model ** 0.5)
    T = ids.shape[1] if ids.shape[1] != -1 else max_len
    pos = fluid.layers.assign(_pos_encoding_table(max_len, d_model)[:T])
    emb = fluid.layers.elementwise_add(emb, pos, axis=1)
    # zero out padding positions
    return fluid.layers.elementwise_mul(emb, mask, axis=0)


def transformer(src_ids, tgt_ids, src_mask, tgt_mask, src_vocab, tgt_vocab,
                max_len=256, d_model=512, n_head=8, d_ffn=2048,
                n_layer=6, dropout=0.1, dtype="float32",
                attention_impl="base"):
    """Returns logits [B, T_tgt, tgt_vocab].

    masks: [B, T] float 1/0 validity (from @LEN companions or fed directly).
    """
    T_src, T_tgt = src_ids.shape[1], tgt_ids.shape[1]
    src_mask3 = fluid.layers.unsqueeze(src_mask, [2])
    tgt_mask3 = fluid.layers.unsqueeze(tgt_mask, [2])
    fused = attention_impl != "base"
    enc_bias = None if fused else _attn_bias_from_mask(src_mask, n_head, T_src)
    dec_self_bias = None if fused else _attn_bias_from_mask(
        tgt_mask, n_head, T_tgt, causal=True)
    dec_cross_bias = None if fused else _attn_bias_from_mask(src_mask, n_head, T_tgt)

    enc = _embed(src_ids, src_mask3, src_vocab, d_model, max_len, "src", dtype)
    if dropout:
        enc = fluid.layers.dropout(
            enc, dropout, dropout_implementation="upscale_in_train")
    for i in range(n_layer):
        enc = encoder_layer(enc, enc_bias, d_model, n_head, d_ffn, dropout,
                            f"enc.{i}", kv_mask=src_mask, impl=attention_impl)

    dec = _embed(tgt_ids, tgt_mask3, tgt_vocab, d_model, max_len, "tgt", dtype)
    if dropout:
        dec = fluid.layers.dropout(
            dec, dropout, dropout_implementation="upscale_in_train")
    for i in range(n_layer):
        dec = decoder_layer(dec, enc, dec_self_bias, dec_cross_bias, d_model,
                            n_head, d_ffn, dropout, f"dec.{i}",
                            src_mask=src_mask, tgt_mask=tgt_mask,
                            impl=attention_impl)

    logits = fluid.layers.fc(
        dec, tgt_vocab, num_flatten_dims=2, bias_attr=False,
        param_attr=fluid.ParamAttr(name="tgt.out_proj"))
    return logits


def build(src_vocab=30000, tgt_vocab=30000, max_len=64, d_model=512,
          n_head=8, d_ffn=2048, n_layer=6, dropout=0.1,
          warmup_steps=4000, with_optimizer=True, label_smoothing=0.0,
          dtype="float32", attention_impl="base"):
    """Train program over fixed-length padded batches.

    Feeds: src_ids [B,T], tgt_ids [B,T], lbl_ids [B,T] (tgt shifted),
    src_mask/tgt_mask [B,T] float.  Returns (feed names, avg_cost, token_acc).
    """
    src_ids = fluid.layers.data("src_ids", [max_len], dtype="int64",
                                append_batch_size=True)
    tgt_ids = fluid.layers.data("tgt_ids", [max_len], dtype="int64")
    lbl_ids = fluid.layers.data("lbl_ids", [max_len], dtype="int64")
    src_mask = fluid.layers.data("src_mask", [max_len])
    tgt_mask = fluid.layers.data("tgt_mask", [max_len])

    logits = transformer(src_ids, tgt_ids, src_mask, tgt_mask, src_vocab,
                         tgt_vocab, max_len, d_model, n_head, d_ffn, n_layer,
                         dropout, dtype, attention_impl)
    lbl = fluid.layers.unsqueeze(lbl_ids, [2])
    loss = fluid.layers.softmax_with_cross_entropy(logits, lbl)  # [B,T,1]
    loss = fluid.layers.squeeze(loss, [2])
    masked = fluid.layers.elementwise_mul(loss, tgt_mask)
    tok_count = fluid.layers.reduce_sum(tgt_mask)
    avg_cost = fluid.layers.elementwise_div(
        fluid.layers.reduce_sum(masked), tok_count)

    if with_optimizer:
        lr = fluid.layers.learning_rate_scheduler.noam_decay(
            d_model, warmup_steps)
        opt = fluid.optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.98,
                                   epsilon=1e-9)
        opt.minimize(avg_cost)
    return (["src_ids", "tgt_ids", "lbl_ids", "src_mask", "tgt_mask"],
            avg_cost, tok_count)


def tp_sharding_rules():
    """Tensor-parallel PartitionSpecs for ParallelExecutor
    (BuildStrategy.sharding_rules): FFN + attention projections sharded over
    the ``mp`` mesh axis (Megatron layout: fc1/q/k/v column-, fc2/out
    row-parallel)."""
    return [
        (r".*\.ffn\.fc1\.w", (None, "mp")),
        (r".*\.ffn\.fc2\.w", ("mp", None)),
        (r".*\.attn\.(q|k|v)\.w", (None, "mp")),
        (r".*\.self\.(q|k|v)\.w", (None, "mp")),
        (r".*\.cross\.(q|k|v)\.w", (None, "mp")),
        (r".*\.(attn|self|cross)\.out\.w", ("mp", None)),
        (r".*word_emb", ("mp", None)),
        (r"tgt\.out_proj", (None, "mp")),
    ]
