"""Model zoo mirroring the reference's benchmark/test model set
(benchmark/fluid/models/ + dist_transformer.py + dist_ctr.py)."""
from . import deepfm, mnist, resnet, stacked_lstm, transformer, vgg  # noqa: F401
