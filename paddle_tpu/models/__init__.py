"""Model zoo mirroring the reference's benchmark/test model set
(benchmark/fluid/models/ + dist_transformer.py + dist_ctr.py)."""
from . import (  # noqa: F401
    book,
    deepfm,
    machine_translation,
    mnist,
    resnet,
    se_resnext,
    stacked_lstm,
    transformer,
    vgg,
)
