"""Attention seq2seq for machine translation (reference
benchmark/fluid/models/machine_translation.py:53,104 — bi-GRU encoder,
attention decoder trained with DynamicRNN, beam-search inference with the
While + TensorArray decode stack).

Train and infer programs share parameter names, so params trained with
``build(mode="train")`` load directly into ``build(mode="infer")``.
"""
from __future__ import annotations

import paddle_tpu as fluid

L = fluid.layers


def _encoder(src_ids, src_vocab, emb_dim, hid, prefix="enc"):
    emb = L.embedding(src_ids, [src_vocab, emb_dim],
                      param_attr=fluid.ParamAttr(name=f"{prefix}.emb"))
    fwd_in = L.fc(emb, hid * 3, num_flatten_dims=2, bias_attr=False,
                  param_attr=fluid.ParamAttr(name=f"{prefix}.fwd_in.w"))
    fwd = L.dynamic_gru(fwd_in, hid,
                        param_attr=fluid.ParamAttr(name=f"{prefix}.fwd.w"),
                        bias_attr=fluid.ParamAttr(name=f"{prefix}.fwd.b"))
    bwd_in = L.fc(emb, hid * 3, num_flatten_dims=2, bias_attr=False,
                  param_attr=fluid.ParamAttr(name=f"{prefix}.bwd_in.w"))
    bwd = L.dynamic_gru(bwd_in, hid, is_reverse=True,
                        param_attr=fluid.ParamAttr(name=f"{prefix}.bwd.w"),
                        bias_attr=fluid.ParamAttr(name=f"{prefix}.bwd.b"))
    enc = L.concat([fwd, bwd], axis=2)                    # [B, T, 2H]
    return enc


def _attend(dec_h, enc_proj, enc_states, src_mask):
    """Dot attention: dec_h [B,H_d] vs enc_proj [B,T,H_d] → ctx [B,2H]."""
    scores = L.matmul(enc_proj, L.unsqueeze(dec_h, [2]))  # [B,T,1]
    scores = L.squeeze(scores, [2])                       # [B,T]
    neg = L.scale(L.elementwise_sub(src_mask,
                                    L.fill_constant_batch_size_like(
                                        src_mask, [-1, src_mask.shape[1]],
                                        "float32", 1.0)), scale=1e9)
    scores = L.elementwise_add(scores, neg)               # -1e9 on padding
    w = L.softmax(scores)                                 # [B,T]
    ctx = L.matmul(L.unsqueeze(w, [1]), enc_states)       # [B,1,2H]
    return L.squeeze(ctx, [1])


def _step_logits(cur_emb, h, enc_proj, enc_states, src_mask, hid, tgt_vocab):
    """One decoder step shared by train/infer: returns (new_h, logits)."""
    ctx = _attend(h, enc_proj, enc_states, src_mask)
    gate_in = L.fc([cur_emb, ctx, h], hid * 3, bias_attr=False,
                   param_attr=[fluid.ParamAttr(name="dec.gru_in.w_emb"),
                               fluid.ParamAttr(name="dec.gru_in.w_ctx"),
                               fluid.ParamAttr(name="dec.gru_in.w_h")])
    new_h = _gru_cell(gate_in, h, hid)
    logits = L.fc(new_h, tgt_vocab,
                  param_attr=fluid.ParamAttr(name="dec.out.w"),
                  bias_attr=fluid.ParamAttr(name="dec.out.b"))
    return new_h, logits


def _gru_cell(gates_x, h, hid):
    """Single GRU step from pre-projected x gates [B,3H] + state [B,H]
    (weights named for train/infer sharing)."""
    gates_h = L.fc(h, hid * 3, bias_attr=fluid.ParamAttr(name="dec.gru.b"),
                   param_attr=fluid.ParamAttr(name="dec.gru.w"))
    g = L.elementwise_add(gates_x, gates_h)               # [B, 3H]
    u = L.sigmoid(L.slice(g, axes=[1], starts=[0], ends=[hid]))
    r = L.sigmoid(L.slice(g, axes=[1], starts=[hid], ends=[2 * hid]))
    c_x = L.slice(gates_x, axes=[1], starts=[2 * hid], ends=[3 * hid])
    c_h = L.slice(gates_h, axes=[1], starts=[2 * hid], ends=[3 * hid])
    c = L.tanh(L.elementwise_add(c_x, L.elementwise_mul(r, c_h)))
    one_minus_u = L.scale(u, scale=-1.0, bias=1.0)
    return L.elementwise_add(L.elementwise_mul(u, h),
                             L.elementwise_mul(one_minus_u, c))


def build(src_vocab=10000, tgt_vocab=10000, emb_dim=256, hid=256,
          max_len=32, beam_size=4, mode="train", lr=1e-3,
          with_optimizer=True):
    """mode="train": returns (feed names, avg_cost).
    mode="infer": returns (feed names, sentence ids [B*beam, max_len],
    scores [B*beam, 1])."""
    src = L.data("src_ids", [max_len], dtype="int64")
    src_mask = L.data("src_mask", [max_len])
    enc = _encoder(src, src_vocab, emb_dim, hid)
    enc_proj = L.fc(enc, hid, num_flatten_dims=2, bias_attr=False,
                    param_attr=fluid.ParamAttr(name="dec.att_proj.w"))
    h0 = L.fc(_last_state(enc, src_mask), hid, act="tanh",
              param_attr=fluid.ParamAttr(name="dec.h0.w"),
              bias_attr=fluid.ParamAttr(name="dec.h0.b"))

    if mode == "train":
        tgt = L.data("tgt_ids", [max_len], dtype="int64")
        lbl = L.data("lbl_ids", [max_len], dtype="int64")
        tgt_mask = L.data("tgt_mask", [max_len])
        tgt_emb = L.embedding(tgt, [tgt_vocab, emb_dim],
                              param_attr=fluid.ParamAttr(name="dec.emb"))
        # teacher-forced decode as a StaticRNN over target steps
        rnn = L.StaticRNN()
        with rnn.step():
            cur = rnn.step_input(tgt_emb)                 # [B, emb]
            h = rnn.memory(init=h0)
            new_h, logits = _step_logits(cur, h, enc_proj, enc, src_mask,
                                         hid, tgt_vocab)
            rnn.update_memory(h, new_h)
            rnn.step_output(logits)
        logits_seq = rnn()                                # [B, T, V]
        loss = L.softmax_with_cross_entropy(
            logits_seq, L.unsqueeze(lbl, [2]))
        loss = L.squeeze(loss, [2])
        masked = L.elementwise_mul(loss, tgt_mask)
        avg_cost = L.elementwise_div(L.reduce_sum(masked),
                                     L.reduce_sum(tgt_mask))
        if with_optimizer:
            fluid.optimizer.Adam(lr).minimize(avg_cost)
        return (["src_ids", "src_mask", "tgt_ids", "lbl_ids", "tgt_mask"],
                avg_cost)

    # -- beam-search inference (While + TensorArray + beam_search ops) -----
    B = 1  # static batch for the decode loop; tile inputs to B*beam
    bw = B * beam_size
    start, end_id = 1, 2
    cand_ids = L.data("cand_ids", [tgt_vocab], dtype="int64")  # [bw, V] iota
    enc_t = _tile_rows(enc, beam_size)
    proj_t = _tile_rows(enc_proj, beam_size)
    mask_t = _tile_rows(src_mask, beam_size)
    h = _tile_rows(h0, beam_size)

    pre_ids = L.fill_constant([bw, 1], "int64", start)
    pre_scores = L.data("beam_seed", [1])                 # [bw,1] 0/-inf
    ids_arr = L.create_array("int64", [bw], max_len=max_len)
    par_arr = L.create_array("int64", [bw], max_len=max_len)
    score_arr = L.create_array("float32", [bw], max_len=max_len)
    i = L.fill_constant([1], "int64", 0)
    n = L.fill_constant([1], "int64", max_len)
    cond = L.less_than(i, n)
    with L.While(cond).block():
        # ids [bw, 1]: the trailing-1 dim is squeezed by lookup_table,
        # giving [bw, emb] directly
        cur_emb = L.embedding(pre_ids, [tgt_vocab, emb_dim],
                              param_attr=fluid.ParamAttr(name="dec.emb"))
        new_h, logits = _step_logits(cur_emb, h, proj_t, enc_t, mask_t,
                                     hid, tgt_vocab)
        logp = L.log(L.softmax(logits))
        cand_scores = L.elementwise_add(logp, pre_scores)
        sel_ids, sel_scores, parent = L.beam_search(
            pre_ids, pre_scores, cand_ids, cand_scores,
            beam_size=beam_size, end_id=end_id)
        # beams were reordered: gather the decoder state by parent
        L.assign(L.gather(new_h, parent), h)
        L.array_write(L.squeeze(sel_ids, [1]), i, ids_arr)
        L.array_write(parent, i, par_arr)
        L.array_write(L.squeeze(sel_scores, [1]), i, score_arr)
        L.assign(sel_ids, pre_ids)
        L.assign(sel_scores, pre_scores)
        L.increment(i, 1)
        L.less_than(i, n, cond=cond)
    decode = L.beam_search_decode(ids_arr, par_arr, beam_size=beam_size,
                                  end_id=end_id, scores_array=score_arr)
    return (["src_ids", "src_mask", "cand_ids", "beam_seed"], decode,
            pre_scores)


def _last_state(enc, src_mask):
    """Masked last encoder state [B, 2H] (lengths from the mask sum)."""
    lens = L.cast(L.reduce_sum(src_mask, dim=1), "int32")
    from ..layers.nn import _alias_len
    _alias_len(enc, lens)
    return L.sequence_last_step(enc)


from paddle_tpu.layers.nn import _tile_rows  # shared beam fan-out
