"""MNIST convnet (reference: benchmark/fluid/models/mnist.py cnn_model)."""
from __future__ import annotations

import paddle_tpu as fluid


def cnn_model(data):
    conv_pool_1 = fluid.layers.conv2d(data, 20, 5, act="relu")
    pool_1 = fluid.layers.pool2d(conv_pool_1, 2, "max", 2)
    conv_pool_2 = fluid.layers.conv2d(pool_1, 50, 5, act="relu")
    pool_2 = fluid.layers.pool2d(conv_pool_2, 2, "max", 2)
    predict = fluid.layers.fc(pool_2, 10, act="softmax")
    return predict


def build(batch_size=None, lr=0.001, with_optimizer=True):
    """Build train program; returns (feeds, loss, acc)."""
    images = fluid.layers.data("pixel", [1, 28, 28])
    label = fluid.layers.data("label", [1], dtype="int64")
    predict = cnn_model(images)
    cost = fluid.layers.cross_entropy(predict, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(predict, label)
    if with_optimizer:
        opt = fluid.optimizer.Adam(learning_rate=lr)
        opt.minimize(avg_cost)
    return ["pixel", "label"], avg_cost, acc
