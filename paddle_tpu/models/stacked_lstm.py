"""Stacked LSTM sentiment model over variable-length text
(reference: benchmark/fluid/models/stacked_dynamic_lstm.py — IMDB,
emb 512 → N × [fc + lstm] → max-pool concat → softmax)."""
from __future__ import annotations

import paddle_tpu as fluid


def stacked_lstm_net(data, dict_dim, class_dim=2, emb_dim=512, hid_dim=512,
                     stacked_num=3):
    emb = fluid.layers.embedding(data, [dict_dim, emb_dim])
    fc1 = fluid.layers.fc(emb, hid_dim * 4, num_flatten_dims=2)
    lstm1, cell1 = fluid.layers.dynamic_lstm(fc1, hid_dim * 4)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = fluid.layers.fc(inputs[-1], hid_dim * 4, num_flatten_dims=2)
        lstm, cell = fluid.layers.dynamic_lstm(
            fc, hid_dim * 4, is_reverse=(i % 2) == 0)
        inputs = [fc, lstm]
    fc_last = fluid.layers.sequence_pool(inputs[0], "max")
    lstm_last = fluid.layers.sequence_pool(inputs[1], "max")
    prediction = fluid.layers.fc([fc_last, lstm_last], class_dim,
                                 act="softmax")
    return prediction


def build(dict_dim=30000, class_dim=2, emb_dim=512, hid_dim=512,
          stacked_num=3, lr=0.002, with_optimizer=True):
    data = fluid.layers.data("words", [1], dtype="int64", lod_level=1)
    label = fluid.layers.data("label", [1], dtype="int64")
    predict = stacked_lstm_net(data, dict_dim, class_dim, emb_dim, hid_dim,
                               stacked_num)
    cost = fluid.layers.cross_entropy(predict, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(predict, label)
    if with_optimizer:
        fluid.optimizer.Adam(lr).minimize(avg_cost)
    return ["words", "label"], avg_cost, acc
