"""DeepFM-style CTR model with large sparse embeddings
(reference: python/paddle/fluid/tests/unittests/dist_ctr.py +
dist_ctr_reader.py — sparse embedding for categorical features, dense MLP,
joint sigmoid CTR loss)."""
from __future__ import annotations

import paddle_tpu as fluid


def ctr_deepfm(dense_input, sparse_ids, sparse_field_count, sparse_dim,
               embed_dim=10, fc_sizes=(400, 400, 400)):
    """dense_input [B, dense_dim]; sparse_ids [B, fields] int64 ids into a
    shared hash space of sparse_dim."""
    emb = fluid.layers.embedding(
        sparse_ids, [sparse_dim, embed_dim],
        param_attr=fluid.ParamAttr(
            name="ctr.sparse_emb",
            initializer=fluid.initializer.Uniform(-0.01, 0.01)),
        is_sparse=True)                            # [B, fields, embed_dim]
    # FM second-order term: 0.5*((Σv)² − Σv²)
    sum_emb = fluid.layers.reduce_sum(emb, dim=1)              # [B, k]
    sum_sq = fluid.layers.square(sum_emb)
    sq_emb = fluid.layers.square(emb)
    sq_sum = fluid.layers.reduce_sum(sq_emb, dim=1)
    fm = fluid.layers.scale(
        fluid.layers.elementwise_sub(sum_sq, sq_sum), scale=0.5)

    # first-order sparse term
    emb1 = fluid.layers.embedding(
        sparse_ids, [sparse_dim, 1],
        param_attr=fluid.ParamAttr(name="ctr.sparse_w1"),
        is_sparse=True)                            # [B, fields, 1]
    first = fluid.layers.reduce_sum(emb1, dim=1)   # [B, 1]

    # deep part
    flat = fluid.layers.reshape(emb, [0, emb.shape[1] * emb.shape[2]])
    deep = fluid.layers.concat([flat, dense_input], axis=1)
    for i, sz in enumerate(fc_sizes):
        deep = fluid.layers.fc(deep, sz, act="relu",
                               param_attr=fluid.ParamAttr(name=f"ctr.fc{i}.w"))
    deep_out = fluid.layers.fc(deep, 1)

    fm_out = fluid.layers.fc(fm, 1)
    logit = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(deep_out, fm_out), first)
    return logit


def tp_sharding_rules():
    """Model-parallel PartitionSpecs for ParallelExecutor
    (BuildStrategy.sharding_rules): both CTR tables row-sharded over the
    ``mp`` mesh axis — the mesh-native analogue of the pserver path's
    sharded distributed lookup table, for tables too large for one
    chip's HBM.  GSPMD inserts the cross-shard gathers; the lazy
    optimizer state (Adam moments) inherits the same row sharding."""
    return [
        # trailing .* catches the optimizer accumulators
        # (ctr.sparse_emb_moment1_0, ...) so Adam state shards with its
        # table; scalar accumulators fail the divisibility guard and
        # stay replicated
        (r"ctr\.sparse_emb.*", ("mp", None)),
        (r"ctr\.sparse_w1.*", ("mp", None)),
    ]


def build(dense_dim=13, sparse_fields=26, sparse_dim=int(1e5), embed_dim=10,
          lr=1e-4, with_optimizer=True):
    dense = fluid.layers.data("dense", [dense_dim])
    sparse = fluid.layers.data("sparse", [sparse_fields], dtype="int64")
    label = fluid.layers.data("label", [1])
    logit = ctr_deepfm(dense, sparse, sparse_fields, sparse_dim, embed_dim)
    loss = fluid.layers.sigmoid_cross_entropy_with_logits(logit, label)
    avg_cost = fluid.layers.mean(loss)
    if with_optimizer:
        fluid.optimizer.Adam(lr).minimize(avg_cost)
    return ["dense", "sparse", "label"], avg_cost, logit
