"""SE-ResNeXt (reference benchmark/fluid/models/se_resnext.py): grouped
3x3 convolutions (cardinality) + squeeze-and-excitation channel gating."""
from __future__ import annotations

import paddle_tpu as fluid

L = fluid.layers


def conv_bn(input, num_filters, filter_size, stride=1, groups=1, act=None):
    conv = L.conv2d(input, num_filters, filter_size, stride=stride,
                    padding=(filter_size - 1) // 2, groups=groups,
                    bias_attr=False)
    return L.batch_norm(conv, act=act)


def squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = L.pool2d(input, pool_type="avg", global_pooling=True)
    pool = L.reshape(pool, [-1, num_channels])
    squeeze = L.fc(pool, max(num_channels // reduction_ratio, 4), act="relu")
    excitation = L.fc(squeeze, num_channels, act="sigmoid")
    # channel gate broadcast over H, W
    gate = L.reshape(excitation, [-1, num_channels, 1, 1])
    return L.elementwise_mul(input, gate)


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio):
    conv0 = conv_bn(input, num_filters, 1, act="relu")
    conv1 = conv_bn(conv0, num_filters, 3, stride=stride,
                    groups=cardinality, act="relu")
    conv2 = conv_bn(conv1, num_filters * 2, 1)
    scaled = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    if input.shape[1] != num_filters * 2 or stride != 1:
        shortcut = conv_bn(input, num_filters * 2, 1, stride=stride)
    else:
        shortcut = input
    return L.relu(L.elementwise_add(shortcut, scaled))


def se_resnext(input, class_dim, depth=50, cardinality=32,
               reduction_ratio=16):
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth]
    filters = [128, 256, 512, 1024]
    conv = conv_bn(input, 64, 7, stride=2, act="relu")
    conv = L.pool2d(conv, 3, "max", 2, pool_padding=1)
    for block, n in enumerate(cfg):
        for i in range(n):
            conv = bottleneck_block(
                conv, filters[block], 2 if i == 0 and block != 0 else 1,
                cardinality, reduction_ratio)
    pool = L.pool2d(conv, pool_type="avg", global_pooling=True)
    flat = L.reshape(pool, [-1, pool.shape[1]])
    drop = L.dropout(flat, dropout_prob=0.5)
    return L.fc(drop, class_dim, act="softmax")


def build(class_dim=1000, image_shape=(3, 224, 224), depth=50, lr=0.1,
          cardinality=32, with_optimizer=True):
    img = L.data("data", list(image_shape))
    label = L.data("label", [1], dtype="int64")
    predict = se_resnext(img, class_dim, depth, cardinality)
    cost = L.cross_entropy(predict, label)
    avg_cost = L.mean(cost)
    acc = L.accuracy(predict, label)
    if with_optimizer:
        fluid.optimizer.Momentum(lr, momentum=0.9).minimize(avg_cost)
    return ["data", "label"], avg_cost, acc
