"""fluid.evaluator — program-state evaluators
(reference python/paddle/fluid/evaluator.py:44; deprecated there in
favor of fluid.metrics but still public API, so kept for parity).

An Evaluator owns persistable state vars accumulated by ops it appends
to the MAIN program (the executor writes persistable outputs back to the
scope — the same mechanism optimizer ops use), a ``reset`` program that
zero-fills them, and an ``eval`` program that reads the states and
computes the final metric.  State vars get zero initializers in the
startup program too, so running startup is enough to start accumulating.
"""
from __future__ import annotations

import warnings

import numpy as np

from . import layers
from .core import unique_name
from .core.program import Program, program_guard
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


def _clone_var(block, var):
    return block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                            persistable=True)


class Evaluator:
    """Base: state creation + reset program (evaluator.py:44)."""

    def __init__(self, name, **kwargs):
        warnings.warn(
            f"The {self.__class__.__name__} is deprecated, please use "
            f"fluid.metrics.{self.__class__.__name__} instead.", Warning)
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        """Zero the accumulated states (start of an epoch)."""
        if reset_program is None:
            reset_program = Program()
        with program_guard(main_program=reset_program):
            for var in self.states:
                g_var = _clone_var(reset_program.global_block, var)
                layers.fill_constant(shape=g_var.shape, value=0.0,
                                     dtype=g_var.dtype, out=g_var)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()

    def _create_state(self, suffix, dtype, shape):
        state = self.helper.create_global_variable(
            name=unique_name.generate(f"{self.helper.name}_{suffix}"),
            persistable=True, dtype=dtype, shape=tuple(shape))
        self.helper.set_variable_initializer(state, ConstantInitializer(0.0))
        self.states.append(state)
        return state

    def _accumulate(self, state, batch_value):
        """state += batch_value, appended to the main program (the
        executor's persistable-write mechanism carries it across runs)."""
        value = batch_value
        if tuple(value.shape or ()) != tuple(state.shape or ()):
            value = layers.reshape(value, list(state.shape))
        if value.dtype != state.dtype:
            value = layers.cast(value, state.dtype)
        self.helper.append_op("elementwise_add",
                              {"X": [state], "Y": [value]},
                              {"Out": [state]}, {})


class ChunkEvaluator(Evaluator):
    """Accumulate chunk_eval counters; eval() -> (precision, recall, f1)
    over the whole pass (evaluator.py:126)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("chunk_eval")
        (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
         num_correct_chunks) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self.num_infer_chunks = self._create_state("num_infer", "int64", (1,))
        self.num_label_chunks = self._create_state("num_label", "int64", (1,))
        self.num_correct_chunks = self._create_state(
            "num_correct", "int64", (1,))
        self._accumulate(self.num_infer_chunks, num_infer_chunks)
        self._accumulate(self.num_label_chunks, num_label_chunks)
        self._accumulate(self.num_correct_chunks, num_correct_chunks)
        self.metrics = [precision, recall, f1_score]

    def eval(self, executor, eval_program=None):
        from .core.executor import global_scope

        scope = global_scope()

        def _scalar(v):
            return float(np.asarray(scope.find_var(v.name)).ravel()[0])

        infer = _scalar(self.num_infer_chunks)
        label = _scalar(self.num_label_chunks)
        correct = _scalar(self.num_correct_chunks)
        precision = correct / infer if infer else 0.0
        recall = correct / label if label else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if correct else 0.0)
        return np.float32(precision), np.float32(recall), np.float32(f1)


class EditDistance(Evaluator):
    """Accumulate edit distances; eval() -> (avg_distance,
    avg_instance_error) over the whole pass (evaluator.py:217)."""

    def __init__(self, input, label, ignored_tokens=None):
        super().__init__("edit_distance")
        if ignored_tokens:
            raise NotImplementedError(
                "EditDistance(ignored_tokens=...) is not supported: the "
                "edit_distance lowering (layers/nn.py edit_distance) has "
                "no token-filter input; strip ignored tokens in the "
                "reader instead")
        distances, seq_num = layers.edit_distance(input=input, label=label)
        self.total_distance = self._create_state(
            "total_distance", "float32", (1,))
        self.seq_num = self._create_state("seq_num", "int64", (1,))
        self.instance_error = self._create_state(
            "instance_error", "int64", (1,))
        self._accumulate(self.total_distance,
                         layers.reduce_sum(distances))
        self._accumulate(self.seq_num, seq_num)
        wrong = layers.reduce_sum(
            layers.cast(layers.less_than(
                layers.fill_constant((1,), "float32", 0.0), distances),
                "int64"))
        self._accumulate(self.instance_error, wrong)
        self.metrics = [distances]

    def eval(self, executor, eval_program=None):
        from .core.executor import global_scope

        scope = global_scope()

        def _scalar(v):
            return float(np.asarray(scope.find_var(v.name)).ravel()[0])

        total = _scalar(self.total_distance)
        n = _scalar(self.seq_num)
        err = _scalar(self.instance_error)
        if n == 0:
            raise ValueError("no sequences accumulated in EditDistance")
        return np.float32(total / n), np.float32(err / n)


class DetectionMAP(Evaluator):
    """Accumulative mean average precision: the detection_map op's
    PosCount/TruePos/FalsePos state slots carry per-class score-bin
    counts across batches (evaluator.py:298; detection_map_op.cc
    accumulative mode)."""

    BINS = 1000

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__("map_eval")
        if class_num is None:
            raise ValueError("DetectionMAP requires class_num")
        # reference packs gt as (label, [difficult,] box); our in-graph op
        # takes the padded [B, Mg, 6] = (label, x1, y1, x2, y2, difficult)
        if gt_difficult is not None:
            label6 = layers.concat([gt_label, gt_box, gt_difficult], axis=-1)
        else:
            zeros = layers.fill_constant_batch_size_like(
                gt_label, list(gt_label.shape), "float32", 0.0)
            label6 = layers.concat([gt_label, gt_box, zeros], axis=-1)
        self.pos_count = self._create_state(
            "pos_count", "float32", (class_num,))
        self.true_pos = self._create_state(
            "true_pos", "float32", (class_num, self.BINS))
        self.false_pos = self._create_state(
            "false_pos", "float32", (class_num, self.BINS))
        # a STATE like the counters: persistable (so eval() reads the
        # scope) and zeroed by reset() along with the count states
        accum_map = self._create_state("map", "float32", (1,))
        from .layers.nn import seq_len_var

        ins = {"DetectRes": [input], "Label": [label6],
               "PosCount": [self.pos_count], "TruePos": [self.true_pos],
               "FalsePos": [self.false_pos]}
        # lengths belong to the FED gt var; the derived concat output has
        # no @LEN companion
        sl = seq_len_var(gt_label)
        if sl is not None:
            ins["GtLen"] = [sl]
        self.helper.append_op(
            "detection_map", ins,
            {"MAP": [accum_map], "AccumPosCount": [self.pos_count],
             "AccumTruePos": [self.true_pos],
             "AccumFalsePos": [self.false_pos]},
            {"class_num": class_num, "background_label": background_label,
             "overlap_threshold": overlap_threshold,
             "evaluate_difficult": evaluate_difficult,
             "ap_version": ap_version})
        self.cur_map = accum_map
        self.metrics = [accum_map]

    def eval(self, executor, eval_program=None):
        """The op's MAP output already reflects the accumulated states;
        return the last computed value from the scope."""
        from .core.executor import global_scope

        v = global_scope().find_var(self.cur_map.name)
        if v is None:
            raise ValueError("DetectionMAP.eval before any batch ran")
        return np.asarray(v)
