"""Marshal layer for the native C TRAINER API (native/paddle_tpu_capi.cc
pt_trainer_*) — train-from-native without authoring Python.

Same bytes-only wire protocol as the inference bridge
(paddle_tpu/inference/capi_bridge.py): the embedded interpreter passes
plain ints/strs/bytes tuples, so the C side compiles against Python.h
alone.  Reference role: the train-from-saved-program capability of
paddle/fluid/train/demo/demo_trainer.cc:1 (load ProgramDescs, run
startup, loop executor.Run, read the loss tensor) — redesigned over the
paddle_tpu Executor and the save_train_model layout (io.py).
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..inference.capi_bridge import HandleRegistry, _np_dtype

_registry = HandleRegistry()


class _NativeTrainer:
    def __init__(self, model_dir: str):
        from .. import io
        from ..core.executor import Executor, Scope, scope_guard

        self.scope = Scope()
        self.exe = Executor()
        with scope_guard(self.scope):
            main, startup, feeds, loss = io.load_train_model(
                model_dir, self.exe)
            # startup creates every persistable (params, optimizer
            # moments, LR counters); the saved state then overwrites it,
            # so a freshly-saved model warm-starts and a checkpointed
            # one resumes exactly
            self.exe.run(startup)
            io.load_persistables(self.exe, model_dir, main)
        self.main = main
        self.startup = startup
        self.feed_names = list(feeds)
        self.loss_name = loss

    def step(self, feed: dict) -> np.ndarray:
        from ..core.executor import scope_guard

        with scope_guard(self.scope):
            (loss,) = self.exe.run(self.main, feed=feed,
                                   fetch_list=[self.loss_name], sync=True)
        return np.asarray(loss)

    def save(self, dirname: str) -> None:
        from .. import io
        from ..core.executor import scope_guard

        with scope_guard(self.scope):
            # the original startup travels with every checkpoint: load
            # runs it first (creating every persistable and the RNG
            # machinery) and the saved state then overwrites it, so the
            # checkpoint resumes exactly
            io.save_train_model(dirname, self.feed_names, self.loss_name,
                                self.exe, main_program=self.main,
                                startup_program=self.startup)


def create(model_dir: str) -> int:
    import os

    if os.environ.get("PT_CAPI_JAX_PLATFORM"):
        # env-var JAX_PLATFORMS is dead once a PJRT plugin registered;
        # honor an explicit platform request in-process (the C train
        # smoke runs on forced CPU this way)
        import jax

        jax.config.update("jax_platforms",
                          os.environ["PT_CAPI_JAX_PLATFORM"])
    return _registry.add(_NativeTrainer(model_dir))


def feed_names(handle: int) -> List[str]:
    return _registry.get(handle).feed_names


def step(handle: int,
         inputs: List[Tuple[str, str, tuple, bytes]]
         ) -> Tuple[str, tuple, bytes]:
    t = _registry.get(handle)
    feed = {}
    for name, dtype, shape, data in inputs:
        feed[name] = np.frombuffer(
            data, dtype=_np_dtype(dtype)).reshape(shape)
    loss = np.ascontiguousarray(t.step(feed))
    return (str(loss.dtype), tuple(int(d) for d in loss.shape),
            loss.tobytes())


def save(handle: int, dirname: str) -> None:
    _registry.get(handle).save(dirname)


def destroy(handle: int) -> None:
    _registry.pop(handle)
