"""Native training support: the marshal bridge behind the C trainer API
(reference role: paddle/fluid/train/ — train from a saved ProgramDesc
without authoring Python)."""
from . import capi_bridge  # noqa: F401
