"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: /root/reference, see SURVEY.md).

The user-facing API mirrors ``paddle.fluid``: build a Program with
``layers.*``, differentiate with ``optimizer.minimize`` (graph-level
autodiff), run with ``Executor`` / ``ParallelExecutor``.  Underneath,
whole program blocks lower to single XLA computations (core/lowering.py);
data parallelism is a sharded jit over a ``jax.sharding.Mesh`` rather than
NCCL op-handles.

    import paddle_tpu as fluid
    x = fluid.layers.data("x", [784])
    y = fluid.layers.data("y", [1], dtype="int64")
    pred = fluid.layers.fc(x, 10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
    fluid.optimizer.SGD(0.01).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={...}, fetch_list=[loss])
"""
from __future__ import annotations

# register all op lowerings first
from . import ops  # noqa: F401
from . import average  # noqa: F401

from . import clip  # noqa: F401
from . import data  # noqa: F401
from . import initializer  # noqa: F401
from . import contrib  # noqa: F401
from . import debugger  # noqa: F401
from . import evaluator  # noqa: F401
from . import net_drawer  # noqa: F401
from . import recordio_writer  # noqa: F401
from .core import backward  # noqa: F401
from . import inference  # noqa: F401
from . import io  # noqa: F401
from . import metrics  # noqa: F401
from . import transpiler  # noqa: F401
from . import layers  # noqa: F401
from . import nets  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from .core.backward import append_backward, calc_gradient  # noqa: F401
gradients = calc_gradient  # later-fluid alias
from . import observability  # noqa: F401
from . import profiler  # noqa: F401
from .lod_tensor import (  # noqa: F401
    LoDTensor, create_lod_tensor, create_random_int_lodtensor)
Tensor = LoDTensor  # reference __init__.py:51 alias
LoDTensorArray = list  # reference core type: a list of LoDTensors
# `from . import annotations` would silently resolve to the _Feature
# bound by `from __future__ import annotations` above (the import system
# short-circuits on an existing attribute) — rebind explicitly
import importlib as _importlib  # noqa: E402
annotations = _importlib.import_module(__name__ + ".annotations")
from .layers import learning_rate_scheduler as learning_rate_decay  # noqa: F401,E402  (reference fluid.learning_rate_decay spelling)
from .core.executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .core.program import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    pipeline_stage_guard,
    program_guard,
)
from .core import unique_name  # noqa: F401
from . import executor, framework  # noqa: F401  (fluid.framework idioms)
from .data_feeder import DataFeeder  # noqa: F401
from .distributed import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from . import pipeline  # noqa: F401  (pipeline parallelism plane)
from . import checkpoint  # noqa: F401  (sharded checkpoints + elastic resize)
from .contrib import (  # noqa: F401
    BeginEpochEvent,
    BeginStepEvent,
    CheckpointConfig,
    EndEpochEvent,
    EndStepEvent,
    Inferencer,
    Trainer,
)
from .transpiler import InferenceTranspiler, memory_optimize, release_memory  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .parallel import (  # noqa: F401
    BuildStrategy,
    ExecutionStrategy,
    ParallelExecutor,
)


from . import platform  # noqa: F401
from .platform import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    DeviceContext,
    DeviceContextPool,
    TPUPlace,
    device_count,
    tpu_places,
)
from .core.flags import get_flags, set_flags  # noqa: F401

__version__ = "0.1.0"
