"""Weak-scaling efficiency harness (BASELINE's "8→64 chip scaling eff").

Reference precedent: ``benchmark/fluid/fluid_benchmark.py:137`` runs the
same model over 1..N GPUs and reports throughput ratios.  On this repo's
single-core CI host, wall-clock over a *virtual* 8-device CPU mesh would
measure core oversubscription (8 device programs time-sliced onto one
core), not sharding quality — so the harness measures what actually
predicts pod-scale behavior: the PER-DEVICE compiled cost of the SPMD
program.

Weak scaling holds per-device batch fixed while growing the mesh.  With
perfect sharding the per-device HLO does the same flops/bytes at any mesh
size (plus collectives); an accidentally-replicated tensor multiplies
per-device work by the mesh size and craters the ratio — exactly the
regression class that is invisible until a real pod run.

Reported:
- ``eff_flops``  = flops/device(dp=1) ÷ flops/device(dp=N)
- ``eff_bytes``  = bytes/device(dp=1) ÷ bytes/device(dp=N)
- ``allreduce_mb`` = per-step all-reduce traffic in the dp=N program
  (should be ≈ 2 × gradient bytes for kAllReduce, independent of batch)
"""
from __future__ import annotations

import re
from typing import Dict

import numpy as np


def _cost(compiled) -> Dict[str, float]:
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed", 0.0))}


def _allreduce_bytes(compiled) -> float:
    """Sum output bytes of all-reduce DEFINITIONS (line-anchored on the
    instruction name, so consumer lines mentioning an %all-reduce operand
    are not double-counted; tuple-shaped combined all-reduces count every
    element)."""
    total = 0.0
    dt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4}
    for line in compiled.as_text().splitlines():
        m = re.match(r"\s*%(all-reduce|reduce-scatter)[\w.\-]* = (.*?) ?(all-reduce|reduce-scatter)\(",
                     line)
        if not m:
            continue
        for dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", m.group(2)):
            if dt not in dt_bytes:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * dt_bytes[dt]
    return total


def scaling_report(per_device_batch: int = 4, big_dp: int = 8,
                   run_step: bool = True) -> Dict[str, float]:
    """Compare per-device compiled cost of the Transformer train step on a
    1-device vs ``big_dp``-device mesh at fixed per-device batch."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..core import unique_name
    from ..core.executor import Scope, scope_guard, Executor, _as_device_array
    from ..core.lowering import analyze_block, build_block_fn
    from ..core.program import Program, program_guard
    from ..models import transformer
    from .parallel_executor import make_mesh

    T = 32
    results = {}
    for dp in (1, big_dp):
        B = per_device_batch * dp
        prog, startup = Program(), Program()
        prog.random_seed = 5
        startup.random_seed = 5
        with program_guard(prog, startup), unique_name.guard():
            feeds, loss, _ = transformer.build(
                src_vocab=1000, tgt_vocab=1000, max_len=T, d_model=128,
                n_head=4, d_ffn=512, n_layer=2, dropout=0.1,
                attention_impl="base")
        mesh = make_mesh({"dp": dp}, jax.devices()[:dp])
        rng = np.random.RandomState(0)
        feed = {"src_ids": rng.randint(0, 1000, (B, T)).astype("int64"),
                "tgt_ids": rng.randint(0, 1000, (B, T)).astype("int64"),
                "lbl_ids": rng.randint(0, 1000, (B, T)).astype("int64"),
                "src_mask": np.ones((B, T), "float32"),
                "tgt_mask": np.ones((B, T), "float32")}
        scope, exe = Scope(), Executor()
        with scope_guard(scope):
            exe.run(startup)
            ordered = sorted(feed)
            plan = analyze_block(prog, 0, ordered, [loss.name])
            fn = build_block_fn(prog, plan, mesh=mesh)
            block = prog.global_block
            dp_shard = NamedSharding(mesh, P("dp"))
            repl = NamedSharding(mesh, P())
            feeds_d = [jax.device_put(
                _as_device_array(feed[n], block.var_or_none(n)), dp_shard)
                for n in ordered]
            donated = [jax.device_put(np.asarray(scope.find_var(n)), repl)
                       for n in plan.donated_reads]
            const = [jax.device_put(np.asarray(scope.find_var(n)), repl)
                     for n in plan.const_reads]
            rng_key = jax.random.PRNGKey(0)
            compiled = jax.jit(fn).lower(
                feeds_d, donated, const, rng_key).compile()
            results[dp] = _cost(compiled)
            if dp == big_dp:
                results["allreduce_mb"] = _allreduce_bytes(compiled) / 1e6
            if run_step:
                fetch, _, _ = compiled(feeds_d, donated, const, rng_key)
                loss_val = float(np.asarray(fetch[0]))
                assert np.isfinite(loss_val), loss_val

    eff_flops = results[1]["flops"] / max(results[big_dp]["flops"], 1.0)
    eff_bytes = results[1]["bytes"] / max(results[big_dp]["bytes"], 1.0)
    return {"devices": big_dp,
            "per_device_batch": per_device_batch,
            "eff_flops": round(eff_flops, 3),
            "eff_bytes": round(eff_bytes, 3),
            "allreduce_mb": round(results.get("allreduce_mb", 0.0), 2)}
