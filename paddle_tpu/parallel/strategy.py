"""Build/execution strategies for parallel execution.

Reference: ``paddle/fluid/framework/details/build_strategy.h:55`` (ReduceStrategy,
GradientScaleStrategy) and ``execution_strategy.h:21``.  On TPU these select
*sharding policies* for the one jitted program instead of assembling an SSA
graph of collective op-handles:

- ``kAllReduce``  → parameters + optimizer state replicated; XLA/GSPMD emits
  the gradient all-reduce over ICI (the NCCLAllReduce analogue).
- ``kReduce``     → optimizer state (and accumulator math) sharded over the
  data axis; GSPMD emits reduce-scatter + all-gather — the reference's
  "reduce → update on one device → broadcast" becomes ZeRO-style sharding.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class ReduceStrategy:
    kAllReduce = 0
    kReduce = 1


class GradientScaleStrategy:
    kCoeffNumDevice = 0
    kOne = 1
    kCustomized = 2


@dataclass
class BuildStrategy:
    reduce_strategy: int = ReduceStrategy.kAllReduce
    gradient_scale_strategy: int = GradientScaleStrategy.kCoeffNumDevice
    debug_graphviz_path: str = ""
    # TPU extensions beyond the 2018 reference: named mesh axes and
    # parameter sharding rules (regex -> PartitionSpec dims) enabling
    # tensor/model parallelism on the same program.
    mesh_shape: Optional[Dict[str, int]] = None          # e.g. {"dp": 8, "mp": 1}
    sharding_rules: List[Tuple[str, tuple]] = field(default_factory=list)
    # e.g. [(r".*ffn1\.w.*", (None, "mp")), (r".*embed.*", ("mp", None))]


@dataclass
class ExecutionStrategy:
    num_threads: int = 0
    use_cuda: bool = True  # parity field; device choice belongs to JAX
    allow_op_delay: bool = False
    num_iteration_per_drop_scope: int = 1
