from .parallel_executor import ParallelExecutor, make_mesh  # noqa: F401
from .multihost import init_from_env  # noqa: F401
from .strategy import (  # noqa: F401
    BuildStrategy,
    ExecutionStrategy,
    GradientScaleStrategy,
    ReduceStrategy,
)
